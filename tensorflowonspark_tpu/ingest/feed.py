"""``IngestFeed`` — the DIRECT-mode twin of ``feeding.DataFeed``.

In ``InputMode.DIRECT`` the driver's partition ledger streams shard *paths*
(tens of bytes each) instead of rows; this feed sits between the node's
``FeedQueues`` and the user ``map_fun``, turning those paths into decoded
record batches through the :class:`~tensorflowonspark_tpu.ingest.readers.
ReaderPipeline` (parallel interleave + decode + prefetch):

    input queue          claimer thread        reader pipeline     map_fun
    paths + markers  ->  claims shards,    ->  N readers, CRC, ->  next_batch
    (from the ledger)    tracks partitions     decode, prefetch

Same consumption contract as ``DataFeed`` — and that contract is what makes
the whole elastic machinery carry over to direct reads unchanged:

- the node's **consumption watermark** (``FeedQueues.note_partition_consumed``)
  advances only after every record of a ledger partition has been *returned
  to the map_fun* — never merely read — so a death re-delivers any
  partition whose records might not have been processed (duplicates
  allowed, loss never);
- keyed ``EndPartition`` markers dedupe an at-least-once re-feed of the
  same partition (its shards are re-READ — duplicates at record level are
  the at-least-once contract — but the watermark counts it once);
- ``EndOfFeed`` / the node stop signal end the feed; ``terminate()``
  fast-drains pending paths so driver feed calls unblock.

The watermark bookkeeping rides the pipeline's ``ShardDone`` tokens: the
chunk queue is FIFO, so popping a shard's token proves all its records left
the queue; a partition reports consumed once every one of its shards' tokens
has popped AND the batch carrying its last records has been handed back.
"""

from __future__ import annotations

import queue
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
from typing import Any, Iterable

from time import monotonic as _monotonic
from time import sleep as _sleep

from tensorflowonspark_tpu import faultinject, telemetry
from tensorflowonspark_tpu.data import DecodedChunk
from tensorflowonspark_tpu.feeding import FeedQueues, batch_to_columns
from tensorflowonspark_tpu.ingest.readers import ReaderPipeline, ShardDone
from tensorflowonspark_tpu.ingest.shards import ShardSpan
from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition, Marker, ResultChunk
from tensorflowonspark_tpu.telemetry import trace as ttrace


class _PartitionJob:
    """Watermark bookkeeping for one ledger partition of shard paths."""

    __slots__ = ("key", "n_shards", "n_done", "closed", "trace", "t0")

    def __init__(self):
        self.key = None
        self.n_shards = 0
        self.n_done = 0
        self.closed = False
        # sampled driver partition's trace ctx (rides the EndPartition) +
        # first-claim time: the ingest partition-consume span's anchors
        self.trace = None
        self.t0 = _monotonic()


class IngestFeed:
    """User-facing DIRECT-mode feed: ``next_batch``/``should_stop``/
    ``batch_results``/``terminate``, drop-in for ``DataFeed`` inside a
    map_fun.

    Deltas from ``DataFeed`` (all deliberate): batches are record payloads
    (zero-copy ``memoryview`` slices of the shard buffer by default — see
    the decode contract below — or whatever ``decode`` returns), and SHARD
    seams inside a ledger partition never truncate batches — shards
    interleave freely.  A completed *ledger partition* does close the
    running batch (partial, like DataFeed's EndPartition): the records
    must reach the map_fun before the partition may be reported consumed,
    and holding them while blocking for more data would freeze the
    watermark the driver's elastic tail drain polls.

    **Zero-copy decode contract** (``TOS_INGEST_ZEROCOPY``, default on):
    records from plain shards are ``memoryview`` slices — no copy between
    the disk read and the map_fun.  A view is *valid until its batch is
    released*: a batch retires when the map_fun comes back for the next
    one, so the batch in hand is always safe — finish with it before
    calling ``next_batch`` again.  Retaining views longer pins whole
    shard buffers in memory — copy (``bytes(view)``) anything you keep.
    ``TOS_INGEST_ZEROCOPY=0`` restores plain ``bytes`` records;
    ``=debug`` keeps zero-copy but *releases* each batch's views on
    retirement, so a retained view raises ``ValueError`` at first touch
    instead of silently leaking.  Gzip shards always deliver ``bytes``.

    **Columnar mode** (``schema=``, a ``dfutil.Schema``): batches are
    ``{column: values}`` dicts sliced zero-copy out of the readers'
    ``dfutil.ColumnChunk``s — fixed-width numeric columns as ``[n]`` /
    ``[n, k]`` ndarray views, ragged columns as ``(values, counts)``
    pairs.  Batches never span chunks (a batch may come back short at a
    chunk boundary — same "up to batch_size" contract as everywhere
    else); ``input_mapping`` renames columns instead of reshaping rows.
    """

    def __init__(
        self,
        queues: FeedQueues,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict[str, str] | None = None,
        stop_event: threading.Event | None = None,
        poll_interval: float = 0.25,
        readers: int | None = None,
        decode=None,
        chunk_records: int = 256,
        verify: bool = True,
        prefetch: int | None = None,
        autotune: bool | None = None,
        zerocopy=None,
        schema=None,
        binary_features=None,
        cache=None,
    ):
        self.queues = queues
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.input_mapping = input_mapping
        self.stop_event = stop_event
        self.poll_interval = poll_interval
        self.done_feeding = False
        self._drained = False
        self._leftover: list = []
        self._claim_error: BaseException | None = None
        self._terminated = threading.Event()
        # the pipeline's own stop flag: terminate()/stop abandon in-flight
        # reads without touching the node-wide stop_event
        self._abandon = threading.Event()
        self.pipeline = ReaderPipeline(
            readers=readers, autotune=autotune, prefetch=prefetch,
            chunk_records=chunk_records, decode=decode, verify=verify,
            stop_event=self._abandon, zerocopy=zerocopy, schema=schema,
            binary_features=binary_features, cache=cache)
        # debug zero-copy: views handed out in the LAST returned batch;
        # released (-> late access raises ValueError) when that batch
        # retires at the next next_batch call
        self._debug_release = self.pipeline.zerocopy == "debug"
        self._prev_views: list = []
        # columnar mode: the partially-served ColumnChunk + its row offset
        self._colchunk = None
        self._coloff = 0
        # rolling feed-queue occupancy (the autoscaling signal
        # cluster.stats() serves per node, same gauge as DataFeed): in
        # DIRECT mode the reader pipeline's prefetch queue IS the feed queue
        self._occupancy = telemetry.gauge("feed.queue_depth")
        # partitions fully read AND fully handed to the map_fun, awaiting
        # the safe moment to report (see _report_ready_keys)
        self._jobs_lock = tos_named_lock("feed._jobs_lock")
        self._ready_keys: list = []
        self._claimer = threading.Thread(target=self._claim_loop, daemon=True,
                                         name="ingest-claimer")
        self._claimer.start()

    # -- claimer thread: input queue -> reader work items --------------------

    def _claim_loop(self) -> None:
        q = self.queues.get_queue(self.qname_in)
        open_job: _PartitionJob | None = None
        try:
            while not self._terminated.is_set():
                if self.stop_event is not None and self.stop_event.is_set():
                    # node-wide stop: abandon in-flight reads too — the
                    # readers must not keep churning through queued shards
                    # for a consumer that is winding down
                    self._abandon.set()
                    return
                try:
                    item = q.get(timeout=self.poll_interval)
                except queue.Empty:
                    continue
                if isinstance(item, EndPartition):
                    job = open_job if open_job is not None else _PartitionJob()
                    open_job = None
                    with self._jobs_lock:
                        job.key = getattr(item, "key", None)
                        job.trace = getattr(item, "trace", None)
                        job.closed = True
                        if job.n_done >= job.n_shards:
                            # every shard already drained through the
                            # consumer (or the partition was empty): ready —
                            # the consumer reports it at its next safe point
                            self._ready_keys.append(job)
                    continue
                if isinstance(item, EndOfFeed):
                    return
                if isinstance(item, Marker):
                    continue
                if isinstance(item, DecodedChunk):
                    # Disaggregated ingest tier: a data-service worker
                    # already decoded this chunk — inject it straight into
                    # the pipeline's decoded-chunk queue (this feed is a
                    # pure consumer).  Each forwarded chunk counts as one
                    # "shard" of its ledger partition, so the watermark
                    # machinery below is byte-for-byte the node-local one.
                    if open_job is None:
                        open_job = _PartitionJob()
                    with self._jobs_lock:
                        open_job.n_shards += 1
                    self.pipeline.inject(item.payload, open_job,
                                         source=item.source)
                    continue
                if not isinstance(item, (str, ShardSpan)):
                    raise TypeError(
                        f"DIRECT-mode feed expects shard PATHS (or ShardSpan "
                        f"sub-shard items) on queue "
                        f"{self.qname_in!r}, got {type(item).__name__}: "
                        "feed this cluster with cluster.train(<path_or_glob>) "
                        "(InputMode.STREAMING is the mode that streams rows)")
                if open_job is None:
                    open_job = _PartitionJob()
                with self._jobs_lock:
                    open_job.n_shards += 1
                self.pipeline.submit(item, open_job)
        except BaseException as e:  # noqa: BLE001 - re-raised in next_batch
            self._claim_error = e
        finally:
            self.pipeline.close()

    # -- consumer side (the map_fun) -----------------------------------------

    def _has_ready_keys(self) -> bool:
        with self._jobs_lock:
            return bool(self._ready_keys)

    def _report_ready_keys(self) -> None:
        """Report partitions whose records have all been handed back.  Only
        called when the consumer holds NO undelivered records (top of
        next_batch, or mid-poll with an empty batch in hand) — the watermark
        must lag the map_fun, never lead it."""
        with self._jobs_lock:
            if not self._ready_keys:
                return
            jobs, self._ready_keys = self._ready_keys, []
        for job in jobs:
            self._report_job(job)

    def _report_job(self, job: _PartitionJob) -> None:
        self.queues.note_partition_consumed(self.qname_in, job.key)
        if job.trace is not None:
            # ingest partition-consume span: first shard claimed -> every
            # record handed to the map_fun (under the driver's sampled
            # train.partition span — the DIRECT-mode end of the trace)
            now = _monotonic()
            ttrace.record_child("feed.partition_consume", job.trace,
                                job.t0, now - job.t0,
                                {"shards": job.n_shards})

    def _on_shard_done(self, token: ShardDone, batch_empty: bool) -> None:
        job = token.tag
        if job is None:
            return
        report = False
        with self._jobs_lock:
            job.n_done += 1
            if job.closed and job.n_done >= job.n_shards:
                if batch_empty:
                    # FIFO: every record of this partition was popped before
                    # its last ShardDone, and with nothing in hand they were
                    # all in batches ALREADY returned — safe to report now
                    # (must not wait for a next_batch call that may never
                    # come: the elastic tail drain polls this watermark)
                    report = True
                else:
                    self._ready_keys.append(job)
        if report:
            self._report_job(job)

    def next_batch(self, batch_size: int) -> list | dict:
        """Pop up to ``batch_size`` decoded records; the batch goes partial
        at end-of-feed / stop / a completed ledger partition (shard seams
        inside a partition never truncate it) / a columnar chunk boundary.
        Calling this RELEASES the previous batch (see the zero-copy decode
        contract in the class docstring)."""
        # Self-fence (ISSUE 13): parked = coordinator unreachable past
        # TOS_COORDINATOR_GRACE_SECS — stop taking new ledger work until
        # the heartbeat loop re-admits us or gives up (same contract as
        # the streaming DataFeed; checked once per batch).
        while self.queues.get("state") == "parked":
            if self.stop_event is not None and self.stop_event.is_set():
                break
            _sleep(self.poll_interval)
        if self._prev_views:
            # debug zero-copy: the previous batch retires NOW — releasing
            # its views makes any retained one fail loudly at first touch
            for v in self._prev_views:
                v.release()
            self._prev_views = []
        self._report_ready_keys()  # the previous batch has been handed over
        batch: list = []
        while len(batch) < batch_size:
            if self._colchunk is not None:
                return self._columnar_batch(batch_size)
            if self._leftover:
                take = batch_size - len(batch)
                if not batch and take >= len(self._leftover):
                    # whole chunk fits an empty batch: adopt the list
                    # instead of copying it element-wise (the hot shape —
                    # batch_size >= chunk_records)
                    batch = self._leftover
                    self._leftover = []
                    continue
                batch.extend(self._leftover[:take])
                del self._leftover[:take]
                continue
            if self._claim_error is not None:
                # checked BEFORE the drained branch: a dying claimer closes
                # the pipeline, so the drain sentinel races this error into
                # the same poll window — ending the feed "cleanly" here
                # would swallow the failure and strand the driver's feed
                raise RuntimeError(
                    f"ingest claim loop failed: {self._claim_error}"
                ) from self._claim_error
            if self._drained:
                if batch:
                    # hand the final records back WITHOUT flagging done: the
                    # map_fun's next call (the proof this batch was
                    # processed) flushes the last partition's consumption
                    # report, then sees done — mirroring DataFeed, where
                    # EndOfFeed always pops on a later call than the batch
                    # that closed the final partition
                    break
                self.done_feeding = True
                break
            if not batch:
                # nothing undelivered in hand: partitions the claimer closed
                # while we were blocked here are safe to report immediately
                self._report_ready_keys()
            elif self._has_ready_keys():
                # a LEDGER partition finished behind the records in hand:
                # close the batch now (DataFeed's partition-end partial
                # batch, at ledger granularity) — blocking here to top the
                # batch up could hold these records indefinitely between
                # feeds, freezing the consumption watermark the driver's
                # elastic tail drain waits on
                break
            if self.stop_event is not None and self.stop_event.is_set():
                self.pipeline.stop()
                self.done_feeding = True
                break
            try:
                item = self.pipeline.get(timeout=self.poll_interval)
            except queue.Empty:
                # same starvation counter as the streaming DataFeed: an
                # empty poll with the consumer hungry (decode behind)
                telemetry.counter("feed.starved_polls").inc()
                continue
            if item is None:  # pipeline fully drained (EndOfFeed reached)
                self._drained = True
                continue
            if isinstance(item, ShardDone):
                self._on_shard_done(item, batch_empty=not batch)
                continue
            if hasattr(item, "slice") and hasattr(item, "counts"):
                # a dfutil.ColumnChunk (schema mode): served by slicing at
                # the loop top — record chunks never mix with these (the
                # schema drives EVERY shard through the columnar decoder)
                self._colchunk, self._coloff = item, 0
                continue
            self._leftover = item  # one decoded chunk (a list)
        if batch:
            self._occupancy.set(self.pipeline.depth())
            telemetry.counter("feed.batches").inc()
            telemetry.counter("feed.rows_consumed").inc(len(batch))
            # same chaos clock as DataFeed: `kill:after_batches=N` fires on
            # consumed batches, so kill-mid-shard tests run in DIRECT mode
            faultinject.batch_consumed()
            if self._debug_release:
                self._prev_views = [r for r in batch
                                    if type(r) is memoryview]
        if self.input_mapping:
            return batch_to_columns(batch, self.input_mapping)
        return batch

    def _columnar_batch(self, batch_size: int) -> dict:
        """Serve up to ``batch_size`` records off the current ColumnChunk
        as zero-copy column views; batches never span chunks (numpy views
        cannot cross two buffers without a copy — a short batch at a chunk
        boundary is the documented trade)."""
        chunk, off = self._colchunk, self._coloff
        take = min(batch_size, len(chunk) - off)
        out = chunk.slice(off, off + take)
        off += take
        if off >= len(chunk):
            self._colchunk, self._coloff = None, 0
        else:
            self._coloff = off
        self._occupancy.set(self.pipeline.depth())
        telemetry.counter("feed.batches").inc()
        telemetry.counter("feed.rows_consumed").inc(take)
        faultinject.batch_consumed()
        if self.input_mapping:
            # same {column -> tensor name} contract as batch_to_columns,
            # minus the per-row reshaping the columns never needed
            return {tname: out[cname]
                    for cname, tname in self.input_mapping.items()}
        return out

    def next_chunk(self):
        """Pop the next WHOLE decoded chunk (a record list, or a
        ``dfutil.ColumnChunk`` in schema mode), or ``None`` at end of feed.

        The data-service worker's consumption surface (``ingest/service.py``):
        a forwarder wants pipeline-sized units to ship, not re-batched
        records.  Same watermark contract as ``next_batch`` — calling again
        is the proof the previous chunk was fully handed over (for the
        service: forwarded AND acked by a trainer), so the partition-
        consumed report the driver's ledger drains on only ever lags the
        actual delivery.  Mixing ``next_chunk`` and ``next_batch`` on one
        feed is not supported (the batch carry-over state is not shared)."""
        while self.queues.get("state") == "parked":
            if self.stop_event is not None and self.stop_event.is_set():
                break
            _sleep(self.poll_interval)
        self._report_ready_keys()  # the previous chunk has been handed over
        while True:
            if self._claim_error is not None:
                raise RuntimeError(
                    f"ingest claim loop failed: {self._claim_error}"
                ) from self._claim_error
            if self._drained:
                self.done_feeding = True
                return None
            if self.stop_event is not None and self.stop_event.is_set():
                self.pipeline.stop()
                self.done_feeding = True
                return None
            self._report_ready_keys()
            try:
                item = self.pipeline.get(timeout=self.poll_interval)
            except queue.Empty:
                telemetry.counter("feed.starved_polls").inc()
                continue
            if item is None:
                self._drained = True
                continue
            if isinstance(item, ShardDone):
                # nothing undelivered in hand by construction (whole chunks
                # only): a closed partition is safe to report immediately
                self._on_shard_done(item, batch_empty=True)
                continue
            self._occupancy.set(self.pipeline.depth())
            # service-side counters, DISTINCT from the trainer feed's
            # feed.rows_consumed: the worker claims these rows and the
            # trainer consumes the very same ones — double-counting one
            # name would double the run report's cluster aggregate
            telemetry.counter("ingest.chunks_claimed").inc()
            telemetry.counter("ingest.rows_claimed").inc(len(item))
            faultinject.batch_consumed()
            return item

    # -- producing results ---------------------------------------------------

    def batch_results(self, results: Iterable[Any], chunk: bool = False) -> None:
        """Emit results to the output queue (parity with ``DataFeed``).

        Zero-copy record views are materialized to ``bytes`` here: a
        result outlives its batch by definition (the decode contract says
        copy what you keep), and views queued raw would pin shard buffers
        AND be unpicklable on the collect wire."""
        from tensorflowonspark_tpu.data import materialize_views

        results = materialize_views(list(results))
        q = self.queues.get_queue(self.qname_out)
        if chunk:
            q.put(ResultChunk(results))
            return
        for r in results:
            q.put(r)

    # -- lifecycle -----------------------------------------------------------

    def should_stop(self) -> bool:
        return self.done_feeding

    def terminate(self) -> None:
        """Stop consuming: abandon in-flight reads, mark terminating, and
        fast-drain pending paths so upstream feed calls unblock."""
        self.done_feeding = True
        self._terminated.set()
        self._abandon.set()
        self.queues.set("state", "terminating")
        q = self.queues.get_queue(self.qname_in)
        while True:
            try:
                q.get(block=True, timeout=0.05)
            except queue.Empty:
                return
