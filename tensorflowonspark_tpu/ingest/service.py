"""Standalone data-service ingest workers — the disaggregated ingest tier.

BENCH_r12 measured the node-local data plane entitlement-capped by per-box
decode CPU: readers live inside each training node, so columnar decode
competes with the training step and reader parallelism can never exceed the
trainer count.  Following the tf.data service design (PAPERS.md) this module
promotes the readers to an independently scaled worker pool:

    driver ledger          ingest workers (role="ingest")         trainers
    shard paths/spans  ->  claim + CRC + columnar decode     ->   IngestFeed,
    (at-least-once,        (ReaderPipeline on OWN cores,          pure consumer
    incarnation-fenced)    cross-epoch ChunkCache)  --chunk_fwd-->

- **Workers are ordinary cluster nodes** whose assigned role is ``ingest``
  (``cluster.run(ingest_workers=N)``): the driver's partition ledger feeds
  them shard paths exactly as it would feed a DIRECT-mode trainer, so
  at-least-once re-feed, the consumption watermark, incarnation fencing,
  and supervised elastic restarts carry over to worker deaths UNCHANGED —
  a SIGKILLed worker's unacked partitions re-feed to its peers or its
  supervised replacement, and no trainer restarts.
- **Decoded chunks stream to trainers** over the existing zero-copy v2/v3
  wire (``dataserver`` op ``chunk_fwd``; ``data.DecodedChunk``): a
  ``ColumnChunk``'s contiguous column buffers travel out-of-band, and the
  trainer's ``IngestFeed`` injects payloads straight into its prefetch
  queue — decode parallelism becomes a fleet knob (``TOS_INGEST_WORKERS``,
  ``cluster.resize_ingest``) instead of a per-trainer constant.
- **Cross-epoch chunk cache** (:class:`ChunkCache`,
  ``TOS_INGEST_CACHE_BYTES``): repeated-epoch reads of the same work item
  + schema serve materialized chunks from memory instead of re-running the
  CRC scan + decode; bounded LRU by payload bytes, ``0`` disables, and the
  schema fingerprint in the key means eviction can never serve a stale
  schema.
- **Global shuffle** (``TOS_INGEST_SHUFFLE``, default on): each worker
  deals its decoded chunks round-robin across ALL trainers (offset by its
  own task index), so a trainer's stream interleaves every shard the pool
  claims — combined with the ledger's seeded between-epoch partition
  shuffle this is the tf.data-service "global shuffle" property.  ``0``
  pins each worker to one trainer (locality mode).

The worker's consumption watermark advances only after a trainer ACKED the
partition's last chunk (``IngestFeed.next_chunk`` hands the next chunk out
only after the previous one was forwarded), so the driver's elastic tail
drain — and therefore ``train()`` returning — proves every record is
buffered trainer-side or better.  Duplicates are allowed (at-least-once),
loss never.
"""

from __future__ import annotations

import collections
import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.data import DecodedChunk, chunk_nbytes
from tensorflowonspark_tpu.ingest.feed import IngestFeed
from tensorflowonspark_tpu.ingest.shards import work_item_key
from tensorflowonspark_tpu.utils.envtune import env_bool as _env_bool
from tensorflowonspark_tpu.utils.envtune import env_int as _env_int

logger = logging.getLogger(__name__)


def cache_bytes_default() -> int:
    """Effective ``TOS_INGEST_CACHE_BYTES`` (0 = cache disabled)."""
    return _env_int("TOS_INGEST_CACHE_BYTES", 0, minimum=0)


def shuffle_default() -> bool:
    """Effective ``TOS_INGEST_SHUFFLE`` (default on: global shuffle)."""
    return _env_bool("TOS_INGEST_SHUFFLE", True)


def schema_fingerprint(schema) -> str | None:
    """Stable identity of a decode schema for cache keying.  ``to_json``
    is the schema's own durable serialization, so two schemas that decode
    identically fingerprint identically across processes and epochs —
    and ANY schema change (column added, width redeclared) changes the
    key, which is what makes a stale-schema cache hit impossible."""
    if schema is None:
        return None
    return schema.to_json()


class ChunkCache:
    """Bounded LRU cache of decoded chunks, keyed by (work item, schema).

    The cross-epoch half of the ingest tier: epoch 2+ reads of a span the
    pool already decoded are served from memory (no IO, no CRC, no parse).
    Values are MATERIALIZED chunk lists (owned buffers — the reader tees
    copies in, see ``ReaderPipeline._emit``), shared read-only between the
    cache and every consumer; the accounting unit is payload bytes
    (``data.chunk_nbytes``), bounded by ``max_bytes`` with LRU eviction.
    ``max_bytes=0`` disables the cache entirely (every get misses, puts
    are dropped) — the ``TOS_INGEST_CACHE_BYTES=0`` contract.

    Thread-safe: one worker's reader pool runs N threads through it.
    """

    def __init__(self, max_bytes: int | None = None):
        self.max_bytes = max(0, int(max_bytes if max_bytes is not None
                                    else cache_bytes_default()))
        self._lock = tos_named_lock("service.cache._lock")
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._bytes = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def key_for(self, item, schema=None, binary_features=None) -> tuple:
        # binary_features is part of the decode contract (bytes-vs-str
        # column values), so it must be part of the key: a hit across a
        # different setting would hand one pipeline the other's types
        bf = tuple(sorted(binary_features)) if binary_features else None
        return (work_item_key(item), schema_fingerprint(schema), bf)

    def get(self, key) -> list | None:
        if not self.enabled:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            telemetry.counter("ingest.cache_misses").inc()
            return None
        telemetry.counter("ingest.cache_hits").inc()
        return entry[0]

    def put(self, key, chunks: list, nbytes: int | None = None) -> bool:
        """Insert one work item's materialized chunks; returns whether the
        entry was admitted (an item bigger than the whole budget is not —
        caching it would just evict everything for a single-use entry).
        ``nbytes`` skips the size walk when the producer already counted
        (the reader tee tracks a running total)."""
        if not self.enabled:
            return False
        if nbytes is None:
            nbytes = sum(chunk_nbytes(c) for c in chunks)
        if nbytes > self.max_bytes:
            telemetry.counter("ingest.cache_oversize_skips").inc()
            return False
        evictions = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, (_, ev_bytes) = self._entries.popitem(last=False)
                self._bytes -= ev_bytes
                evictions += 1
            self._entries[key] = (chunks, nbytes)
            self._bytes += nbytes
            total = self._bytes
        telemetry.counter("ingest.cache_inserts").inc()
        if evictions:
            telemetry.counter("ingest.cache_evictions").inc(evictions)
        telemetry.gauge("ingest.cache_bytes").set(total)
        return True

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "max_bytes": self.max_bytes}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
        telemetry.gauge("ingest.cache_bytes").set(0)


class TrainerForwarder:
    """Deals decoded chunks from one ingest worker across the trainer fleet.

    ``endpoints`` is ``[(executor_id, host, data_port), ...]`` of every
    trainer (the worker reads them off ``ctx.cluster_info``).  Transport is
    the ordinary :class:`~tensorflowonspark_tpu.dataserver.DataClient`
    (authkey handshake, v2/v3 wire, ring upgrade where same-host) — the
    dial-discipline transport home; this class never opens a raw socket.

    Target selection: ``shuffle`` on (``TOS_INGEST_SHUFFLE``, the default)
    rotates round-robin per chunk starting at ``rr_offset`` (the worker's
    task index, so a fleet of workers decorrelates), giving every trainer
    an interleave of every shard the pool claims; off pins this worker to
    ``trainers[rr_offset % T]`` (locality mode).

    Failure handling is at-least-once shaped: a failed send (severed
    socket, trainer mid-restart) drops the client, redials, and retries —
    first the same trainer, then the rest of the rotation — under a
    ``stall_timeout`` budget; only a fleet-wide stall raises.  A trainer
    answering ``terminating`` is retired from the rotation; when every
    trainer has terminated, :meth:`forward` returns False (the consumer
    side of the feed is over).
    """

    def __init__(self, endpoints, authkey: bytes, *, qname: str = "input",
                 shuffle: bool | None = None, rr_offset: int = 0,
                 stop_event: threading.Event | None = None,
                 stall_timeout: float = 60.0, connect_timeout: float = 10.0):
        if not endpoints:
            raise ValueError("ingest forwarder needs at least one trainer")
        self.endpoints = {int(eid): (host, int(port))
                          for eid, host, port in endpoints}
        self.authkey = authkey
        self.qname = qname
        self.shuffle = shuffle if shuffle is not None else shuffle_default()
        self.stall_timeout = stall_timeout
        self.connect_timeout = connect_timeout
        self.stop_event = stop_event
        self._order = sorted(self.endpoints)
        self._pos = rr_offset % len(self._order)
        self._clients: dict[int, object] = {}
        self._terminated: set[int] = set()

    def _client(self, eid: int):
        client = self._clients.get(eid)
        if client is None:
            from tensorflowonspark_tpu.dataserver import DataClient

            host, port = self.endpoints[eid]
            client = DataClient(host, port, self.authkey,
                                connect_timeout=self.connect_timeout,
                                connect_attempts=1)
            self._clients[eid] = client
        return client

    def _drop(self, eid: int) -> None:
        stale = self._clients.pop(eid, None)
        if stale is not None:
            try:
                stale.close()
            except Exception:  # noqa: BLE001  # toslint: allow-silent(the socket already failed; a fresh dial follows)
                pass

    def _rotation(self) -> list[int]:
        live = [e for e in self._order if e not in self._terminated]
        if not live:
            return []
        start = self._pos % len(live)
        if self.shuffle:
            self._pos += 1  # next chunk starts one trainer later
        return live[start:] + live[:start]

    def forward(self, chunk: DecodedChunk) -> bool:
        """Deliver one chunk to some live trainer (retrying/re-routing under
        the stall budget).  True = delivered and acked; False = every
        trainer is terminating, stop producing.  Raises ``RuntimeError``
        when no trainer accepted within ``stall_timeout`` — the worker's
        map_fun error path then owns it (supervised restart / job error),
        with the partition's re-feed covering the undelivered records."""
        deadline = time.monotonic() + self.stall_timeout
        while True:
            rotation = self._rotation()
            if not rotation:
                return False  # every trainer terminated: feed is over
            for eid in rotation:
                if self.stop_event is not None and self.stop_event.is_set():
                    return False
                try:
                    state = self._client(eid).forward_chunks([chunk],
                                                             self.qname)
                except Exception:  # noqa: BLE001 - rerouted below
                    # severed stream / trainer mid-restart: poison this
                    # client and move on; the rotation (and the outer retry
                    # loop) owns delivery
                    telemetry.counter("ingest.forward_errors").inc()
                    logger.warning("chunk forward to trainer %d failed; "
                                   "re-routing", eid, exc_info=True)
                    self._drop(eid)
                    continue
                if state == "terminating":
                    self._terminated.add(eid)
                    self._drop(eid)
                    continue
                telemetry.counter("ingest.chunks_forwarded").inc()
                telemetry.counter("ingest.rows_forwarded").inc(chunk.nrows)
                telemetry.counter("ingest.bytes_forwarded").inc(chunk.nbytes)
                return True
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no trainer accepted a decoded chunk within "
                    f"{self.stall_timeout}s ({len(self._order)} endpoint(s), "
                    f"{len(self._terminated)} terminated)")
            time.sleep(0.2)

    def close(self) -> None:
        for eid in list(self._clients):
            self._drop(eid)


class IngestService:
    """One data-service worker: claim -> decode (cached) -> forward.

    Wraps an :class:`~tensorflowonspark_tpu.ingest.feed.IngestFeed` over
    the worker's own ``FeedQueues`` (the driver's ledger feeds shard
    paths/spans into them through the worker's ``DataServer``, so every
    elastic/at-least-once property of a DIRECT-mode trainer applies to the
    worker verbatim) and a :class:`TrainerForwarder` for the fan-out.

    ``next_chunk`` -> ``forward`` -> ``next_chunk`` is the watermark
    contract: coming back for the next chunk is the proof the previous one
    was ACKED into a trainer's queue, so the consumption report the
    driver's tail drain polls only ever lags real delivery.
    """

    def __init__(self, queues, trainers, authkey: bytes, *,
                 stop_event: threading.Event | None = None,
                 schema=None, binary_features=None, chunk_records: int = 256,
                 readers: int | None = None, prefetch: int | None = None,
                 autotune: bool | None = None, verify: bool = True,
                 cache_bytes: int | None = None, shuffle: bool | None = None,
                 qname_in: str = "input", forward_qname: str = "input",
                 rr_offset: int = 0, forward_timeout: float = 60.0):
        self.cache = ChunkCache(cache_bytes)
        # raw-record mode forces bytes payloads (zerocopy off): a forwarded
        # record must own its buffer — memoryviews of a local shard mmap
        # cannot travel the wire, and the cache stores owned copies anyway.
        # Columnar (schema) mode is unaffected: ColumnChunk buffers ship
        # out-of-band on the v2/v3 wire.
        self.feed = IngestFeed(
            queues, qname_in=qname_in, stop_event=stop_event,
            schema=schema, binary_features=binary_features,
            chunk_records=chunk_records, readers=readers, prefetch=prefetch,
            autotune=autotune, verify=verify,
            zerocopy=("0" if schema is None else None),
            cache=self.cache)
        self.forwarder = TrainerForwarder(
            trainers, authkey, qname=forward_qname, shuffle=shuffle,
            rr_offset=rr_offset, stop_event=stop_event,
            stall_timeout=forward_timeout)

    def run(self) -> dict:
        """Serve until the ledger feed ends (EndOfFeed / stop signal) or
        every trainer terminates; returns delivery totals."""
        chunks = rows = 0
        t0 = time.monotonic()
        try:
            while True:
                chunk = self.feed.next_chunk()
                if chunk is None:
                    break
                if not self.forwarder.forward(DecodedChunk(chunk)):
                    # consumer side is gone (all trainers terminating):
                    # fast-drain the remaining ledger feed so driver feed
                    # calls unblock — mirroring a terminating DataFeed
                    self.feed.terminate()
                    break
                chunks += 1
                rows += len(chunk)
        finally:
            self.forwarder.close()
        secs = time.monotonic() - t0
        telemetry.gauge("ingest.service_rows_per_s").set(
            round(rows / secs, 1) if secs > 0 else 0.0)
        return {"chunks": chunks, "rows": rows,
                "secs": round(secs, 3), "cache": self.cache.stats()}


def ingest_worker_main(args, ctx) -> dict:
    """The ``role="ingest"`` node body (``node_main`` dispatches here
    instead of the user map_fun when the coordinator assigns the ingest
    role).  Decode options come from ``cluster.run(ingest_opts=...)``
    (``NodeConfig.ingest_opts``); trainer endpoints from the registered
    cluster info; the cache/shuffle knobs from the environment."""
    config = ctx._config
    opts = dict(getattr(config, "ingest_opts", None) or {})
    # node-owned keywords: the stop event is ALWAYS the node's (a
    # user-supplied one could not observe the heartbeat stop ladder), and
    # rr_offset defaults to the worker's task index (fleet decorrelation)
    # unless the opts deliberately pin it — neither may collide with the
    # explicit kwargs below (a collision would TypeError every worker)
    opts.pop("stop_event", None)
    rr_offset = opts.pop("rr_offset", ctx.task_index)
    trainers = [(m["executor_id"], m["host"], m["data_port"])
                for m in ctx.cluster_info
                if m["job_name"] not in ("evaluator", "ingest")
                and m.get("data_port")]
    if not trainers:
        raise RuntimeError("ingest worker found no trainer endpoints in the "
                           "cluster info (nothing to forward decoded chunks "
                           "to)")
    service = IngestService(ctx.queues, trainers, config.authkey,
                            stop_event=ctx.stop_requested,
                            rr_offset=rr_offset, **opts)
    stats = service.run()
    logger.info("ingest worker %d done: %d chunk(s) / %d row(s) forwarded "
                "in %.2fs (cache: %s)", ctx.executor_id, stats["chunks"],
                stats["rows"], stats["secs"], stats["cache"])
    return stats
