"""Driver-side shard enumeration for ``InputMode.DIRECT``.

``cluster.train(path_or_glob)`` in DIRECT mode must turn one user string
into the ledger's work items — a ``PartitionedDataset`` whose partitions
carry shard *paths* (one shard per partition by default, the finest
reassignment granularity a node death can trigger).  The enumeration runs
on the driver but the *fed* paths keep the user's URI scheme: a node
resolves each path against its OWN mounts (``utils.paths.resolve_uri``),
so a cluster whose hosts mount ``hopsfs://`` at different roots still
reads the right files — the reference got the same property from the
Hadoop FS client resolving paths executor-side.
"""

from __future__ import annotations

import glob as _glob
import logging
import os

from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.data import PartitionedDataset
from tensorflowonspark_tpu.utils.envtune import env_int as _env_int
from tensorflowonspark_tpu.utils.paths import resolve_uri

logger = logging.getLogger(__name__)

_GLOB_CHARS = frozenset("*?[")

# Default sub-shard granularity (TOS_INGEST_SPAN_BYTES): plain shards above
# this split into record-aligned byte-range work items so N nodes can
# parallelize INSIDE one multi-GB shard.  256 MiB keeps ordinary shard
# layouts (64-256 MB files) whole while carving anything pathological.
_DEFAULT_SPAN_BYTES = 256 << 20


class ShardSpan:
    """One sub-shard work item: a record-aligned byte range of a PLAIN
    (non-gzip) shard.  Travels the partition ledger exactly like a shard
    path — tens of bytes on the wire — and a node reads just its range
    (``tfrecord.read_span_range``): seek, one bounded read, one CRC scan.
    At-least-once re-feed re-reads exactly this range; gzip shards can
    never be span items (no byte-addressable record boundaries), the
    splitter keeps them whole."""

    __slots__ = ("path", "start", "end")

    def __init__(self, path: str, start: int, end: int):
        self.path = path
        self.start = start
        self.end = end

    def __repr__(self) -> str:
        return f"ShardSpan({self.path!r}, [{self.start}:{self.end}))"

    def __eq__(self, other) -> bool:
        return (isinstance(other, ShardSpan) and self.path == other.path
                and self.start == other.start and self.end == other.end)

    def __hash__(self) -> int:
        return hash((self.path, self.start, self.end))


def work_item_key(item) -> tuple:
    """Canonical identity of one ledger work item — a whole-shard path or a
    :class:`ShardSpan` byte range — used as the span half of the ingest
    tier's cross-epoch chunk-cache key (``ingest/service.py``) and as the
    provenance tag on forwarded chunks.  Two items compare equal exactly
    when they name the same bytes of the same file."""
    if isinstance(item, ShardSpan):
        return (item.path, item.start, item.end)
    return (os.fspath(item), None, None)


def span_bytes_default() -> int:
    """The effective ``TOS_INGEST_SPAN_BYTES`` (0 disables splitting)."""
    return _env_int("TOS_INGEST_SPAN_BYTES", _DEFAULT_SPAN_BYTES, minimum=0)


def split_shards(files: list[str], span_bytes: int | None = None) -> list:
    """Expand shard paths into ledger work items, splitting large plain
    shards into :class:`ShardSpan` record-aligned ranges.

    Per file: gzip shards (``tfrecord.is_gzipped_shard``) and files at or
    under ``span_bytes`` stay whole path items (a gzip stream cannot be
    span-split or view-sliced from a seekable buffer — the whole-shard
    streaming read is its only safe shape); larger plain shards become one
    ``ShardSpan`` per ~``span_bytes`` of record data, walked by header
    only (``tfrecord.walk_record_bounds`` — no payload read, no CRC work
    driver-side).  ``span_bytes=0`` disables splitting.
    """
    if span_bytes is None:
        span_bytes = span_bytes_default()
    if span_bytes <= 0:
        return list(files)
    items: list = []
    for path in files:
        if isinstance(path, ShardSpan):
            items.append(path)  # pre-split by an earlier pass
            continue
        local = resolve_uri(path)
        try:
            size = os.path.getsize(local)
        except OSError:
            items.append(path)  # node-side resolution may still find it
            continue
        if size <= span_bytes or tfrecord.is_gzipped_shard(local):
            items.append(path)
            continue
        try:
            bounds = tfrecord.walk_record_bounds(local, span_bytes)
        except tfrecord.RecordError as e:
            # not (valid) TFRecord framing: keep the file a whole item —
            # node-side reads surface the real error with full context if
            # anything actually consumes it (self-service map_funs may
            # legitimately route non-shard files here and never will)
            logger.warning("not span-splitting %s: %s", path, e)
            items.append(path)
            continue
        if len(bounds) <= 1:
            items.append(path)  # one giant record: nothing to split
            continue
        logger.info("splitting %s (%d bytes) into %d record-span items",
                    path, size, len(bounds))
        items.extend(ShardSpan(path, s, e) for s, e in bounds)
    return items


def enumerate_shards(spec) -> list[str]:
    """Expand a DIRECT-mode input spec into a sorted list of shard paths.

    Accepts:

    - a **directory** (local path or registered URI): its ``part-*`` shard
      files (the ``dfutil.save_as_tfrecords`` layout);
    - a **glob** (contains ``*``/``?``/``[``): every match;
    - a **single file**;
    - a **list/tuple of paths**: used verbatim (already enumerated).

    URIs resolve through ``utils.paths`` for the *enumeration* only; the
    returned paths keep the original scheme so each node re-resolves them
    against its own mounts.
    """
    if isinstance(spec, (list, tuple)):
        paths = [p if isinstance(p, ShardSpan) else os.fspath(p)
                 for p in spec]
        if not paths:
            raise FileNotFoundError("empty shard list for DIRECT-mode train")
        return paths
    spec = os.fspath(spec)
    local = resolve_uri(spec)
    prefix_len = len(local)  # to graft matches back under the original URI

    def _restore(match: str) -> str:
        # '/mnt/hopsfs/data/part-0' back to 'hopsfs://nn/data/part-0'
        if match.startswith(local) and local != spec:
            return spec + match[prefix_len:]
        return match

    if any(c in local for c in _GLOB_CHARS):
        matches = sorted(_glob.glob(local))
        if not matches:
            raise FileNotFoundError(f"no shard files match {spec!r}")
        return [_restore(m) for m in matches]
    if os.path.isdir(local):
        matches = sorted(
            f for f in _glob.glob(os.path.join(local, "part-*"))
            if not f.endswith(".json"))
        if not matches:
            raise FileNotFoundError(f"no 'part-*' shard files under {spec!r}")
        sep = "" if spec.endswith("/") else "/"
        return [spec + sep + os.path.basename(m) if local != spec else m
                for m in matches]
    if os.path.exists(local):
        return [spec]
    raise FileNotFoundError(f"DIRECT-mode input {spec!r} does not exist "
                            "(expected a shard directory, glob, or file)")


def shards_as_partitioned(spec, num_partitions: int | None = None,
                          span_bytes: int | None = None
                          ) -> PartitionedDataset:
    """Ledger work items for a DIRECT-mode train: partitions of shard
    paths and (for large plain shards) :class:`ShardSpan` ranges.

    Default is ONE work item per partition — each ledger task is a single
    file or sub-shard range, so a node death mid-epoch re-assigns exactly
    the unread items, ``shuffle_seed`` reorders individual items between
    epochs, and a single multi-GB shard parallelizes across every node
    instead of pinning to one.  Pass ``num_partitions`` to group items
    (round-robin, sizes even out) when a dataset has so many tiny files
    that per-item ledger acks would dominate; ``span_bytes`` overrides
    ``TOS_INGEST_SPAN_BYTES`` (0 disables sub-shard splitting).
    """
    if isinstance(spec, PartitionedDataset):
        return spec
    items = split_shards(enumerate_shards(spec), span_bytes)
    n = len(items) if num_partitions is None else num_partitions
    if not 0 < n <= len(items):
        raise ValueError(f"num_partitions={n} must be in 1..{len(items)} "
                         "(number of shard work items)")
    return PartitionedDataset.from_partitions([items[i::n] for i in range(n)])
