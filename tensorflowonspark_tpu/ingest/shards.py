"""Driver-side shard enumeration for ``InputMode.DIRECT``.

``cluster.train(path_or_glob)`` in DIRECT mode must turn one user string
into the ledger's work items — a ``PartitionedDataset`` whose partitions
carry shard *paths* (one shard per partition by default, the finest
reassignment granularity a node death can trigger).  The enumeration runs
on the driver but the *fed* paths keep the user's URI scheme: a node
resolves each path against its OWN mounts (``utils.paths.resolve_uri``),
so a cluster whose hosts mount ``hopsfs://`` at different roots still
reads the right files — the reference got the same property from the
Hadoop FS client resolving paths executor-side.
"""

from __future__ import annotations

import glob as _glob
import os

from tensorflowonspark_tpu.data import PartitionedDataset
from tensorflowonspark_tpu.utils.paths import resolve_uri

_GLOB_CHARS = frozenset("*?[")


def enumerate_shards(spec) -> list[str]:
    """Expand a DIRECT-mode input spec into a sorted list of shard paths.

    Accepts:

    - a **directory** (local path or registered URI): its ``part-*`` shard
      files (the ``dfutil.save_as_tfrecords`` layout);
    - a **glob** (contains ``*``/``?``/``[``): every match;
    - a **single file**;
    - a **list/tuple of paths**: used verbatim (already enumerated).

    URIs resolve through ``utils.paths`` for the *enumeration* only; the
    returned paths keep the original scheme so each node re-resolves them
    against its own mounts.
    """
    if isinstance(spec, (list, tuple)):
        paths = [os.fspath(p) for p in spec]
        if not paths:
            raise FileNotFoundError("empty shard list for DIRECT-mode train")
        return paths
    spec = os.fspath(spec)
    local = resolve_uri(spec)
    prefix_len = len(local)  # to graft matches back under the original URI

    def _restore(match: str) -> str:
        # '/mnt/hopsfs/data/part-0' back to 'hopsfs://nn/data/part-0'
        if match.startswith(local) and local != spec:
            return spec + match[prefix_len:]
        return match

    if any(c in local for c in _GLOB_CHARS):
        matches = sorted(_glob.glob(local))
        if not matches:
            raise FileNotFoundError(f"no shard files match {spec!r}")
        return [_restore(m) for m in matches]
    if os.path.isdir(local):
        matches = sorted(
            f for f in _glob.glob(os.path.join(local, "part-*"))
            if not f.endswith(".json"))
        if not matches:
            raise FileNotFoundError(f"no 'part-*' shard files under {spec!r}")
        sep = "" if spec.endswith("/") else "/"
        return [spec + sep + os.path.basename(m) if local != spec else m
                for m in matches]
    if os.path.exists(local):
        return [spec]
    raise FileNotFoundError(f"DIRECT-mode input {spec!r} does not exist "
                            "(expected a shard directory, glob, or file)")


def shards_as_partitioned(spec, num_partitions: int | None = None
                          ) -> PartitionedDataset:
    """Ledger work items for a DIRECT-mode train: partitions of shard paths.

    Default is ONE shard per partition — each ledger task is a single file,
    so a node death mid-epoch re-assigns exactly the unread shards, and
    ``shuffle_seed`` reorders individual shards between epochs.  Pass
    ``num_partitions`` to group shards (round-robin, sizes even out) when a
    dataset has so many tiny files that per-shard ledger acks would dominate.
    """
    if isinstance(spec, PartitionedDataset):
        return spec
    files = enumerate_shards(spec)
    n = len(files) if num_partitions is None else num_partitions
    if not 0 < n <= len(files):
        raise ValueError(f"num_partitions={n} must be in 1..{len(files)} "
                         "(number of shard files)")
    return PartitionedDataset.from_partitions([files[i::n] for i in range(n)])
