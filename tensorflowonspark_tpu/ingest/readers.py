"""Node-side shard reader pipeline: parallel interleave + decode + prefetch.

The DIRECT-input-mode data plane (the reference's ``InputMode.TENSORFLOW``,
per the tf.data paper's parallel-interleave/prefetch design, PAPERS.md):
instead of the driver pumping every row over one socket, each node claims
TFRecord *shard paths* and reads the bytes itself —

    work queue (paths) -> N reader threads -> bounded chunk queue -> consumer
                          read + CRC-verify     (the prefetch buffer)
                          + decode

- **Readers** pull work items — whole shard paths, or ``ShardSpan``
  sub-shard byte ranges of a large plain shard — off the shared work queue
  (tf.data's ``interleave(cycle_length=N)``): plain shards/ranges via one
  IO read + native CRC scan, then ZERO-COPY ``memoryview`` record slices
  (``TOS_INGEST_ZEROCOPY``; no per-record copy between disk and consumer),
  gzip shards via streaming decompression (never a whole-file inflate,
  always ``bytes``).  An optional ``decode`` callable runs per record
  inside the reader thread, so decode parallelism rides reader
  parallelism; a ``schema`` routes records through COLUMNAR Example decode
  instead (``dfutil.decode_span_columns`` — chunks materialize as K
  contiguous column buffers, no per-record parse).
- **The chunk queue is the prefetch buffer** (``TOS_INGEST_PREFETCH``
  chunks deep): readers run ahead of the consumer by up to that many
  decoded chunks, and block (backpressure) beyond it.
- **Autotuned parallelism** (``TOS_INGEST_AUTOTUNE``, tf.data-paper style):
  rather than a fixed thread knob, the consumer's pops sample the queue's
  occupancy — a starving consumer (queue near empty, work pending) grows
  the reader pool toward ``TOS_INGEST_READERS``; a saturated queue shrinks
  it (readers retire at shard boundaries).  Occupancy, pool size, and every
  spawn/retire are exported through ``telemetry``.

``IngestFeed`` (``ingest/feed.py``) drives this pipeline from the node's
feed queue; ``bench_ingest.py`` drives it raw for the scaling numbers.
"""

from __future__ import annotations

import logging
import queue
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.ingest.shards import ShardSpan
from tensorflowonspark_tpu.utils.envtune import env_bool as _env_bool
from tensorflowonspark_tpu.utils.envtune import env_int as _env_int
from tensorflowonspark_tpu.utils.envtune import env_str as _env_str
from tensorflowonspark_tpu.utils.paths import resolve_uri

logger = logging.getLogger(__name__)

# Autotune thresholds on the occupancy EMA (fraction of queue capacity):
# below LOW with work pending the consumer is starving (grow the pool);
# above HIGH the readers outrun the consumer (shrink it — the threads
# would only block on the full queue anyway).
_TUNE_LOW = 0.25
_TUNE_HIGH = 0.85
_TUNE_INTERVAL_SECS = 0.2
_EMA_ALPHA = 0.3


class ShardReadError(RuntimeError):
    """A reader thread failed on a shard (corrupt CRC, IO error, decode
    bug); re-raised at the consumer with the shard path attached."""


def zerocopy_mode(zerocopy=None) -> str:
    """Resolve a zero-copy setting to ``'on'`` / ``'off'`` / ``'debug'``.

    ``None`` reads ``TOS_INGEST_ZEROCOPY`` (default on); booleans and the
    knob's string values both normalize.  ``debug`` is zero-copy PLUS
    release tracking: the feed releases delivered views when their batch
    retires, so code that retains a view past the documented lifetime gets
    a loud ``ValueError`` instead of silently pinning shard buffers.
    """
    if zerocopy is None:
        zerocopy = _env_str("TOS_INGEST_ZEROCOPY", "1")
    if isinstance(zerocopy, bool):
        return "on" if zerocopy else "off"
    mode = str(zerocopy).strip().lower()
    if mode in ("0", "off", "false", "no"):
        return "off"
    if mode == "debug":
        return "debug"
    return "on"


def _materialize_chunk(chunk):
    """An OWNED copy of one decoded chunk, safe to outlive its shard read:
    ``memoryview`` records become ``bytes``; a ``dfutil.ColumnChunk`` whose
    column arrays view the shard mmap is rebuilt over owning arrays.
    Already-owned chunks (bytes records, owning arrays) copy the list
    head only."""
    import numpy as np

    if hasattr(chunk, "columns") and hasattr(chunk, "counts"):
        cols = {name: (np.array(col, copy=True)
                       if isinstance(col, np.ndarray)
                       and not col.flags.owndata else col)
                for name, col in chunk.columns.items()}
        if all(cols[n] is chunk.columns[n] for n in cols):
            return chunk  # every column already owns its buffer
        clone = type(chunk)(cols, chunk.counts, chunk.n, chunk.scalars,
                            chunk.widths)
        return clone
    return [bytes(r) if type(r) is memoryview else r for r in chunk]


class ShardDone:
    """Control token: every record of one claimed shard has been pushed
    (FIFO) before this token — popping it proves the shard fully drained
    out of the chunk queue.  ``tag`` is the submitter's opaque bookkeeping
    handle (the ingest feed's partition job)."""

    __slots__ = ("path", "tag")

    def __init__(self, path: str, tag=None):
        self.path = path
        self.tag = tag


class _Failure:
    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


_DRAINED = object()


class ReaderPipeline:
    """Parallel shard readers feeding one bounded decoded-chunk queue.

    Thread roles: ``submit``/``close`` are producer-side (one thread — the
    ingest feed's claimer, or a bench loop); ``get`` is consumer-side (one
    thread — the map_fun via ``IngestFeed``); reader threads are internal.
    """

    def __init__(self, *, readers: int | None = None,
                 autotune: bool | None = None, prefetch: int | None = None,
                 chunk_records: int = 256, decode=None, verify: bool = True,
                 stop_event: threading.Event | None = None,
                 zerocopy=None, schema=None, binary_features=None,
                 cache=None):
        self._max_readers = max(0, readers if readers is not None
                                else _env_int("TOS_INGEST_READERS", 4, minimum=0))
        # Zero-copy decode contract (TOS_INGEST_ZEROCOPY, default ON): plain
        # shards deliver records as MEMORYVIEW slices of the shard buffer —
        # no per-record copy between the disk read and the consumer.  Each
        # view pins the whole buffer, so holders must drop/copy views once
        # their chunk is released (the feed layer defines release as batch
        # retirement); 'off' restores bytes copies, 'debug' releases
        # delivered views so late access fails loudly.  Gzip shards always
        # deliver bytes (stream-decompressed; no stable buffer to view).
        self.zerocopy = zerocopy_mode(zerocopy)
        # Columnar Example decode (schema=...): chunks materialize as
        # dfutil.ColumnChunk — K contiguous column buffers straight from
        # the span scan (native parser when built) instead of per-record
        # parse + per-row repack.  Mutually exclusive with decode= (the
        # schema IS the decoder).
        if schema is not None and decode is not None:
            raise ValueError("schema= and decode= are mutually exclusive: "
                             "columnar decode is driven by the schema")
        self.schema = schema
        self.binary_features = binary_features
        # readers=0: SYNCHRONOUS mode — no reader threads at all, get()
        # reads the next shard inline in the consumer thread (the tf.data
        # ``num_parallel_calls=None`` analogue).  Trades away read/compute
        # overlap for zero cross-thread traffic — the right shape when a
        # node has one core to its name (bench_ingest measures node
        # scale-out in exactly this configuration).
        self._sync = self._max_readers == 0
        self._autotune = (not self._sync) and (
            autotune if autotune is not None
            else _env_bool("TOS_INGEST_AUTOTUNE", True))
        depth = max(1, prefetch if prefetch is not None
                    else _env_int("TOS_INGEST_PREFETCH", 8))
        self.chunk_records = max(1, chunk_records)
        self.decode = decode
        self.verify = verify
        # Cross-epoch chunk cache (ingest/service.py ChunkCache, or any
        # object with get/put/key_for): repeated reads of the same work
        # item + schema serve MATERIALIZED decoded chunks from memory
        # instead of re-running the CRC scan + decode.  Inactive with a
        # per-record ``decode`` callable — its identity cannot be part of
        # the cache key, and serving another decoder's output would be
        # silent corruption.
        self._cache = cache if (cache is not None
                                and getattr(cache, "enabled", True)
                                and decode is None) else None
        # sync mode buffers one whole shard's chunks at a time (get() is
        # both reader and consumer, so a bounded put would self-deadlock)
        self._out: queue.Queue = queue.Queue(maxsize=0 if self._sync else depth)
        self._work: queue.Queue = queue.Queue()  # paths: tiny, unbounded
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._lock = tos_named_lock("readers._lock")
        self._active = 0
        self._target = 1 if self._autotune else self._max_readers
        self._closed = False
        self._drained_pushed = False
        # consumer-side autotune state (touched only from get(); the lock
        # covers the reader-pool fields both sides mutate)
        self._occupancy_ema = 0.0
        self._last_tune = time.monotonic()
        for _ in range(self._target):
            self._spawn_reader_locked()  # pre-publication: no lock needed yet

    # -- producer side -------------------------------------------------------

    def submit(self, path, tag=None) -> None:
        """Queue one work item — a shard path, or a :class:`ShardSpan`
        sub-shard range — for a reader to claim; ``tag`` rides the item's
        ``ShardDone`` token back to the consumer."""
        self._work.put((path, tag))

    def inject(self, payload, tag=None, source=None) -> bool:
        """Producer-side: hand an ALREADY-DECODED chunk (a record list or a
        ``dfutil.ColumnChunk``) straight to the consumer, bypassing the
        readers — how the trainer-side feed consumes chunks a data-service
        worker decoded remotely (``data.DecodedChunk``).  FIFO with the
        work-item bookkeeping: the chunk's ``ShardDone`` follows it
        immediately, so the partition watermark machinery sees each
        forwarded chunk as one fully-drained "shard".  Returns False when
        the pipeline was stopped with the consumer gone."""
        if not self._put(payload):
            return False
        ok = self._put(ShardDone(source if source is not None
                                 else "<forwarded>", tag))
        if ok:
            telemetry.counter("ingest.chunks_injected").inc()
            telemetry.counter("ingest.records_injected").inc(len(payload))
        return ok

    def close(self) -> None:
        """No more shards will be submitted; readers exit as the work queue
        drains, and the consumer sees end-of-pipeline after the last chunk."""
        with self._lock:
            self._closed = True
            # sync mode signals drain via (closed AND work empty) inside
            # _sync_get — pushing the sentinel here would let it overtake
            # still-queued work items
            push = (not self._sync and self._active == 0
                    and not self._drained_pushed)
            if push:
                self._drained_pushed = True
        if push:
            # outside the lock: the put may block on a full prefetch queue,
            # and the consumer needs the lock to drain it (autotune path)
            self._put(_DRAINED)

    def stop(self) -> None:
        """Abandon everything in flight (terminate/stop-signal path)."""
        self._stop.set()

    # -- consumer side -------------------------------------------------------

    def depth(self) -> int:
        """Decoded chunks queued ahead of the consumer (0 in sync mode)."""
        return self._out.qsize()

    def get(self, timeout: float = 0.25):
        """Pop the next item: a list of records (one decoded chunk), a
        :class:`ShardDone` token, or ``None`` once the pipeline has fully
        drained.  Raises ``queue.Empty`` on timeout and
        :class:`ShardReadError` when a reader failed."""
        if self._sync:
            return self._sync_get(timeout)
        self._maybe_tune()
        item = self._out.get(timeout=timeout)
        if item is _DRAINED:
            return None
        if isinstance(item, _Failure):
            raise item.error
        return item

    def _sync_get(self, timeout: float):
        """readers=0: serve buffered chunks, else read the next shard
        INLINE in the calling (consumer) thread."""
        try:
            item = self._out.get_nowait()
        except queue.Empty:  # toslint: allow-silent(no buffered chunk yet: fall through to claim the next shard)
            pass
        else:
            if item is _DRAINED:
                return None
            return item
        if self._stop.is_set():
            return None
        with self._lock:
            closed = self._closed
        if closed:
            # close() precedes no further submits: an empty work queue IS
            # the drain — answer now instead of blocking a full timeout
            # only to discover it (the stall used to add one poll_interval
            # to EVERY sync-mode feed's tail)
            try:
                path, tag = self._work.get_nowait()
            except queue.Empty:
                # observing closed proves every inject() already landed
                # (the claimer injects before calling close, and both
                # sides synchronize on self._lock) — so ONE out-queue
                # re-check closes the race where a chunk was injected
                # between the get_nowait at the top and the closed read
                # above; without it that chunk would be stranded and the
                # feed would report drained with records undelivered
                try:
                    item = self._out.get_nowait()
                except queue.Empty:
                    return None
                return None if item is _DRAINED else item
        else:
            try:
                path, tag = self._work.get(timeout=timeout)
            except queue.Empty:
                with self._lock:
                    closed = self._closed
                if closed:
                    # closed while we were blocked on the (empty) work
                    # queue — but chunks may have been inject()ed into the
                    # out queue during that wait (the pure-consumer feed's
                    # claimer): re-enter from the top, which drains them
                    # before the work-empty check can answer drained
                    return self._sync_get(timeout)
                raise
        try:
            with telemetry.timed("ingest.shard_read_secs"):
                self._read_one(path, tag)
        except Exception as e:  # noqa: BLE001 - same contract as the pool
            wrapped = ShardReadError(f"reading shard {path!r} failed: {e}")
            wrapped.__cause__ = e
            telemetry.counter("ingest.reader_errors").inc()
            raise wrapped from e
        return self._sync_get(timeout)

    def _maybe_tune(self) -> None:
        """Occupancy-EMA autotune, driven by consumer pops (no timer
        thread): grow while the consumer starves, shrink while readers
        saturate the queue.  Sampling at pop time biases toward the moments
        that matter — when the consumer actually wants data."""
        occupancy = self._out.qsize()
        telemetry.gauge("ingest.prefetch_depth").set(occupancy)
        if not self._autotune:
            return
        self._occupancy_ema += _EMA_ALPHA * (occupancy / self._out.maxsize
                                             - self._occupancy_ema)
        now = time.monotonic()
        if now - self._last_tune < _TUNE_INTERVAL_SECS:
            return
        self._last_tune = now
        telemetry.gauge("ingest.queue_occupancy").set(
            round(self._occupancy_ema, 4))
        if (self._occupancy_ema < _TUNE_LOW and not self._work.empty()):
            # closed does NOT gate growth: it only means no more submits,
            # and the work queue may still be deep
            with self._lock:
                if self._target < self._max_readers and self._active > 0:
                    self._target += 1
                    self._spawn_reader_locked()
                    telemetry.counter("ingest.reader_spawns").inc()
        elif self._occupancy_ema > _TUNE_HIGH:
            with self._lock:
                if self._target > 1:
                    self._target -= 1  # a reader retires at its next boundary

    # -- reader pool ---------------------------------------------------------

    def _spawn_reader_locked(self) -> None:
        """Start one reader; caller holds ``self._lock`` (or is __init__,
        pre-publication)."""
        self._active += 1
        telemetry.gauge("ingest.readers_active").set(self._active)
        threading.Thread(target=self._reader_loop, daemon=True,
                         name=f"ingest-reader-{self._active}").start()

    def _reader_loop(self) -> None:
        retired = False
        try:
            while not self._stop.is_set():
                with self._lock:
                    if self._active > self._target:
                        # autotune shrink: exactly one reader retires per
                        # decrement, accounted here so the exit path below
                        # never double-counts (target >= 1, so a retiree is
                        # never the last reader)
                        self._active -= 1
                        retired = True
                        telemetry.counter("ingest.reader_retires").inc()
                        telemetry.gauge("ingest.readers_active").set(self._active)
                        return
                try:
                    path, tag = self._work.get(timeout=0.1)
                except queue.Empty:
                    with self._lock:
                        if self._closed:
                            return
                    continue
                try:
                    with telemetry.timed("ingest.shard_read_secs"):
                        self._read_one(path, tag)
                except Exception as e:  # noqa: BLE001 - re-raised consumer-side
                    wrapped = ShardReadError(f"reading shard {path!r} failed: {e}")
                    wrapped.__cause__ = e
                    telemetry.counter("ingest.reader_errors").inc()
                    self._put(_Failure(wrapped))
                    return
        finally:
            if not retired:
                push = False
                with self._lock:
                    self._active -= 1
                    telemetry.gauge("ingest.readers_active").set(self._active)
                    if (self._active == 0
                            and (self._closed or self._stop.is_set())
                            and not self._drained_pushed):
                        self._drained_pushed = True
                        push = True
                if push:
                    # outside the lock (the put can block on a full queue
                    # whose consumer needs the lock); _put gives up only
                    # when stop is set AND the consumer stopped draining,
                    # at which point nobody would read the sentinel anyway
                    self._put(_DRAINED)

    def _read_one(self, item, tag) -> None:
        """Read + verify one work item (whole shard, or a ``ShardSpan``
        sub-shard range), pushing decoded chunks then the item's
        ``ShardDone``.  Plain shards take the span path — ONE open, one
        native CRC scan, then zero-copy ``memoryview`` record slices (or
        bytes copies with ``TOS_INGEST_ZEROCOPY=0``); with ``schema`` set,
        chunks of spans decode columnar (``dfutil.decode_span_columns``)
        into contiguous column buffers instead.  Gzip shards stream (probe
        open + gzip.open) and always deliver bytes."""
        # Cross-epoch chunk cache: a repeated read of the same work item
        # (same bytes, same schema) serves the MATERIALIZED chunks straight
        # from memory — no IO, no CRC scan, no decode.  Misses tee their
        # decoded chunks into the cache on the way out (materialized copies:
        # a cached record must own its buffer, never view a shard mmap that
        # retires with this read).
        tee: dict | None = None
        cache_key = None
        if self._cache is not None:
            cache_key = self._cache.key_for(item, self.schema,
                                            self.binary_features)
            hit = self._cache.get(cache_key)
            if hit is not None:
                nrecs = 0
                for chunk in hit:
                    nrecs += len(chunk)
                    if not self._put(chunk):
                        return  # stopped with the consumer gone
                self._put(ShardDone(item, tag))
                telemetry.counter("ingest.shards_read").inc()
                telemetry.counter("ingest.records_read").inc(nrecs)
                return
            # Tee this read into the cache — UNLESS the item is knowably
            # inadmissible up front (a span bigger than the whole budget):
            # materializing copies that put() would only throw away doubles
            # peak reader memory for zero benefit.  Whole-shard items of
            # unknown decoded size start a tee and abandon it the moment
            # the running byte count crosses the budget (_emit).
            budget = self._cache.max_bytes
            known = (item.end - item.start if isinstance(item, ShardSpan)
                     else None)
            if known is None or known <= budget:
                tee = {"chunks": [], "bytes": 0, "budget": budget}
        # Zero-copy record mode maps the shard instead of read()ing it:
        # the CRC scan and the record views walk page-cache pages
        # directly, saving a full DRAM copy pass per shard — the pass
        # that caps aggregate multi-node ingest of one large shard.
        # Columnar and bytes-copy modes keep the bytes read (their
        # decoders materialize/copy anyway).
        use_map = self.schema is None and self.zerocopy != "off"
        if isinstance(item, ShardSpan):
            local = resolve_uri(item.path)
            gz = False
            if use_map:
                buf, spans = tfrecord.map_span_range(local, item.start,
                                                     item.end, self.verify)
            else:
                buf, spans = tfrecord.read_span_range(local, item.start,
                                                      item.end, self.verify)
        else:
            local = resolve_uri(item)
            buf = None  # stays None for gzip shards (they stream)
            if use_map:
                # ONE open: gzip probe off the mapped head + CRC scan
                buf, spans = tfrecord.map_record_spans(local, self.verify)
                gz = buf is None
            else:
                with open(local, "rb") as f:
                    gz = tfrecord._is_gzip_shard(f.read(12))
                    if not gz:
                        f.seek(0)
                        buf = f.read()  # one read, no probe+rest concat copy
                if not gz:
                    spans = tfrecord.scan_record_spans(buf, self.verify,
                                                       name=local)
        if self.schema is not None:
            nrecs, nbytes = self._read_columnar(local, buf,
                                                None if gz else spans, gz,
                                                tee)
            if nrecs is None:
                return  # stopped with the consumer gone
        elif not gz:
            # span fast path: with no decode callable, chunks are plain
            # list windows — no per-record append/accounting loop on the
            # hot path.  Views materialize eagerly (pure slice objects,
            # ~100 ns each, no payload bytes); the BYTES-copy mode slices
            # per window INSIDE the push loop so the bounded prefetch
            # queue keeps pacing the memcpy cost — an eager full-shard
            # copy list would double peak memory per reader.
            zc = self.zerocopy != "off"
            decode = self.decode
            nrecs = len(spans)
            nbytes = sum(length for _, length in spans)
            cr = self.chunk_records
            if decode is None:
                records = tfrecord.record_views(buf, spans) if zc else None
                for i in range(0, nrecs, cr):
                    chunk = (records[i:i + cr] if zc else
                             [buf[off:off + length]
                              for off, length in spans[i:i + cr]])
                    if not self._emit(chunk, tee):
                        return  # stopped with the consumer gone
            else:
                # decode INTERLEAVED with chunk pushes: per-record decode
                # cost paces the queue, so the autotuner's pop-time
                # occupancy sampling sees the decode rate, not one
                # end-of-shard burst.  Decode callables keep their
                # PRE-EXISTING bytes contract (bytes() of a bytes slice is
                # the same object; of an mmap view, the one per-record
                # copy — noise next to per-record Python decode): handing
                # views to decoders written against bytes would crash
                # every one of them for no measurable win.
                chunk: list = []
                for off, length in spans:
                    chunk.append(decode(bytes(buf[off:off + length])))
                    if len(chunk) >= cr:
                        if not self._put(chunk):
                            return
                        chunk = []
                if chunk and not self._put(chunk):
                    return
        else:
            payloads = tfrecord.read_records(local, verify=self.verify,
                                             gzipped=True)
            decode = self.decode
            nbytes = 0
            nrecs = 0
            chunk: list = []
            for payload in payloads:
                nbytes += len(payload)
                nrecs += 1
                chunk.append(decode(payload) if decode is not None else payload)
                if len(chunk) >= self.chunk_records:
                    if not self._emit(chunk, tee):
                        return  # stopped with the consumer gone
                    chunk = []
            if chunk and not self._emit(chunk, tee):
                return
        self._put(ShardDone(item, tag))
        telemetry.counter("ingest.shards_read").inc()
        telemetry.counter("ingest.records_read").inc(nrecs)
        telemetry.counter("ingest.bytes_read").inc(nbytes)
        if tee is not None and tee["chunks"] is not None:
            # the whole item decoded cleanly AND stayed under budget: its
            # materialized chunks are now a cache entry (put re-enforces
            # the byte bound + LRU eviction)
            self._cache.put(cache_key, tee["chunks"], nbytes=tee["bytes"])

    def _emit(self, chunk, tee: dict | None) -> bool:
        """Push one decoded chunk; with the cache teeing this read, append
        a MATERIALIZED copy (owned buffers — zero-copy views die with the
        shard buffer, a cache entry must not).  A tee whose running byte
        count crosses the cache budget is abandoned mid-item — the copies
        are freed immediately instead of riding to an inevitable oversize
        rejection at put()."""
        if tee is not None and tee["chunks"] is not None:
            from tensorflowonspark_tpu.data import chunk_nbytes

            tee["bytes"] += chunk_nbytes(chunk)
            if tee["bytes"] > tee["budget"]:
                tee["chunks"] = None  # inadmissible: stop copying, free now
                telemetry.counter("ingest.cache_oversize_skips").inc()
            else:
                tee["chunks"].append(_materialize_chunk(chunk))
        return self._put(chunk)

    def _read_columnar(self, local: str, buf, spans, gz: bool,
                       tee: list | None = None):
        """Columnar (schema) decode of one work item: every
        ``chunk_records`` spans become ONE ``dfutil.ColumnChunk`` — the
        native parser turns a span window into K contiguous column buffers
        without a per-record Python hop; gzip shards accumulate streamed
        records into the same chunk shape.  Returns ``(nrecs, nbytes)``,
        or ``(None, None)`` when the pipeline stopped mid-item."""
        from tensorflowonspark_tpu import dfutil

        cr = self.chunk_records
        nrecs = 0
        nbytes = 0
        if not gz:
            for i in range(0, len(spans), cr):
                window = spans[i:i + cr]
                cols, counts = dfutil.decode_span_columns(
                    buf, window, self.schema, self.binary_features)
                if not self._emit(dfutil.ColumnChunk.from_schema(
                        cols, counts, self.schema), tee):
                    return None, None
                nrecs += len(window)
                nbytes += sum(length for _, length in window)
            return nrecs, nbytes
        batch: list = []
        for payload in tfrecord.read_records(local, verify=self.verify,
                                             gzipped=True):
            batch.append(payload)
            nbytes += len(payload)
            if len(batch) >= cr:
                cols, counts = dfutil.records_to_columns(
                    batch, self.schema, self.binary_features)
                if not self._emit(dfutil.ColumnChunk.from_schema(
                        cols, counts, self.schema), tee):
                    return None, None
                nrecs += len(batch)
                batch = []
        if batch:
            cols, counts = dfutil.records_to_columns(
                batch, self.schema, self.binary_features)
            if not self._emit(dfutil.ColumnChunk.from_schema(
                    cols, counts, self.schema), tee):
                return None, None
            nrecs += len(batch)
        return nrecs, nbytes

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to stop(): blocking on the full
        prefetch queue IS the backpressure, but an abandoned pipeline (stop
        set, consumer gone) must not strand the reader thread forever."""
        while True:
            try:
                self._out.put(item, timeout=0.1)
                return True
            except queue.Full:
                if self._stop.is_set():
                    return False


def prefetch_iterator(iterable, depth: int = 2):
    """Host-side prefetch: a background thread runs the source iterator up
    to ``depth`` items ahead of the consumer (the tf.data ``prefetch``
    stage).  Source exceptions re-raise at the consumer, at the position
    they would have surfaced unprefetched."""
    if depth <= 0:
        yield from iterable
        return
    buf: queue.Queue = queue.Queue(maxsize=depth)
    DONE = object()
    stopped = threading.Event()
    failure: list[BaseException] = []

    def _bounded_put(item) -> bool:
        while not stopped.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce() -> None:
        try:
            for item in iterable:
                if not _bounded_put(item):
                    return  # consumer abandoned the generator
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            failure.append(e)
        finally:
            _bounded_put(DONE)

    thread = threading.Thread(target=_produce, name="ingest-prefetch",
                              daemon=True)
    thread.start()
    try:
        while True:
            item = buf.get()
            if item is DONE:
                if failure:
                    raise failure[0]
                return
            yield item
    finally:
        stopped.set()  # an abandoning consumer must not strand the producer


def device_prefetch(batches, depth: int = 2, device=None):
    """Prefetch-to-device double buffering: ``jax.device_put`` batch N+1
    while the consumer computes on batch N (the host->device half of the
    tf.data-paper pipeline; ``parallel.dp.make_batch_iterator`` applies the
    same idea to streaming feeds).  Degrades to host-side prefetch when jax
    is unavailable (pure-IO consumers, tests without a backend)."""
    try:
        import jax
    except Exception:  # noqa: BLE001 - jax-free consumers still prefetch
        yield from prefetch_iterator(batches, depth)
        return

    def _placed():
        for batch in batches:
            yield jax.device_put(batch, device)

    yield from prefetch_iterator(_placed(), depth)
