"""Node-side shard reader pipeline: parallel interleave + decode + prefetch.

The DIRECT-input-mode data plane (the reference's ``InputMode.TENSORFLOW``,
per the tf.data paper's parallel-interleave/prefetch design, PAPERS.md):
instead of the driver pumping every row over one socket, each node claims
TFRecord *shard paths* and reads the bytes itself —

    work queue (paths) -> N reader threads -> bounded chunk queue -> consumer
                          read + CRC-verify     (the prefetch buffer)
                          + decode

- **Readers** pull whole shards off the shared work queue (tf.data's
  ``interleave(cycle_length=N)``): plain shards via one
  ``tfrecord.read_record_spans`` IO read + native CRC scan, gzip shards via
  streaming decompression (never a whole-file inflate).  An optional
  ``decode`` callable runs per record inside the reader thread, so decode
  parallelism rides reader parallelism.
- **The chunk queue is the prefetch buffer** (``TOS_INGEST_PREFETCH``
  chunks deep): readers run ahead of the consumer by up to that many
  decoded chunks, and block (backpressure) beyond it.
- **Autotuned parallelism** (``TOS_INGEST_AUTOTUNE``, tf.data-paper style):
  rather than a fixed thread knob, the consumer's pops sample the queue's
  occupancy — a starving consumer (queue near empty, work pending) grows
  the reader pool toward ``TOS_INGEST_READERS``; a saturated queue shrinks
  it (readers retire at shard boundaries).  Occupancy, pool size, and every
  spawn/retire are exported through ``telemetry``.

``IngestFeed`` (``ingest/feed.py``) drives this pipeline from the node's
feed queue; ``bench_ingest.py`` drives it raw for the scaling numbers.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.utils.envtune import env_bool as _env_bool
from tensorflowonspark_tpu.utils.envtune import env_int as _env_int
from tensorflowonspark_tpu.utils.paths import resolve_uri

logger = logging.getLogger(__name__)

# Autotune thresholds on the occupancy EMA (fraction of queue capacity):
# below LOW with work pending the consumer is starving (grow the pool);
# above HIGH the readers outrun the consumer (shrink it — the threads
# would only block on the full queue anyway).
_TUNE_LOW = 0.25
_TUNE_HIGH = 0.85
_TUNE_INTERVAL_SECS = 0.2
_EMA_ALPHA = 0.3


class ShardReadError(RuntimeError):
    """A reader thread failed on a shard (corrupt CRC, IO error, decode
    bug); re-raised at the consumer with the shard path attached."""


class ShardDone:
    """Control token: every record of one claimed shard has been pushed
    (FIFO) before this token — popping it proves the shard fully drained
    out of the chunk queue.  ``tag`` is the submitter's opaque bookkeeping
    handle (the ingest feed's partition job)."""

    __slots__ = ("path", "tag")

    def __init__(self, path: str, tag=None):
        self.path = path
        self.tag = tag


class _Failure:
    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


_DRAINED = object()


class ReaderPipeline:
    """Parallel shard readers feeding one bounded decoded-chunk queue.

    Thread roles: ``submit``/``close`` are producer-side (one thread — the
    ingest feed's claimer, or a bench loop); ``get`` is consumer-side (one
    thread — the map_fun via ``IngestFeed``); reader threads are internal.
    """

    def __init__(self, *, readers: int | None = None,
                 autotune: bool | None = None, prefetch: int | None = None,
                 chunk_records: int = 256, decode=None, verify: bool = True,
                 stop_event: threading.Event | None = None):
        self._max_readers = max(0, readers if readers is not None
                                else _env_int("TOS_INGEST_READERS", 4, minimum=0))
        # readers=0: SYNCHRONOUS mode — no reader threads at all, get()
        # reads the next shard inline in the consumer thread (the tf.data
        # ``num_parallel_calls=None`` analogue).  Trades away read/compute
        # overlap for zero cross-thread traffic — the right shape when a
        # node has one core to its name (bench_ingest measures node
        # scale-out in exactly this configuration).
        self._sync = self._max_readers == 0
        self._autotune = (not self._sync) and (
            autotune if autotune is not None
            else _env_bool("TOS_INGEST_AUTOTUNE", True))
        depth = max(1, prefetch if prefetch is not None
                    else _env_int("TOS_INGEST_PREFETCH", 8))
        self.chunk_records = max(1, chunk_records)
        self.decode = decode
        self.verify = verify
        # sync mode buffers one whole shard's chunks at a time (get() is
        # both reader and consumer, so a bounded put would self-deadlock)
        self._out: queue.Queue = queue.Queue(maxsize=0 if self._sync else depth)
        self._work: queue.Queue = queue.Queue()  # paths: tiny, unbounded
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._lock = threading.Lock()
        self._active = 0
        self._target = 1 if self._autotune else self._max_readers
        self._closed = False
        self._drained_pushed = False
        # consumer-side autotune state (touched only from get(); the lock
        # covers the reader-pool fields both sides mutate)
        self._occupancy_ema = 0.0
        self._last_tune = time.monotonic()
        for _ in range(self._target):
            self._spawn_reader_locked()  # pre-publication: no lock needed yet

    # -- producer side -------------------------------------------------------

    def submit(self, path: str, tag=None) -> None:
        """Queue one shard path for a reader to claim; ``tag`` rides the
        shard's ``ShardDone`` token back to the consumer."""
        self._work.put((path, tag))

    def close(self) -> None:
        """No more shards will be submitted; readers exit as the work queue
        drains, and the consumer sees end-of-pipeline after the last chunk."""
        with self._lock:
            self._closed = True
            # sync mode signals drain via (closed AND work empty) inside
            # _sync_get — pushing the sentinel here would let it overtake
            # still-queued work items
            push = (not self._sync and self._active == 0
                    and not self._drained_pushed)
            if push:
                self._drained_pushed = True
        if push:
            # outside the lock: the put may block on a full prefetch queue,
            # and the consumer needs the lock to drain it (autotune path)
            self._put(_DRAINED)

    def stop(self) -> None:
        """Abandon everything in flight (terminate/stop-signal path)."""
        self._stop.set()

    # -- consumer side -------------------------------------------------------

    def depth(self) -> int:
        """Decoded chunks queued ahead of the consumer (0 in sync mode)."""
        return self._out.qsize()

    def get(self, timeout: float = 0.25):
        """Pop the next item: a list of records (one decoded chunk), a
        :class:`ShardDone` token, or ``None`` once the pipeline has fully
        drained.  Raises ``queue.Empty`` on timeout and
        :class:`ShardReadError` when a reader failed."""
        if self._sync:
            return self._sync_get(timeout)
        self._maybe_tune()
        item = self._out.get(timeout=timeout)
        if item is _DRAINED:
            return None
        if isinstance(item, _Failure):
            raise item.error
        return item

    def _sync_get(self, timeout: float):
        """readers=0: serve buffered chunks, else read the next shard
        INLINE in the calling (consumer) thread."""
        try:
            item = self._out.get_nowait()
        except queue.Empty:  # toslint: allow-silent(no buffered chunk yet: fall through to claim the next shard)
            pass
        else:
            if item is _DRAINED:
                return None
            return item
        if self._stop.is_set():
            return None
        try:
            path, tag = self._work.get(timeout=timeout)
        except queue.Empty:
            with self._lock:
                if self._closed:
                    return None
            raise
        try:
            with telemetry.timed("ingest.shard_read_secs"):
                self._read_one(path, tag)
        except Exception as e:  # noqa: BLE001 - same contract as the pool
            wrapped = ShardReadError(f"reading shard {path!r} failed: {e}")
            wrapped.__cause__ = e
            telemetry.counter("ingest.reader_errors").inc()
            raise wrapped from e
        return self._sync_get(timeout)

    def _maybe_tune(self) -> None:
        """Occupancy-EMA autotune, driven by consumer pops (no timer
        thread): grow while the consumer starves, shrink while readers
        saturate the queue.  Sampling at pop time biases toward the moments
        that matter — when the consumer actually wants data."""
        occupancy = self._out.qsize()
        telemetry.gauge("ingest.prefetch_depth").set(occupancy)
        if not self._autotune:
            return
        self._occupancy_ema += _EMA_ALPHA * (occupancy / self._out.maxsize
                                             - self._occupancy_ema)
        now = time.monotonic()
        if now - self._last_tune < _TUNE_INTERVAL_SECS:
            return
        self._last_tune = now
        telemetry.gauge("ingest.queue_occupancy").set(
            round(self._occupancy_ema, 4))
        if (self._occupancy_ema < _TUNE_LOW and not self._work.empty()):
            # closed does NOT gate growth: it only means no more submits,
            # and the work queue may still be deep
            with self._lock:
                if self._target < self._max_readers and self._active > 0:
                    self._target += 1
                    self._spawn_reader_locked()
                    telemetry.counter("ingest.reader_spawns").inc()
        elif self._occupancy_ema > _TUNE_HIGH:
            with self._lock:
                if self._target > 1:
                    self._target -= 1  # a reader retires at its next boundary

    # -- reader pool ---------------------------------------------------------

    def _spawn_reader_locked(self) -> None:
        """Start one reader; caller holds ``self._lock`` (or is __init__,
        pre-publication)."""
        self._active += 1
        telemetry.gauge("ingest.readers_active").set(self._active)
        threading.Thread(target=self._reader_loop, daemon=True,
                         name=f"ingest-reader-{self._active}").start()

    def _reader_loop(self) -> None:
        retired = False
        try:
            while not self._stop.is_set():
                with self._lock:
                    if self._active > self._target:
                        # autotune shrink: exactly one reader retires per
                        # decrement, accounted here so the exit path below
                        # never double-counts (target >= 1, so a retiree is
                        # never the last reader)
                        self._active -= 1
                        retired = True
                        telemetry.counter("ingest.reader_retires").inc()
                        telemetry.gauge("ingest.readers_active").set(self._active)
                        return
                try:
                    path, tag = self._work.get(timeout=0.1)
                except queue.Empty:
                    with self._lock:
                        if self._closed:
                            return
                    continue
                try:
                    with telemetry.timed("ingest.shard_read_secs"):
                        self._read_one(path, tag)
                except Exception as e:  # noqa: BLE001 - re-raised consumer-side
                    wrapped = ShardReadError(f"reading shard {path!r} failed: {e}")
                    wrapped.__cause__ = e
                    telemetry.counter("ingest.reader_errors").inc()
                    self._put(_Failure(wrapped))
                    return
        finally:
            if not retired:
                push = False
                with self._lock:
                    self._active -= 1
                    telemetry.gauge("ingest.readers_active").set(self._active)
                    if (self._active == 0
                            and (self._closed or self._stop.is_set())
                            and not self._drained_pushed):
                        self._drained_pushed = True
                        push = True
                if push:
                    # outside the lock (the put can block on a full queue
                    # whose consumer needs the lock); _put gives up only
                    # when stop is set AND the consumer stopped draining,
                    # at which point nobody would read the sentinel anyway
                    self._put(_DRAINED)

    def _read_one(self, path: str, tag) -> None:
        """Read + verify one whole shard, pushing decoded chunks then the
        shard's ``ShardDone``.  Plain shards take the span path — ONE open,
        one native CRC scan, per-record slices (on remote filesystems every
        extra open is a metadata round-trip); gzip shards stream (probe
        open + gzip.open)."""
        local = resolve_uri(path)
        decode = self.decode
        nbytes = 0
        nrecs = 0
        chunk: list = []
        with open(local, "rb") as f:
            gz = tfrecord._is_gzip_shard(f.read(12))
            if gz:
                buf = None
            else:
                f.seek(0)
                buf = f.read()  # one read, no probe+rest concat copy
        if gz:
            payloads = tfrecord.read_records(local, verify=self.verify,
                                             gzipped=True)
        else:
            spans = tfrecord.scan_record_spans(buf, self.verify, name=local)
            payloads = (buf[off:off + length] for off, length in spans)
        for payload in payloads:
            nbytes += len(payload)
            nrecs += 1
            chunk.append(decode(payload) if decode is not None else payload)
            if len(chunk) >= self.chunk_records:
                if not self._put(chunk):
                    return  # stopped with the consumer gone
                chunk = []
        if chunk and not self._put(chunk):
            return
        self._put(ShardDone(path, tag))
        telemetry.counter("ingest.shards_read").inc()
        telemetry.counter("ingest.records_read").inc(nrecs)
        telemetry.counter("ingest.bytes_read").inc(nbytes)

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to stop(): blocking on the full
        prefetch queue IS the backpressure, but an abandoned pipeline (stop
        set, consumer gone) must not strand the reader thread forever."""
        while True:
            try:
                self._out.put(item, timeout=0.1)
                return True
            except queue.Full:
                if self._stop.is_set():
                    return False


def prefetch_iterator(iterable, depth: int = 2):
    """Host-side prefetch: a background thread runs the source iterator up
    to ``depth`` items ahead of the consumer (the tf.data ``prefetch``
    stage).  Source exceptions re-raise at the consumer, at the position
    they would have surfaced unprefetched."""
    if depth <= 0:
        yield from iterable
        return
    buf: queue.Queue = queue.Queue(maxsize=depth)
    DONE = object()
    stopped = threading.Event()
    failure: list[BaseException] = []

    def _bounded_put(item) -> bool:
        while not stopped.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce() -> None:
        try:
            for item in iterable:
                if not _bounded_put(item):
                    return  # consumer abandoned the generator
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            failure.append(e)
        finally:
            _bounded_put(DONE)

    thread = threading.Thread(target=_produce, name="ingest-prefetch",
                              daemon=True)
    thread.start()
    try:
        while True:
            item = buf.get()
            if item is DONE:
                if failure:
                    raise failure[0]
                return
            yield item
    finally:
        stopped.set()  # an abandoning consumer must not strand the producer


def device_prefetch(batches, depth: int = 2, device=None):
    """Prefetch-to-device double buffering: ``jax.device_put`` batch N+1
    while the consumer computes on batch N (the host->device half of the
    tf.data-paper pipeline; ``parallel.dp.make_batch_iterator`` applies the
    same idea to streaming feeds).  Degrades to host-side prefetch when jax
    is unavailable (pure-IO consumers, tests without a backend)."""
    try:
        import jax
    except Exception:  # noqa: BLE001 - jax-free consumers still prefetch
        yield from prefetch_iterator(batches, depth)
        return

    def _placed():
        for batch in batches:
            yield jax.device_put(batch, device)

    yield from prefetch_iterator(_placed(), depth)
