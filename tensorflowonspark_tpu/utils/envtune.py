"""Env-tunable numeric knobs (reference: ``TFOS_SERVER_TIMEOUT``-style ops
overrides, ``reservation.py:~120-160``): ops can raise fleet-wide budgets
without touching job code.  Shared by the cluster, data plane, and the
elastic-recovery layer so every timeout/retry default follows one pattern.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_unregistered_warned: set[str] = set()


def _note_read(name: str) -> None:
    """Warn (once per name) when a ``TOS_*`` knob is read without being in
    the central registry — an undiscoverable knob is a knob ops cannot tune;
    ``utils/knobs.py`` + the README table are the discovery surface, and the
    ``knob-discipline`` checker enforces the same invariant statically."""
    if not name.startswith("TOS_") or name in _unregistered_warned:
        return
    from tensorflowonspark_tpu.utils import knobs

    if name not in knobs.KNOBS:
        _unregistered_warned.add(name)
        logger.warning("env knob %s is not registered in utils/knobs.py; "
                       "add it so ops can discover it", name)


def env_float(name: str, default: float) -> float:
    """Positive float from the environment, else ``default``.

    0 is NOT "no timeout" for the knobs this serves — it would make every
    bounded wait fail instantly; non-positive and junk values fall back to
    the default with a warning instead.
    """
    _note_read(name)
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
    if value <= 0:
        logger.warning("ignoring non-positive %s=%r", name, raw)
        return default
    return value


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Integer knob with a floor (retry/attempt counts must stay >= 1)."""
    _note_read(name)
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
    if value < minimum:
        logger.warning("ignoring %s=%r below floor %d", name, raw, minimum)
        return default
    return value


def env_str(name: str, default: str = "") -> str:
    """String knob, returned verbatim when set (``default`` when unset).

    Empty-string values pass through: for knobs like ``TOS_COORDINATOR_HOST``
    the empty string is a meaningful setting (bind all interfaces), not an
    absence.
    """
    _note_read(name)
    raw = os.environ.get(name)
    return default if raw is None else raw


_BOOL_VALUES = {"1": True, "true": True, "yes": True, "on": True,
                "0": False, "false": False, "no": False, "off": False}


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob; junk values fall back to the default with a warning
    (an ops typo must degrade to the documented default, never silently
    flip a feature)."""
    _note_read(name)
    raw = os.environ.get(name)
    if not raw:
        return default
    value = _BOOL_VALUES.get(raw.strip().lower())
    if value is None:
        logger.warning("ignoring non-boolean %s=%r", name, raw)
        return default
    return value
