"""Env-tunable numeric knobs (reference: ``TFOS_SERVER_TIMEOUT``-style ops
overrides, ``reservation.py:~120-160``): ops can raise fleet-wide budgets
without touching job code.  Shared by the cluster, data plane, and the
elastic-recovery layer so every timeout/retry default follows one pattern.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def env_float(name: str, default: float) -> float:
    """Positive float from the environment, else ``default``.

    0 is NOT "no timeout" for the knobs this serves — it would make every
    bounded wait fail instantly; non-positive and junk values fall back to
    the default with a warning instead.
    """
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
    if value <= 0:
        logger.warning("ignoring non-positive %s=%r", name, raw)
        return default
    return value


def env_int(name: str, default: int, minimum: int = 1) -> int:
    """Integer knob with a floor (retry/attempt counts must stay >= 1)."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return default
    if value < minimum:
        logger.warning("ignoring %s=%r below floor %d", name, raw, minimum)
        return default
    return value
