"""Utility helpers (networking, paths, logging)."""

from tensorflowonspark_tpu.utils.net import find_free_port, local_ip  # noqa: F401
from tensorflowonspark_tpu.utils.paths import absolute_path, register_fs_root  # noqa: F401
