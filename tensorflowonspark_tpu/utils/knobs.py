"""Central registry of every ``TOS_*`` tuning knob.

One row per knob: name, type, documented default, and a one-line operator
docstring.  This is the single source of truth that

- ``utils/envtune`` warns against at read time (an ``env_*`` call on an
  unregistered ``TOS_*`` name is a knob that ops cannot discover);
- the ``knob-discipline`` checker in ``tensorflowonspark_tpu.analysis``
  cross-checks statically: every knob read in the tree must be registered
  here, every registered knob must be read somewhere, and the README
  "Tuning knobs" table must match ``knob_table_markdown()`` exactly
  (regenerate with ``python -m tensorflowonspark_tpu.analysis
  --write-knob-table``).

Defaults are *rendered* strings — some real defaults are computed (e.g.
``TOS_DEAD_NODE_TIMEOUT``), and the registry documents what ops should
expect, not a value the runtime reads back.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "float" | "int" | "str" | "bool"
    default: str  # rendered default, as documented to operators
    doc: str  # one-line operator-facing description


_ALL = (
    Knob("TOS_AUTOSCALE", "bool", "1",
         "Autoscaler kill switch: 0 makes cluster.autoscale() a no-op "
         "(cluster.resize() stays available for manual scaling)."),
    Knob("TOS_AUTOSCALE_COOLDOWN_SECS", "float", "30",
         "Autoscaler hysteresis: hold window after any scale action before "
         "the next one may fire (cooldown_hold decisions)."),
    Knob("TOS_AUTOSCALE_MAX", "int", "8",
         "Autoscaler upper bound on feedable node count (policy desired "
         "counts are clamped into [MIN, MAX])."),
    Knob("TOS_AUTOSCALE_MIN", "int", "1",
         "Autoscaler lower bound on feedable node count."),
    Knob("TOS_AUTOSCALE_TICK_SECS", "float", "5",
         "Autoscaler cadence: seconds between policy decision cycles "
         "(each tick samples cluster.stats over ~2 ticks of window)."),
    Knob("TOS_COLLECTIVE_ALGO", "str", "ring",
         "Cross-host collective all-reduce algorithm: 'ring' (bandwidth-"
         "optimal chunked ring) or 'naive' (gather-broadcast through rank "
         "0 — the bench control and tiny-payload fallback)."),
    Knob("TOS_COLLECTIVE_BUCKET_BYTES", "int", "4194304 (4 MiB)",
         "Cross-host collectives: gradient-bucket / wire-chunk size — "
         "pytree leaves pack into buckets of this many bytes (each bucket "
         "reduced as it fills, overlapping communication with host "
         "transfer), and ring transfers sub-chunk to it."),
    Knob("TOS_COLLECTIVE_EVICT_QUORUM", "int", "0 (majority of survivors)",
         "Gray-failure eviction: distinct survivor suspicion votes "
         "(transitive blame resolved) required before the coordinator "
         "evicts a straggling collective member; 0 derives a majority of "
         "the formation's survivors."),
    Knob("TOS_COLLECTIVE_MIN_WORLD", "int", "1",
         "Gray-failure eviction floor: an eviction that would shrink a "
         "collective group's effective world below this is refused (the "
         "group then rides the collective timeout instead)."),
    Knob("TOS_COLLECTIVE_PROBATION_SECS", "float", "30",
         "How long an evicted (slow-but-alive) collective member stays "
         "benched before its continuing heartbeats readmit it; the group "
         "grows back at its next generation barrier."),
    Knob("TOS_COLLECTIVE_SUSPECT_FACTOR", "float", "8",
         "Straggler detection: a peer-plane receive wait running this many "
         "times past the rolling typical wait files a suspicion vote "
         "(floored at 0.5s, capped at a quarter of the collective timeout; "
         "relative, so uniform slowness never flags anyone)."),
    Knob("TOS_COLLECTIVE_TIMEOUT", "float", "120",
         "Budget (seconds) for one cross-host collective exchange and for "
         "the group-formation rendezvous window; expiry poisons the round "
         "(CollectiveAborted) instead of wedging the trainer."),
    Knob("TOS_CONNECT_ATTEMPTS", "int", "3",
         "Dial attempts (with backoff + jitter) for control/data-plane "
         "clients before a connection error surfaces."),
    Knob("TOS_COORDINATOR_GRACE_SECS", "float",
         "max(12, 6 x heartbeat_interval)",
         "Node-side self-fence: heartbeat silence (seconds) after which a "
         "node stops accepting new ledger work and PARKS (a replacement "
         "may own its slot); at 4x this budget the node gives up and "
         "exits.  A supervised coordinator restart re-admits parked nodes "
         "on the next successful ping."),
    Knob("TOS_COORDINATOR_HOST", "str", "(bind all, advertise local_ip())",
         "Interface an *authenticated* coordinator binds and advertises; "
         "ignored without an authkey (loopback-only then)."),
    Knob("TOS_DEAD_NODE_TIMEOUT", "float", "max(12, 6 x heartbeat_interval)",
         "Heartbeat silence (seconds) after which the driver monitor "
         "declares a node dead."),
    Knob("TOS_DRAIN_STALL_TIMEOUT", "float", "300",
         "Elastic train() tail drain: stop waiting for buffered partitions "
         "after this long without consumption progress."),
    Knob("TOS_DRAIN_TIMEOUT", "float", "60",
         "cluster.resize scale-in: budget for a victim to drain (serving "
         "in-flight + buffered partitions) and exit after EOF before the "
         "reaper escalates to terminate."),
    Knob("TOS_EMBED_CKPT_EVERY", "int", "0 (disabled)",
         "Sharded embedding tier: checkpoint each node's resident shard "
         "range every N training steps (ShardedTable.maybe_checkpoint); "
         "0 leaves durability to explicit checkpoint() calls."),
    Knob("TOS_EMBED_DEDUP", "bool", "1",
         "Sharded embedding tier: 1 dedups a batch's flat ids (np.unique) "
         "before the lookup exchange so each unique row crosses the wire "
         "once; 0 ships per-position ids verbatim (debug / tiny batches)."),
    Knob("TOS_EMBED_LOOKUP_TIMEOUT", "float", "30",
         "Serving-side sharded embeddings: budget (seconds) for one "
         "fan-out lookup round against the replica shards before the "
         "request errors."),
    Knob("TOS_EOF_TIMEOUT", "float", "20",
         "Budget (seconds) for the teardown-path EndOfFeed round-trip to "
         "each node."),
    Knob("TOS_FAULTINJECT", "str", "(unset: disabled)",
         "Deterministic chaos-hook spec (kill / drop_heartbeats / sever); "
         "see faultinject.py for the grammar."),
    Knob("TOS_FEED_TIMEOUT", "float", "600",
         "How long one driver feed call may block against a node whose "
         "consumer has stalled."),
    Knob("TOS_FS_ROOTS", "str", "(unset: no mappings)",
         "scheme=root remote-filesystem mappings (os.pathsep-separated) "
         "carrying register_fs_root() into node processes."),
    Knob("TOS_INGEST_CACHE_BYTES", "int", "0 (disabled)",
         "Data-service tier: cross-epoch decoded-chunk cache budget per "
         "ingest worker (payload bytes, LRU); repeated-epoch reads of the "
         "same shard span + schema serve from memory instead of "
         "re-decoding.  0 disables the cache."),
    Knob("TOS_INGEST_SHUFFLE", "bool", "1",
         "Data-service tier: 1 deals each worker's decoded chunks "
         "round-robin across ALL trainers (global shuffle — every "
         "trainer's stream interleaves every shard the pool claims); 0 "
         "pins each worker to one trainer (locality mode)."),
    Knob("TOS_INGEST_WORKERS", "int", "0 (node-local ingest)",
         "Data-service tier size: cluster.run() default for the number of "
         "standalone ingest-worker nodes (role='ingest') that claim the "
         "DIRECT-mode ledger's shard items, decode on their own cores, "
         "and stream chunks to trainers; 0 keeps decode node-local."),
    Knob("TOS_LOCK_WITNESS", "str", "0 (off)",
         "Runtime lock witness (tossan): 1/raise records per-thread "
         "held-sets + the global acquisition-order graph over every "
         "tos_named_lock and raises LockOrderError at acquire time on an "
         "order inversion; 'warn' records inversions without raising; 0 "
         "reduces the witness to a single attribute check per acquire."),
    Knob("TOS_LOCK_STALL_SECS", "float", "5",
         "Lock witness stall budget: a witnessed acquire that has waited "
         "this long dumps all-thread stacks to the flight recorder "
         "(lock_stall event) once per wait episode."),
    Knob("TOS_INGEST_AUTOTUNE", "bool", "1",
         "DIRECT-mode ingest: autotune reader parallelism from decode-queue "
         "occupancy (start at 1, grow while the consumer starves, shrink "
         "when readers saturate); 0 pins TOS_INGEST_READERS threads."),
    Knob("TOS_INGEST_PREFETCH", "int", "8",
         "DIRECT-mode ingest: decoded-chunk prefetch depth (bounded queue "
         "capacity) between the shard readers and the consuming map_fun."),
    Knob("TOS_INGEST_READERS", "int", "4",
         "DIRECT-mode ingest: parallel shard-reader threads per node (the "
         "autotune ceiling; exact pool size when TOS_INGEST_AUTOTUNE=0; "
         "0 = synchronous in-consumer reads, zero pipeline threads)."),
    Knob("TOS_INGEST_SPAN_BYTES", "int", "268435456 (256 MiB)",
         "DIRECT-mode ingest: plain (non-gzip) shards larger than this "
         "split into record-aligned sub-shard work items so N nodes "
         "parallelize inside one multi-GB shard; 0 keeps shards whole."),
    Knob("TOS_INGEST_ZEROCOPY", "str", "1",
         "DIRECT-mode ingest zero-copy record views: 1 delivers records "
         "as memoryview slices of the shard buffer (valid until the batch "
         "retires), 0 restores bytes copies, 'debug' releases retired "
         "batches' views so a retained view fails loudly."),
    Knob("TOS_MAX_PARTITION_ATTEMPTS", "int", "3",
         "Total feed attempts per partition (at-least-once ledger) before "
         "the job fails."),
    Knob("TOS_METRICS", "bool", "1",
         "Telemetry master switch: 0 makes every counter/gauge/histogram a "
         "no-op and stops the heartbeat metric piggyback."),
    Knob("TOS_METRICS_EXPORT_SECS", "float", "30",
         "Cadence of the driver's periodic aggregated-metrics export to "
         "TensorBoard scalars (written under <log_dir>/metrics)."),
    Knob("TOS_RUN_REPORT", "bool", "1",
         "Write the end-of-run JSON run report (run_report.json in the "
         "cluster log_dir) at shutdown; needs TOS_METRICS on."),
    Knob("TOS_MAX_RESTARTS", "int", "2",
         "Supervised restarts allowed per executor slot before it is "
         "permanently failed."),
    Knob("TOS_RECOVERY_TIMEOUT", "float", "90",
         "How long the partition ledger waits for a dead slot to come back "
         "before failing the job."),
    Knob("TOS_REREGISTER_TIMEOUT", "float", "60",
         "Window a respawned replacement gets to re-register before the "
         "supervisor counts another death."),
    Knob("TOS_RESERVATION_TIMEOUT", "float", "120",
         "How long the driver waits for all nodes to register at startup."),
    Knob("TOS_RESTART_BACKOFF_BASE", "float", "0.5",
         "Supervised-restart backoff: delay before the first restart "
         "(seconds)."),
    Knob("TOS_RESTART_BACKOFF_FACTOR", "float", "2.0",
         "Supervised-restart backoff: multiplier per successive restart."),
    Knob("TOS_RESTART_BACKOFF_MAX", "float", "10.0",
         "Supervised-restart backoff: cap on the per-restart delay "
         "(seconds)."),
    Knob("TOS_RING_PROBE_BYTES", "int", "65536",
         "Payload size for the one-shot ring-vs-loopback transport probe "
         "(cached per process; see TOS_SHM_RING)."),
    Knob("TOS_SEND_WINDOW", "int", "4",
         "Pipelined feed: max unacknowledged chunk frames in flight per "
         "node connection (1 = strict request/reply ping-pong)."),
    Knob("TOS_SENDER_POOL", "int", "0 (one sender per node)",
         "Cap on concurrent chunk SENDS across all node connections in "
         "train()/inference() (permit per chunk, never held across a "
         "partition); 0 = unlimited."),
    Knob("TOS_SERVE_CLIENT_SLACK", "float", "30",
         "GatewayClient reply-reaper backstop: extra seconds past the "
         "server-enforced request deadline before an unanswered reply "
         "marks the connection dead (the client then poisons it)."),
    Knob("TOS_SERVE_CONN_OUTSTANDING", "int", "128",
         "Serving frontend: max pipelined requests outstanding per client "
         "connection; excess requests get the fast-fail 'unavailable' "
         "reply instead of queuing."),
    Knob("TOS_SERVE_HANDSHAKE_TIMEOUT", "float", "5",
         "Serving frontend: seconds a new connection may take to finish "
         "the HMAC handshake before the reactor reaps it (slow-loris "
         "protection)."),
    Knob("TOS_SERVE_SWITCH_INTERVAL", "float", "1 (milliseconds)",
         "GIL switch interval (ms) the serving frontend sets for the "
         "driver process while the reactor runs; CPython's 5ms default "
         "convoys reactor/batcher/router handoffs (pass 5 to opt out)."),
    Knob("TOS_SERVE_CANARY_PCT", "int", "25",
         "Staged rollout default: percent of live traffic routed to the "
         "canary cohort by gateway.rollout() when canary_pct is not "
         "passed (shadow rollouts mirror this percent instead)."),
    Knob("TOS_SERVE_ROLLOUT_WINDOW_SECS", "float", "5",
         "Rollout governor cadence: sliding-window length (seconds) over "
         "which canary error-rate/p99/divergence are compared against the "
         "primary baseline before promote/rollback fires."),
    Knob("TOS_SERVE_TENANT_RATE", "float", "0 (unlimited)",
         "Per-tenant admission rate limit: rows/second of token-bucket "
         "budget per unit of tenant weight (1s of burst capacity); a "
         "tenant over its bucket gets fast-fail ServeThrottled replies "
         "while other tenants keep their latency.  0 disables rate "
         "limiting."),
    Knob("TOS_SERVE_SHED_LADDER", "str", "0.5,0.8",
         "Brownout ladder: comma-separated admission-queue occupancy "
         "fractions at which overload shedding escalates — level 1 pauses "
         "shadow-mirror traffic, level 2 sheds tenants past their "
         "weight-proportional queue share (lowest-weight overage first), "
         "before the queue-full cliff (ServeQueueFull) at 100%."),
    Knob("TOS_SERVE_QUEUE", "int", "256",
         "Serving gateway admission control: max queued (not yet "
         "dispatched) predict requests before fast-fail rejection "
         "(ServeQueueFull, the wire 'unavailable' error)."),
    Knob("TOS_SERVE_MAX_BATCH", "int", "64",
         "Serving micro-batcher: rows coalesced into one batch — also the "
         "static batch shape every batch is padded to, so the node's "
         "jitted apply compiles once."),
    Knob("TOS_SERVE_MAX_DELAY_MS", "float", "5",
         "Serving micro-batcher: max milliseconds the oldest queued "
         "request waits for co-riders before a partial batch is flushed."),
    Knob("TOS_SERVE_TIMEOUT", "float", "30",
         "Default per-request deadline (seconds) for gateway predict "
         "calls; expired requests are answered with ServeTimeout."),
    Knob("TOS_SHM_RING", "str", "(unset: measured probe decides)",
         "Same-host shared-memory ring for the data plane: 1 forces it on, "
         "0 forces TCP, unset lets a one-shot ring-vs-loopback probe pick "
         "the faster transport."),
    Knob("TOS_SHUTDOWN_TIMEOUT", "float", "120",
         "Budget for shutdown() to join node processes before escalating "
         "to terminate/kill."),
    Knob("TOS_TRACE", "bool", "0",
         "Distributed request tracing master switch: 1 records sampled "
         "spans into per-thread rings, ships them on heartbeats, and "
         "writes trace_*.json + a merged Perfetto trace.json at shutdown."),
    Knob("TOS_TRACE_SAMPLE", "float", "0.01",
         "Trace sampling rate in (0, 1]: every round(1/rate)-th root "
         "(request / train partition) is traced, deterministically "
         "(counter-based, not random); 1 traces everything."),
    Knob("TOS_FLIGHT_EVENTS", "int", "256",
         "Flight-recorder ring capacity per process (structured "
         "death/restart/retry/resync/reload/fault events, independent of "
         "TOS_TRACE); 0 disables the recorder."),
)

KNOBS: dict[str, Knob] = {k.name: k for k in _ALL}

# README block delimiters; knob_table_markdown() emits the table BETWEEN
# these, and the knob-discipline checker requires the block to match.
TABLE_BEGIN = "<!-- knob-table:begin (generated; run `python -m tensorflowonspark_tpu.analysis --write-knob-table`) -->"
TABLE_END = "<!-- knob-table:end -->"


def find_table_block(lines: list[str]) -> tuple[int, int] | None:
    """(begin, end) indices of the marker lines in README lines, else None.
    The one marker-locating implementation shared by the knob-discipline
    checker and ``--write-knob-table`` so the two can never drift."""
    try:
        return lines.index(TABLE_BEGIN), lines.index(TABLE_END)
    except ValueError:
        return None


def knob_table_markdown() -> str:
    """The generated README "Tuning knobs" table body (no markers)."""
    rows = ["| Knob | Type | Default | What it tunes |",
            "|---|---|---|---|"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(f"| `{k.name}` | {k.kind} | `{k.default}` | {k.doc} |")
    return "\n".join(rows)
