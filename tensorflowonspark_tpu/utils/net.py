"""Networking helpers.

Parity with ``tensorflowonspark/util.py:~1-50`` (find free port / loopback
detection).  Unlike the reference — which binds a port, releases it, and
re-binds later (the ``release_port`` race documented in SURVEY.md §5.2) — we
prefer handing live, already-bound sockets to their consumers so there is no
bind-then-release window.
"""

from __future__ import annotations

import socket


def find_free_port(host: str = "") -> int:
    """Return a currently-free TCP port (note: racy; prefer bound_socket)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def bound_socket(host: str = "") -> socket.socket:
    """Return a listening socket bound to an OS-assigned port (race-free)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(128)
    return s


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-read")
        buf.extend(chunk)
    return bytes(buf)


def local_ip() -> str:
    """Best-effort non-loopback IP of this host, else 127.0.0.1."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # No packets are sent; this just selects a routable interface.
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
