"""Networking helpers.

Parity with ``tensorflowonspark/util.py:~1-50`` (find free port / loopback
detection).  Unlike the reference — which binds a port, releases it, and
re-binds later (the ``release_port`` race documented in SURVEY.md §5.2) — we
prefer handing live, already-bound sockets to their consumers so there is no
bind-then-release window.
"""

from __future__ import annotations

import socket


def find_free_port(host: str = "") -> int:
    """Return a currently-free TCP port (note: racy; prefer bound_socket)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def bound_socket(host: str = "") -> socket.socket:
    """Return a listening socket bound to an OS-assigned port (race-free)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(128)
    return s


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-read")
        buf.extend(chunk)
    return bytes(buf)


def backoff_delay(attempt: int, base: float, factor: float, max_delay: float,
                  jitter: float = 0.25) -> float:
    """Jittered exponential backoff before ``attempt`` (0-based): the one
    formula behind every retry schedule here (dials, supervised restarts),
    so tuning the shape tunes them all.  ±jitter decorrelates a fleet
    retrying the same endpoint in lockstep."""
    import random

    delay = min(max_delay, base * factor**attempt)
    return max(0.0, delay * (1.0 + jitter * (2.0 * random.random() - 1.0)))


def connect_with_backoff(
    address: tuple[str, int],
    timeout: float = 60.0,
    attempts: int = 3,
    base: float = 0.3,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
) -> socket.socket:
    """Dial with bounded exponential backoff + jitter.

    A single-shot connect fails hard during a coordinator or peer *restart
    window* (a supervised restart spends backoff + re-register time with the
    port dark), so every long-lived client retries briefly before surfacing
    the error.  Jitter decorrelates a cluster's worth of clients re-dialing
    the same endpoint at once.  Only connect-level ``OSError`` retries;
    anything after the socket is up (auth, protocol) is the caller's problem.
    """
    import time

    last: OSError | None = None
    for attempt in range(max(1, attempts)):
        try:
            return socket.create_connection(address, timeout=timeout)
        except OSError as e:
            last = e
            if attempt >= attempts - 1:
                break
            time.sleep(backoff_delay(attempt, base, factor, max_delay, jitter))
    raise ConnectionError(
        f"could not connect to {address[0]}:{address[1]} after "
        f"{max(1, attempts)} attempt(s): {last}") from last


def local_ip() -> str:
    """Best-effort non-loopback IP of this host, else 127.0.0.1."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # No packets are sent; this just selects a routable interface.
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


_NONCE_BYTES = 32
# Domain separation for the server's proof: without it a rogue server could
# reflect the client's own digest back as "proof" of knowing the authkey.
_SRV_PROOF_PREFIX = b"tos-coordinator-srv:"


def _digest(authkey: bytes, payload: bytes) -> bytes:
    import hashlib
    import hmac

    return hmac.new(authkey, payload, hashlib.sha256).digest()


def hmac_handshake_server(sock: socket.socket, authkey: bytes) -> bool:
    """MUTUAL challenge-response on the shared cluster authkey;
    constant-time digest compares before any payload deserialization.
    Shared by the data plane (pickle frames, ``dataserver.py``) and the
    control plane (JSON frames, ``coordinator.py``) — the two-way form of
    the ``multiprocessing`` authkey handshake the reference's manager
    queues relied on (``TFManager.py:~20-40``): the server verifies the
    client AND proves its own knowledge of the key, so a port-squatting
    impostor cannot impersonate the coordinator to a dialing node."""
    import hmac
    import os

    nonce_s = os.urandom(_NONCE_BYTES)
    sock.sendall(nonce_s)
    buf = recv_exact(sock, 2 * _NONCE_BYTES)  # client nonce + client digest
    nonce_c, got = buf[:_NONCE_BYTES], buf[_NONCE_BYTES:]
    ok = hmac.compare_digest(_digest(authkey, nonce_s), got)
    # Always answer with a fixed-size proof frame; a failed verify gets
    # random bytes (never a digest), so the peer's compare fails too.
    sock.sendall(_digest(authkey, _SRV_PROOF_PREFIX + nonce_c) if ok
                 else os.urandom(_NONCE_BYTES))
    return ok


def hmac_handshake_client(sock: socket.socket, authkey: bytes) -> bool:
    import hmac
    import os

    nonce_s = recv_exact(sock, _NONCE_BYTES)
    nonce_c = os.urandom(_NONCE_BYTES)
    sock.sendall(nonce_c + _digest(authkey, nonce_s))
    proof = recv_exact(sock, _NONCE_BYTES)
    return hmac.compare_digest(proof, _digest(authkey, _SRV_PROOF_PREFIX + nonce_c))
