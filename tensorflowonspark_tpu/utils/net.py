"""Networking helpers.

Parity with ``tensorflowonspark/util.py:~1-50`` (find free port / loopback
detection).  Unlike the reference — which binds a port, releases it, and
re-binds later (the ``release_port`` race documented in SURVEY.md §5.2) — we
prefer handing live, already-bound sockets to their consumers so there is no
bind-then-release window.
"""

from __future__ import annotations

import socket


def find_free_port(host: str = "") -> int:
    """Return a currently-free TCP port (note: racy; prefer bound_socket)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def bound_socket(host: str = "") -> socket.socket:
    """Return a listening socket bound to an OS-assigned port (race-free)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(128)
    return s


def set_nodelay(sock: socket.socket) -> None:
    """Disable Nagle on a request/reply socket.

    Every stream here is strict request/reply with each frame written in one
    ``sendmsg``/``sendall``, so Nagle buys no batching — but together with
    delayed ACKs it stalls small frames ~40ms per round-trip, which is the
    entire latency budget of the serving gateway (measured: the 1-row
    serving config sat at ~76 qps with p50 38ms before this, ~25x worse
    than after).  Applied to both ends of data-plane, control-plane, and
    gateway connections; best-effort (non-TCP test doubles just skip)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:  # toslint: allow-silent(non-TCP socket or platform without TCP_NODELAY; Nagle is then not in play anyway)
        pass


def recv_exact_into(sock: socket.socket, buf) -> None:
    """Fill a writable buffer exactly from the socket (``recv_into`` loop —
    the zero-copy receive primitive: bytes land directly in the caller's
    preallocated buffer, no per-read ``bytes`` objects to join)."""
    view = memoryview(buf)
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:])
        if n == 0:
            raise ConnectionError("socket closed mid-read")
        got += n


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    buf = bytearray(n)
    recv_exact_into(sock, buf)
    return bytes(buf)


# sendmsg iovec count is bounded by the kernel (IOV_MAX, 1024 on Linux);
# stay under it per call.
_IOV_MAX = 512


def byte_views(buffers) -> list:
    """Flat-byte memoryviews of ``buffers``, empties dropped — the shape
    both send paths (blocking ``sendmsg_all``, the reactor's
    ``sendmsg_some``) consume."""
    return [v for v in (memoryview(b).cast("B") for b in buffers) if len(v)]


def consume_sent(views: list, sent: int) -> None:
    """Drop ``sent`` leading bytes from a list of byte views, in place —
    the short-write bookkeeping shared by every scatter-gather sender."""
    while sent:
        if sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        else:
            views[0] = views[0][sent:]
            sent = 0


def sendmsg_all(sock: socket.socket, buffers) -> None:
    """Scatter-gather send of a buffer list with NO intermediate join.

    The zero-copy send primitive of the data plane: frame headers and
    payload buffers (bytes, memoryviews, pickle out-of-band buffers) go to
    the kernel as an iovec via ``socket.sendmsg`` — the one copy on the
    send path is the kernel's.  Handles short writes and the IOV_MAX cap.
    """
    views = byte_views(buffers)
    while views:
        consume_sent(views, sock.sendmsg(views[:_IOV_MAX]))


def sendmsg_some(sock: socket.socket, views: list) -> int:
    """ONE scatter-gather send attempt on a non-blocking socket.

    The serving reactor's write primitive: accepts whatever the kernel
    buffer takes right now, consumes it from ``views`` in place, and
    returns the byte count (0 when the buffer is full — the caller parks
    the remainder and re-arms EVENT_WRITE).  Never blocks, never loops.
    """
    try:
        sent = sock.sendmsg(views[:_IOV_MAX])
    except BlockingIOError:
        return 0
    consume_sent(views, sent)
    return sent


def backoff_delay(attempt: int, base: float, factor: float, max_delay: float,
                  jitter: float = 0.25) -> float:
    """Jittered exponential backoff before ``attempt`` (0-based): the one
    formula behind every retry schedule here (dials, supervised restarts),
    so tuning the shape tunes them all.  ±jitter decorrelates a fleet
    retrying the same endpoint in lockstep."""
    import random

    delay = min(max_delay, base * factor**attempt)
    return max(0.0, delay * (1.0 + jitter * (2.0 * random.random() - 1.0)))


def connect_with_backoff(
    address: tuple[str, int],
    timeout: float = 60.0,
    attempts: int = 3,
    base: float = 0.3,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
) -> socket.socket:
    """Dial with bounded exponential backoff + jitter.

    A single-shot connect fails hard during a coordinator or peer *restart
    window* (a supervised restart spends backoff + re-register time with the
    port dark), so every long-lived client retries briefly before surfacing
    the error.  Jitter decorrelates a cluster's worth of clients re-dialing
    the same endpoint at once.  Only connect-level ``OSError`` retries;
    anything after the socket is up (auth, protocol) is the caller's problem.
    """
    import time

    last: OSError | None = None
    for attempt in range(max(1, attempts)):
        try:
            sock = socket.create_connection(address, timeout=timeout)
            set_nodelay(sock)
            return sock
        except OSError as e:
            last = e
            if attempt >= attempts - 1:
                break
            time.sleep(backoff_delay(attempt, base, factor, max_delay, jitter))
    raise ConnectionError(
        f"could not connect to {address[0]}:{address[1]} after "
        f"{max(1, attempts)} attempt(s): {last}") from last


def local_ip() -> str:
    """Best-effort non-loopback IP of this host, else 127.0.0.1."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # No packets are sent; this just selects a routable interface.
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


_NONCE_BYTES = 32
#: Size of the client's handshake response (nonce + digest) — what a
#: non-blocking server accumulates before it can verify.
HANDSHAKE_BLOB_BYTES = 2 * _NONCE_BYTES
# Domain separation for the server's proof: without it a rogue server could
# reflect the client's own digest back as "proof" of knowing the authkey.
_SRV_PROOF_PREFIX = b"tos-coordinator-srv:"


def _digest(authkey: bytes, payload: bytes) -> bytes:
    import hashlib
    import hmac

    return hmac.new(authkey, payload, hashlib.sha256).digest()


def hmac_server_challenge() -> bytes:
    """The server's opening handshake frame (its nonce) — sent first."""
    import os

    return os.urandom(_NONCE_BYTES)


def hmac_server_verify(authkey: bytes, nonce_s: bytes,
                       client_blob: bytes) -> tuple[bool, bytes]:
    """Verify a client's ``HANDSHAKE_BLOB_BYTES`` response to ``nonce_s``.

    Returns ``(ok, proof)`` where ``proof`` is the fixed-size frame to send
    back regardless of outcome: the real server proof when the client
    verified, random bytes (never a digest) otherwise, so the peer's
    compare fails too.  This is the verification half of
    ``hmac_handshake_server``, split out so a non-blocking server (the
    serving reactor) can run the same handshake incrementally."""
    import hmac
    import os

    nonce_c = bytes(client_blob[:_NONCE_BYTES])
    got = bytes(client_blob[_NONCE_BYTES:])
    ok = hmac.compare_digest(_digest(authkey, nonce_s), got)
    proof = (_digest(authkey, _SRV_PROOF_PREFIX + nonce_c) if ok
             else os.urandom(_NONCE_BYTES))
    return ok, proof


def hmac_handshake_server(sock: socket.socket, authkey: bytes) -> bool:
    """MUTUAL challenge-response on the shared cluster authkey;
    constant-time digest compares before any payload deserialization.
    Shared by the data plane (pickle frames, ``dataserver.py``) and the
    control plane (JSON frames, ``coordinator.py``) — the two-way form of
    the ``multiprocessing`` authkey handshake the reference's manager
    queues relied on (``TFManager.py:~20-40``): the server verifies the
    client AND proves its own knowledge of the key, so a port-squatting
    impostor cannot impersonate the coordinator to a dialing node."""
    nonce_s = hmac_server_challenge()
    sock.sendall(nonce_s)
    buf = recv_exact(sock, HANDSHAKE_BLOB_BYTES)  # client nonce + digest
    ok, proof = hmac_server_verify(authkey, nonce_s, buf)
    sock.sendall(proof)
    return ok


# -- same-host transport probe ------------------------------------------------
#
# PERF_NOTES round 5 measured the shm ring ~3x SLOWER than loopback TCP on a
# 1-core box (its request/reply ping-pong pays scheduler wakeups the kernel
# TCP path amortizes) yet it used to be selected unconditionally.  The probe
# below settles ring-vs-TCP empirically, once per process: a short measured
# round-trip exchange on each transport, cached.  ``TOS_SHM_RING`` still
# forces either way (1 = always ring, 0 = never); unset means "probe".

_ring_probe_cache: dict[int, bool] = {}


def _probe_tcp_loopback(payload: bytes, rounds: int) -> float:
    """Seconds for ``rounds`` loopback-TCP round-trips of ``payload``."""
    import threading
    import time

    srv = bound_socket("127.0.0.1")
    port = srv.getsockname()[1]
    n = len(payload)

    def _echo() -> None:
        try:
            conn, _ = srv.accept()
            with conn:
                buf = bytearray(n)
                for _ in range(rounds):
                    recv_exact_into(conn, buf)
                    conn.sendall(buf)
        except OSError:
            return

    t = threading.Thread(target=_echo, daemon=True, name="tcp-probe-echo")
    t.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5.0) as c:
            c.settimeout(5.0)
            buf = bytearray(n)
            t0 = time.perf_counter()
            for _ in range(rounds):
                c.sendall(payload)
                recv_exact_into(c, buf)
            return time.perf_counter() - t0
    finally:
        srv.close()
        t.join(timeout=5.0)


def _probe_shm_ring(payload: bytes, rounds: int) -> float:
    """Seconds for ``rounds`` shm-ring round-trips of ``payload``; raises
    when the native ring is unavailable."""
    import contextlib
    import threading
    import time

    from tensorflowonspark_tpu import shm_ring

    # both creates INSIDE the cleanup scope: if the second one fails (shm
    # quota, /dev/shm full) the first segment must still be unlinked —
    # POSIX shm persists past process death until someone unlinks it
    c2s = s2c = None
    t = None
    try:
        c2s = shm_ring.ShmRing.create(capacity=max(1 << 20, 4 * len(payload)))
        s2c = shm_ring.ShmRing.create(capacity=max(1 << 20, 4 * len(payload)))

        def _echo() -> None:
            try:
                for _ in range(rounds):
                    s2c.put_bytes(c2s.get_bytes(timeout=5.0), timeout=5.0)
            except Exception:  # noqa: BLE001 - probe peer: any failure ends it
                return

        t = threading.Thread(target=_echo, daemon=True, name="ring-probe-echo")
        t.start()
        t0 = time.perf_counter()
        for _ in range(rounds):
            c2s.put_bytes(payload, timeout=5.0)
            s2c.get_bytes(timeout=5.0)
        return time.perf_counter() - t0
    finally:
        if t is not None:
            t.join(timeout=5.0)
        for ring in (c2s, s2c):
            if ring is not None:
                for cleanup in (ring.close_write, ring.unlink, ring.detach):
                    with contextlib.suppress(Exception):
                        cleanup()


def ring_beats_loopback(payload_bytes: int | None = None,
                        rounds: int = 16) -> bool:
    """Measured once per process (then cached): is the same-host shm ring
    actually faster than loopback TCP for data-plane-sized messages?

    Called by ``DataClient`` on the first same-host dial when ``TOS_SHM_RING``
    is unset — the slower transport is never silently selected again
    (VERDICT r5 weak #5).  Payload size defaults from ``TOS_RING_PROBE_BYTES``.
    """
    import logging

    from tensorflowonspark_tpu.utils.envtune import env_int

    if payload_bytes is None:
        payload_bytes = env_int("TOS_RING_PROBE_BYTES", 64 * 1024)
    cached = _ring_probe_cache.get(payload_bytes)
    if cached is not None:
        return cached
    payload = b"\x5a" * payload_bytes
    try:
        ring_s = _probe_shm_ring(payload, rounds)
        tcp_s = _probe_tcp_loopback(payload, rounds)
        verdict = ring_s < tcp_s
        logging.getLogger(__name__).info(
            "transport probe (%d x %d B round-trips): ring %.1f ms, "
            "loopback TCP %.1f ms -> %s", rounds, payload_bytes,
            ring_s * 1e3, tcp_s * 1e3, "ring" if verdict else "TCP")
    except Exception:  # noqa: BLE001 - no compiler/shm: TCP is the only option
        logging.getLogger(__name__).debug(
            "transport probe could not run the ring side; staying on TCP",
            exc_info=True)
        verdict = False
    _ring_probe_cache[payload_bytes] = verdict
    return verdict


def hmac_handshake_client(sock: socket.socket, authkey: bytes) -> bool:
    import hmac
    import os

    nonce_s = recv_exact(sock, _NONCE_BYTES)
    nonce_c = os.urandom(_NONCE_BYTES)
    sock.sendall(nonce_c + _digest(authkey, nonce_s))
    proof = recv_exact(sock, _NONCE_BYTES)
    return hmac.compare_digest(proof, _digest(authkey, _SRV_PROOF_PREFIX + nonce_c))
