"""tossan, runtime half: named locks with an optional deadlock witness.

Every threaded module constructs its locks through :func:`tos_named_lock` /
:func:`tos_named_condition` instead of bare ``threading.Lock()`` /
``threading.Condition()``.  The *name* is the lock's identity in the global
acquisition-order graph — one node per name, so every ``Coordinator``
instance's ``coordinator._lock`` is the same node, which is the granularity
lock-order discipline is defined at (the static half,
``analysis/lockgraph.py``, resolves ``with self._lock:`` scopes to the same
names).

Witness off (the production default), a :class:`TosLock` costs one
attribute check per acquire/release on top of the underlying primitive —
the trace-stub pattern (``telemetry/trace.py``): instrumented code pays a
``None`` check, nothing else.

Witness on (``TOS_LOCK_WITNESS=1``; the tier-1 conftest turns it on for the
whole suite), every acquire:

- records the lock into a **per-thread held-set**;
- folds ``held -> acquired`` edges into a **global order graph**, keeping
  the first-observed stack per edge;
- **raises** :class:`LockOrderError` *at acquire time* when the new edge
  closes a cycle — catching an AB/BA deadlock the moment the second order
  is attempted, even when the threads never actually interleave into the
  deadly embrace this run (``TOS_LOCK_WITNESS=warn`` records a flight
  event + counter instead of raising, for soaks that must keep running);
- raises immediately on re-acquiring a non-reentrant lock this thread
  already holds (a guaranteed self-deadlock, no interleaving needed);
- waits in **stall-sized slices**: a lock with waiters held past
  ``TOS_LOCK_STALL_SECS`` dumps every thread's stack to the flight
  recorder (``telemetry.trace.event("lock_stall", ...)``) once per stall
  episode, then keeps waiting — the postmortem lands even if the process
  later wedges for good;
- emits **hold-time histograms** (``lock.hold_ms.<name>``) through the
  telemetry registry on release.

``threading.Condition`` integration: :class:`TosLock` implements the
``_is_owned`` / ``_release_save`` / ``_acquire_restore`` protocol, so
``cond.wait()`` keeps the witness held-set exact across the release/
re-acquire inside the wait.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback

from tensorflowonspark_tpu.utils.envtune import env_float, env_str

#: Frames kept per recorded stack (first-observed edge sites, error reports).
STACK_DEPTH = 12


class LockOrderError(RuntimeError):
    """An acquisition order inversion: taking this lock while holding those
    locks closes a cycle in the global order graph — two threads running
    the two orders concurrently can deadlock."""


class _Witness:
    """Global lock-order witness shared by every :class:`TosLock`.

    Edge fast path: ``(held, acquired)`` pairs already in the graph are a
    dict hit with no lock taken (dict reads are atomic under the GIL);
    only a never-seen edge pays the graph lock + cycle check.
    """

    def __init__(self, mode: str = "raise",
                 stall_secs: float | None = None):
        self.mode = mode  # "raise" | "warn"
        self.stall_secs = (env_float("TOS_LOCK_STALL_SECS", 5.0)
                           if stall_secs is None else stall_secs)
        self._local = threading.local()
        # (held_name, acquired_name) -> first-observed formatted stack.
        # Guarded by _graph_lock for writes; read lock-free on the fast path.
        self._edges: dict[tuple[str, str], str] = {}
        self._succ: dict[str, set[str]] = {}  # name -> direct successors
        self._graph_lock = threading.Lock()
        self.inversions: list[str] = []  # warn-mode reports (tests assert ==[])

    # -- held-set ------------------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def held_names(self) -> list[str]:
        return [lock.name for lock, _ in self._held()]

    # -- order graph ----------------------------------------------------------

    def _reachable(self, src: str, dst: str) -> list[str] | None:
        """A path ``src -> ... -> dst`` in the order graph, else None.
        Caller holds ``_graph_lock``."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._succ.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _note_edges(self, lock: "TosLock", held: list) -> None:
        """Fold ``held -> lock`` edges in; raise/report on a closed cycle."""
        name = lock.name
        for other, _ in held:
            if other.name == name:
                continue  # distinct same-named instances: one graph node
            key = (other.name, name)
            if key in self._edges:  # fast path: known-good order
                continue
            with self._graph_lock:
                if key in self._edges:
                    continue
                back = self._reachable(name, other.name)
                if back is not None:
                    self._report_inversion(other.name, name, back)
                    continue  # warn mode fell through: record it anyway
                self._edges[key] = _brief_stack()
                self._succ.setdefault(other.name, set()).add(name)

    def _report_inversion(self, held_name: str, name: str,
                          back_path: list[str]) -> None:
        chain = " -> ".join(back_path + [name])
        first_hop = self._edges.get((back_path[0], back_path[1])) if (
            len(back_path) > 1) else None
        msg = (f"lock order inversion: acquiring '{name}' while holding "
               f"'{held_name}' closes the cycle {chain}\n"
               f"--- this acquisition (thread "
               f"{threading.current_thread().name}) ---\n{_brief_stack()}")
        if first_hop:
            msg += (f"--- first-observed reverse edge "
                    f"'{back_path[0]}' -> '{back_path[1]}' ---\n{first_hop}")
        from tensorflowonspark_tpu.telemetry import trace

        trace.event("lock_inversion", lock=name, held=held_name, chain=chain)
        if self.mode == "raise":
            raise LockOrderError(msg)
        self.inversions.append(msg)

    # -- acquire / release -----------------------------------------------------

    def acquire(self, lock: "TosLock", blocking: bool, timeout: float) -> bool:
        held = self._held()
        if not lock.reentrant:
            for other, _ in held:
                if other is lock:
                    raise LockOrderError(
                        f"self-deadlock: thread "
                        f"{threading.current_thread().name} re-acquires "
                        f"non-reentrant lock '{lock.name}' it already "
                        f"holds\n{_brief_stack()}")
        if held:
            self._note_edges(lock, held)
        got = self._acquire_sliced(lock, blocking, timeout)
        if got:
            held.append((lock, time.monotonic()))
        return got

    def _acquire_sliced(self, lock: "TosLock", blocking: bool,
                        timeout: float) -> bool:
        """Blocking acquire in stall-sized slices so a starved waiter can
        dump the fleet's stacks without a watchdog thread."""
        inner = lock._inner
        if not blocking:
            return inner.acquire(False)
        deadline = None if timeout < 0 else time.monotonic() + timeout
        dumped = False
        waited = 0.0
        while True:
            if deadline is None:
                wait = self.stall_secs
            else:
                wait = min(self.stall_secs, deadline - time.monotonic())
                if wait < 0:
                    return False
            if inner.acquire(True, wait):
                return True
            waited += wait
            # only a wait that actually exceeded the stall budget is a
            # stall (a short caller timeout expiring is not)
            if not dumped and waited >= self.stall_secs:
                self._dump_stall(lock)
                dumped = True

    def _dump_stall(self, lock: "TosLock") -> None:
        """A lock with a waiter (us) held past the stall budget: dump every
        thread's stack to the flight recorder, once per episode."""
        from tensorflowonspark_tpu.telemetry import trace

        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = {}
        for ident, frame in sys._current_frames().items():
            tb = traceback.format_stack(frame, limit=STACK_DEPTH)
            stacks[names.get(ident, str(ident))] = "".join(tb)
        trace.event("lock_stall", lock=lock.name,
                    holder=lock.owner_name(), waiter=
                    threading.current_thread().name,
                    stall_secs=self.stall_secs, stacks=stacks)

    def release(self, lock: "TosLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _, t0 = held.pop(i)
                from tensorflowonspark_tpu import telemetry

                telemetry.histogram(f"lock.hold_ms.{lock.name}").observe(
                    (time.monotonic() - t0) * 1e3)
                break
        lock._inner.release()


def _brief_stack() -> str:
    frames = traceback.format_stack(limit=STACK_DEPTH)
    # drop the witness's own frames from the tail: the report should end at
    # the acquire call site, not inside this module
    return "".join(f for f in frames if "/utils/locks.py" not in f)


class TosLock:
    """A named lock: raw ``threading.Lock``/``RLock`` semantics when the
    witness is off (one attribute check extra), full order/stall/hold-time
    witnessing when on.  Owner tracking (for ``Condition`` integration and
    stall reports) is two attribute stores per acquire/release."""

    __slots__ = ("name", "reentrant", "_inner", "_owner", "_count")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: int | None = None
        self._count = 0

    # -- core protocol ---------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        w = _witness
        if w is None:
            got = self._inner.acquire(blocking, timeout)
        else:
            got = w.acquire(self, blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
        return got

    def release(self) -> None:
        if self._count == 1:
            self._owner = None
        self._count -= 1
        w = _witness
        if w is None:
            self._inner.release()
        else:
            w.release(self)

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._owner is not None

    def owner_name(self) -> str | None:
        ident = self._owner
        if ident is None:
            return None
        for t in threading.enumerate():
            if t.ident == ident:
                return t.name
        return str(ident)

    # -- threading.Condition protocol -----------------------------------------
    # Condition(lock) drives these so cond.wait() keeps the witness held-set
    # exact across its internal release/re-acquire.

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        state = (self._owner, self._count)
        self._owner, self._count = None, 0
        w = _witness
        if w is None:
            if state[1] > 1:  # reentrant: unwind every level
                for _ in range(state[1]):
                    self._inner.release()
            else:
                self._inner.release()
        else:
            for _ in range(state[1]):
                w.release(self)
        return state

    def _acquire_restore(self, state) -> None:
        owner, count = state
        w = _witness
        for _ in range(max(1, count)):
            if w is None:
                self._inner.acquire()
            else:
                w.acquire(self, True, -1)
        self._owner, self._count = owner, max(1, count)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "locked" if self.locked() else "unlocked"
        return f"<TosLock {self.name!r} {state}>"


def tos_named_lock(name: str, reentrant: bool = False) -> TosLock:
    """The one sanctioned lock constructor for threaded modules: ``name``
    is the node in the global order graph (convention:
    ``<module>.<attr>``, e.g. ``"coordinator._lock"``)."""
    _ensure_witness_init()
    return TosLock(name, reentrant=reentrant)


def tos_named_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` over a witnessed named lock."""
    return threading.Condition(tos_named_lock(name))


# -- witness lifecycle ---------------------------------------------------------

_witness: _Witness | None = None
_witness_init = False
_init_lock = threading.Lock()


def _ensure_witness_init() -> None:
    """Arm the witness from ``TOS_LOCK_WITNESS`` on first factory use
    (lazily, like the tracer singleton): '1'/'raise' raise on inversion,
    'warn' record-only, anything else off."""
    global _witness, _witness_init
    if _witness_init:
        return
    with _init_lock:
        if _witness_init:
            return
        raw = env_str("TOS_LOCK_WITNESS", "0").strip().lower()
        if raw in ("1", "true", "yes", "on", "raise"):
            _witness = _Witness(mode="raise")
        elif raw == "warn":
            _witness = _Witness(mode="warn")
        _witness_init = True


def enable_witness(mode: str = "raise",
                   stall_secs: float | None = None) -> _Witness:
    """Arm (or re-arm, resetting the graph) the witness — tests and the
    bench's off/on compare."""
    global _witness, _witness_init
    with _init_lock:
        _witness = _Witness(mode=mode, stall_secs=stall_secs)
        _witness_init = True
        return _witness


def disable_witness() -> None:
    global _witness, _witness_init
    with _init_lock:
        _witness = None
        _witness_init = True


def get_witness() -> _Witness | None:
    return _witness


def order_graph() -> dict[str, list[str]]:
    """The observed order graph (name -> sorted successors) — empty when
    the witness is off."""
    w = _witness
    if w is None:
        return {}
    with w._graph_lock:
        return {a: sorted(bs) for a, bs in w._succ.items()}
