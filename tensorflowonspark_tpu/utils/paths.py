"""Filesystem path plumbing.

Parity with ``TFNode.hdfs_path(ctx, path)`` (``tensorflowonspark/TFNode.py:~30-70``):
resolve user-relative paths against a default filesystem so checkpoints land
on HopsFS/HDFS in production and on local disk in tests.  The reference
prefixes ``hdfs://namenode/...``; here remote schemes can be *mapped* to a
local mount root (tests register ``hdfs://`` → tmpdir), because checkpoint
libraries (orbax) speak POSIX while production TPU-VM images mount HopsFS/GCS
via FUSE.
"""

from __future__ import annotations

import os
from urllib.parse import urlparse

# scheme -> local root that backs it (e.g. a FUSE mountpoint).
_FS_ROOTS: dict[str, str] = {}

# Env carrier so registrations survive into spawned node processes: the
# launchers pass os.environ through to children (the same way Spark shipped
# the Hadoop conf to executors), so a driver-side register_fs_root is
# visible inside every node's resolve_uri without extra plumbing.
_ENV_KEY = "TOS_FS_ROOTS"


def register_fs_root(scheme: str, local_root: str, export: bool = True) -> None:
    """Map a filesystem scheme (``hdfs``, ``hopsfs``, ``gs``) to a local root.

    ``export=True`` (default) also records the mapping in ``os.environ`` so
    node processes launched afterwards inherit it.
    """
    _load_env_roots()  # don't drop inherited mappings when re-exporting
    _FS_ROOTS[scheme.rstrip(":/")] = local_root
    if export:
        os.environ[_ENV_KEY] = os.pathsep.join(
            f"{s}={r}" for s, r in sorted(_FS_ROOTS.items()))


def _load_env_roots() -> None:
    from tensorflowonspark_tpu.utils.envtune import env_str

    for pair in env_str("TOS_FS_ROOTS", "").split(os.pathsep):
        if "=" in pair:
            scheme, root = pair.split("=", 1)
            _FS_ROOTS.setdefault(scheme, root)


def resolve_uri(path: str) -> str:
    """Translate a possibly-remote URI into a local filesystem path.

    ``hdfs://nn/a/b`` with root ``/mnt/hopsfs`` → ``/mnt/hopsfs/a/b``.
    Unregistered schemes raise so misconfiguration fails fast.
    """
    parsed = urlparse(path)
    if parsed.scheme in ("", "file"):
        return parsed.path if parsed.scheme == "file" else path
    if parsed.scheme not in _FS_ROOTS:
        _load_env_roots()
    root = _FS_ROOTS.get(parsed.scheme)
    if root is None:
        raise ValueError(
            f"no local root registered for scheme {parsed.scheme!r}; "
            f"call register_fs_root({parsed.scheme!r}, <mountpoint>)"
        )
    return os.path.join(root, parsed.path.lstrip("/"))


def absolute_path(path: str, default_fs: str = "", working_dir: str | None = None) -> str:
    """Resolve ``path`` the way ``TFNode.hdfs_path`` does.

    - absolute local path or explicit scheme → unchanged;
    - relative path with a ``default_fs`` (e.g. ``hdfs://nn/user/x``) →
      joined under the default fs;
    - otherwise → joined under ``working_dir`` (cwd by default).
    """
    if urlparse(path).scheme or os.path.isabs(path):
        return path
    if default_fs:
        return default_fs.rstrip("/") + "/" + path
    return os.path.join(working_dir or os.getcwd(), path)
