"""Write-ahead journal for the control plane (ISSUE 13).

Every durable control-plane mutation (slot register/death/retire, incarnation
bump, manifest set, collective ``form`` membership, serving replica registry,
rendezvous open/close, partition-ledger assign/ack/requeue) appends one
compact JSON-lines record here, fsync'd before the mutation's reply leaves
the coordinator — so a coordinator crash loses nothing that was acknowledged.
Recovery is O(delta): a periodic snapshot (atomic ``<path>.snap`` replace +
journal truncate) bounds the tail :func:`replay` has to walk.

This module is the ONE home of journal file opens and ``os.fsync`` calls
(enforced by the toslint ``journal-discipline`` checker): the durability
contract — append ordering, torn-tail tolerance, snapshot/truncate atomicity
— lives in one reviewed place instead of being re-derived at every call
site.

Record wire shape (one JSON object per line)::

    {"n": <monotone seq>, "k": "<kind>", "d": {...payload...}}

Snapshot shape (``<path>.snap``)::

    {"schema": "tos-journal-v1", "seq": <last seq folded in>, "state": {...}}

Crash-ordering contract: the snapshot is replaced atomically BEFORE the
journal is truncated, and records carry sequence numbers — if a crash lands
between the two, :func:`replay` skips tail records the snapshot already
folded in (``n <= seq``) instead of double-applying them.  A torn final
line (a crash mid-append) is dropped with a warning; corruption anywhere
else fails replay loudly (a silently half-replayed control plane is worse
than a dead one).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock

logger = logging.getLogger(__name__)

SCHEMA = "tos-journal-v1"
SNAPSHOT_SUFFIX = ".snap"


class Journal:
    """Append-only fsync'd JSON-lines journal with atomic snapshots.

    Thread contract: all methods are safe to call from any thread; appends
    are totally ordered by the internal lock.  Callers that need record
    order to match state-mutation order must append while holding the same
    lock that guards the mutation (the coordinator does).
    """

    def __init__(self, path: str, truncate: bool = False):
        self.path = str(path)
        self._lock = tos_named_lock("journal._lock")
        self._closed = False
        self._seq = 0
        self._since_snapshot = 0
        if truncate:
            # a fresh server run must never replay a previous run's tail
            try:
                os.remove(self.path + SNAPSHOT_SUFFIX)
            except FileNotFoundError:  # toslint: allow-silent(no prior snapshot is the common fresh-run case)
                pass
            flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC | os.O_APPEND
        else:
            flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
        self._fd = os.open(self.path, flags, 0o644)

    # -- appending -----------------------------------------------------------

    def append(self, kind: str, payload: dict | None = None,
               sync: bool = True) -> int:
        """Durably append one record; returns its sequence number.  With
        ``sync=True`` (the default for state mutations) the record is on
        disk (fsync) before this returns — the caller may acknowledge the
        mutation to the network.  ``sync=False`` is for OBSERVATIONAL
        riders (ledger assign/ack records, replayed as no-ops): the write
        lands in the OS immediately and is flushed by the next synced
        append or snapshot, so a crash can lose at most the rider tail —
        never a mutation — while callers holding hot locks (the ledger
        condition) skip the fsync latency cliff."""
        with self._lock:
            if self._closed:
                raise ValueError("journal is closed")
            self._seq += 1
            seq = self._seq
            line = json.dumps({"n": seq, "k": kind, "d": payload or {}},
                              separators=(",", ":"), default=str)
            os.write(self._fd, line.encode("utf-8") + b"\n")
            if sync:
                os.fsync(self._fd)
            self._since_snapshot += 1
        return seq

    def appended_since_snapshot(self) -> int:
        with self._lock:
            return self._since_snapshot

    # -- snapshots -----------------------------------------------------------

    def snapshot(self, state: dict) -> None:
        """Atomically persist a full-state snapshot and truncate the journal
        (the records it folds in are no longer needed for recovery).  The
        caller must pass a ``state`` consistent with every record appended
        so far — hold the state lock across build-and-snapshot."""
        doc = json.dumps({"schema": SCHEMA, "seq": self._seq, "state": state},
                         separators=(",", ":"), default=str)
        tmp = self.path + SNAPSHOT_SUFFIX + ".tmp"
        with self._lock:
            if self._closed:
                raise ValueError("journal is closed")
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                os.write(fd, doc.encode("utf-8"))
                os.fsync(fd)
            finally:
                os.close(fd)
            # replace-then-truncate: a crash in between leaves records the
            # snapshot already folded in — replay's seq filter skips them
            os.replace(tmp, self.path + SNAPSHOT_SUFFIX)
            os.ftruncate(self._fd, 0)
            os.fsync(self._fd)
            self._since_snapshot = 0

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                os.close(self._fd)

    @property
    def closed(self) -> bool:
        return self._closed


def replay(path: str) -> tuple[dict | None, list[dict]]:
    """Read back ``(snapshot_state_or_None, tail_records)`` for recovery.

    Deterministic: two replays of the same files return identical results.
    Records the snapshot already folded in (``n <= snapshot seq``) are
    skipped; a torn final line is dropped with a warning; any other
    corruption raises.
    """
    snap_state: dict | None = None
    snap_seq = 0
    snap_path = str(path) + SNAPSHOT_SUFFIX
    if os.path.exists(snap_path):
        with open(snap_path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != SCHEMA:
            raise ValueError(f"unknown journal snapshot schema in {snap_path}: "
                             f"{doc.get('schema')!r}")
        snap_state = doc.get("state") or {}
        snap_seq = int(doc.get("seq") or 0)
    records: list[dict] = []
    if os.path.exists(path):
        with open(path, "rb") as f:
            lines = f.read().split(b"\n")
        last_payload = max((i for i, raw in enumerate(lines) if raw.strip()),
                           default=-1)
        for i, raw in enumerate(lines):
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw.decode("utf-8"))
            except ValueError:
                if i == last_payload:
                    # torn tail: the crash landed mid-append; the record was
                    # never acknowledged, dropping it is the correct outcome
                    logger.warning("dropping torn final journal record in %s",
                                   path)
                    break
                raise ValueError(
                    f"corrupt journal record at {path} line {i + 1}") from None
            if int(rec.get("n") or 0) <= snap_seq:
                continue  # already folded into the snapshot
            records.append(rec)
    return snap_state, records
