"""Deterministic, env-gated fault injection for the recovery paths.

The elastic-recovery layer (``supervisor.py``, the partition ledger in
``cluster.py``, incarnation fencing in ``coordinator.py``) is only trustworthy
if every recovery path runs in fast tier-1 tests — not just in soak runs that
happen to hit a flake.  This module plants three chaos hooks at the exact
seams a real failure would hit, all disabled unless ``TOS_FAULTINJECT`` is
set (typically via ``per_node_env``, so one node of a test cluster misbehaves
deterministically while its peers stay healthy):

- ``kill`` — SIGKILL this node after its map_fun consumed N feed batches
  (hook: ``feeding.DataFeed.next_batch``).  Models an OOM kill / preemption
  mid-epoch: no deregister, no error report, just silence.
- ``drop_heartbeats`` — swallow the first K liveness pings (hook: the
  heartbeat loop in ``node.py``).  Models a network partition: the process
  lives on as a *zombie* the coordinator has declared dead, which is exactly
  what incarnation fencing exists for.
- ``sever`` — abruptly close the node's data-plane connection on the M-th
  data-carrying op (hook: ``dataserver.DataServer``).  Models a mid-partition
  socket loss with the node still healthy; the driver must requeue and refeed.
- ``kill_collective`` — SIGKILL this node inside its N-th collective
  all-reduce, after the first chunk exchange (hook: ``collective/ops.py``).
  Models a preemption mid-gradient-exchange: partial chunks in flight,
  peers blocked in the same round — survivors must abort at the generation
  barrier and the restart must rejoin (``collective/group.py``).
- ``kill_coordinator`` — crash the control-plane server on its N-th
  dispatched op (hook: ``coordinator.CoordinatorServer._dispatch``): every
  connection severed, in-memory state wiped, the request in flight never
  answered.  The journaled-recovery path (``journal.py`` + the coordinator
  supervisor) must replay and resume under a bumped epoch.  Armed in the
  DRIVER process (the coordinator lives there).
- ``delay_net:ms=M`` — network degradation: injects M milliseconds of
  latency on every control-plane send (``coordinator._send_msg``) and every
  data-carrying server op (``dataserver``) in the armed process, for as
  long as the process lives.
- ``flap:period=S`` — periodic network flapping: during every ODD
  S-second window since arming, this node's liveness pings are swallowed
  (zombie phase) and its first data-carrying op of the window severs the
  connection; even windows are healthy (re-admit phase).  Wall-clock
  driven by design — the action models link flap, not a counted event.
- ``stall_collective:after_rounds=N[,secs=S]`` — the GRAY failure: inside
  its N-th collective all-reduce (same seam as ``kill_collective``) the
  process goes silent for S seconds (default 300) and then resumes —
  alive the whole time, heartbeating, just not moving gradient bytes.
  Models a long GC pause / stolen core / wedged NIC queue: the case
  straggler detection + quorum eviction exist for (survivors must evict
  and continue at W-1 instead of thrashing on the collective timeout).
- ``slow_peer:ms=M`` — degraded-NIC gray fault: injects M milliseconds of
  latency on every collective PEER-PLANE send (``collective/transport``)
  in the armed process, for as long as it lives.  Armed on every node it
  models uniform slowness — the false-positive case eviction must never
  fire on; armed on one it models the persistent outlier.
- ``bad_model:nan=1,ms=M`` — model regression on the CANDIDATE bundle:
  while this serving replica is serving a rollout candidate (never the
  boot/primary bundle — the hook carries that bit), every batch's outputs
  are corrupted to NaN (``nan=1``) and/or delayed M milliseconds (hook:
  ``serving/loop.py``).  Models a bad export mid-canary: the rollout
  governor must detect the divergence/latency and auto-roll-back with
  zero failed primary requests.
- ``hot_tenant:mult=K,tenant=T`` — driver-side overload amplifier: every
  admission-time token-bucket charge for tenant T (all tenants when
  ``tenant`` is omitted) is multiplied by K (hook:
  ``serving/tenancy.py``), so a modest real load presents as K× the
  tenant's rate limit.  Models one tenant flooding: only T may see
  throttled replies while other tenants keep their p99.

Spec grammar (``TOS_FAULTINJECT``): semicolon-separated actions, each
``name:key=value,key=value`` —

    TOS_FAULTINJECT="kill:after_batches=3,incarnation=0"
    TOS_FAULTINJECT="drop_heartbeats:count=8;sever:after_data_ops=2"
    TOS_FAULTINJECT="kill_coordinator:after_ops=40"
    TOS_FAULTINJECT="delay_net:ms=5;flap:period=2"
    TOS_FAULTINJECT="stall_collective:after_rounds=3,secs=8,executor=1"
    TOS_FAULTINJECT="slow_peer:ms=25"

Common keys: ``executor=E`` fires only on that executor id (ids are assigned
at registration, so per-node targeting usually rides ``per_node_env``
instead); ``role=R`` fires only in processes whose ASSIGNED cluster role
matches (``role=ingest`` targets the data-service tier from a cluster-wide
spec — roles are registration-order, so per-launch-index env cannot);
``incarnation=I`` fires only at that node incarnation — the idiom
for "die once": a restarted node re-parses the same env but its incarnation
moved on, so the fault stays disarmed.  Counters are plain in-process
counts — same schedule every run.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time

logger = logging.getLogger(__name__)

ENV_VAR = "TOS_FAULTINJECT"


class FaultInjected(Exception):
    """Raised by hooks that simulate infrastructure faults (e.g. ``sever``);
    handlers treat it as the fault itself, never as a handler bug."""


class _Action:
    __slots__ = ("name", "threshold", "executor", "incarnation", "role",
                 "extra", "fired", "count", "hb_cycle", "sever_cycle")

    def __init__(self, name: str, threshold: int,
                 executor: int | None, incarnation: int | None,
                 role: str | None = None, extra: dict | None = None):
        self.name = name
        self.threshold = threshold
        self.executor = executor
        self.incarnation = incarnation
        self.role = role
        # secondary action parameters (e.g. stall_collective's `secs=`)
        self.extra = extra or {}
        self.fired = False
        self.count = 0
        # flap bookkeeping: last down-window index counted / severed, so
        # each odd window is metered once and severs exactly one connection
        self.hb_cycle = -1
        self.sever_cycle = -1


class FaultPlan:
    """Parsed ``TOS_FAULTINJECT`` spec with deterministic counters."""

    _KEYS = {"kill": "after_batches",
             "drop_heartbeats": "count",
             "sever": "after_data_ops",
             # SIGKILL mid-collective: fires inside the Nth all-reduce, after
             # the first chunk exchange (ops.py), so partial gradient chunks
             # are genuinely in flight when the process dies — the round the
             # generation-barrier rejoin must fence and survive
             "kill_collective": "after_rounds",
             # crash the control-plane server on its Nth dispatched op
             # (coordinator._dispatch) — the journaled-recovery chaos clock
             "kill_coordinator": "after_ops",
             # gray failure: go silent for `secs` inside the Nth all-reduce
             # (same seam as kill_collective) — alive, heartbeating, not
             # moving bytes; straggler detection must evict, not thrash
             "stall_collective": "after_rounds",
             # continuous network degradation: the "threshold" is the
             # parameter (ms of latency / seconds of flap period), not a
             # count — see _CONTINUOUS
             "delay_net": "ms",
             "slow_peer": "ms",
             "flap": "period",
             # candidate-bundle regression: nan=1 corrupts outputs, the
             # ms= extra inflates latency — fires only while the serving
             # replica is on a rollout CANDIDATE bundle (see serving/loop)
             "bad_model": "nan",
             # driver-side tenant-flood amplifier: every token-bucket
             # charge for the targeted tenant is multiplied by `mult`
             "hot_tenant": "mult"}
    # optional secondary keys per action (float-valued)
    _EXTRA_KEYS = {"stall_collective": frozenset({"secs"}),
                   "bad_model": frozenset({"ms"})}
    # optional string-valued keys per action (never int-coerced)
    _STR_KEYS = {"hot_tenant": frozenset({"tenant"})}
    # one-shot actions fire once when the counter REACHES the threshold;
    # windowed actions fire on EVERY call until the threshold is spent
    # (drop_heartbeats swallows the first K pings — one dropped ping would
    # never outlast the driver's dead-node timeout)
    _WINDOWED = frozenset({"drop_heartbeats"})
    # continuous actions never "fire and disarm": they degrade the process
    # for its whole life (delay_net / slow_peer / bad_model / hot_tenant)
    # or on a periodic schedule (flap)
    _CONTINUOUS = frozenset({"delay_net", "slow_peer", "flap", "bad_model",
                             "hot_tenant"})

    def __init__(self, actions: list[_Action]):
        self._lock = tos_named_lock("faultinject._lock")
        self._actions = actions
        self._executor_id: int | None = None
        self._incarnation = 0
        self._role = ""
        self._t0 = time.monotonic()  # flap phase anchor (arming time)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        actions: list[_Action] = []
        for chunk in spec.split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            name, _, rest = chunk.partition(":")
            name = name.strip()
            if name not in cls._KEYS:
                raise ValueError(
                    f"unknown fault action {name!r} in {spec!r} "
                    f"(known actions: {', '.join(sorted(cls._KEYS))})")
            kv = {}
            role: str | None = None
            for pair in filter(None, (p.strip() for p in rest.split(","))):
                k, _, v = pair.partition("=")
                k = k.strip()
                if k == "role":
                    # role filter (string-valued): fire only in processes
                    # whose ASSIGNED cluster role matches — the idiom for
                    # targeting the data-service tier, whose role is
                    # registration-order and so cannot ride per_node_env
                    role = v.strip()
                    continue
                # secondary parameters (e.g. stall secs) may be fractional
                # and a few (e.g. hot_tenant's tenant=) are strings;
                # thresholds/filters stay integral
                if k in cls._STR_KEYS.get(name, frozenset()):
                    kv[k] = v.strip()
                else:
                    kv[k] = (float(v)
                             if k in cls._EXTRA_KEYS.get(name, frozenset())
                             else int(v))
            threshold = kv.pop(cls._KEYS[name], 1)
            executor = kv.pop("executor", None)
            incarnation = kv.pop("incarnation", None)
            extra = {k: kv.pop(k) for k in list(kv)
                     if k in (cls._EXTRA_KEYS.get(name, frozenset())
                              | cls._STR_KEYS.get(name, frozenset()))}
            if kv:
                raise ValueError(f"unknown keys {sorted(kv)} for fault {name!r}")
            actions.append(_Action(name, threshold, executor, incarnation,
                                   role, extra))
        return cls(actions)

    def set_identity(self, executor_id: int, incarnation: int = 0,
                     role: str = "") -> None:
        with self._lock:
            self._executor_id = executor_id
            self._incarnation = incarnation
            self._role = role

    def _tick(self, name: str) -> "_Action | None":
        """Advance the named action's counter; the fired action (truthy)
        when it fires this call, else None."""
        with self._lock:
            for a in self._actions:
                if a.name != name or a.fired:
                    continue
                if a.executor is not None and a.executor != self._executor_id:
                    continue
                if a.incarnation is not None and a.incarnation != self._incarnation:
                    continue
                if a.role is not None and a.role != self._role:
                    continue
                a.count += 1
                if a.name in self._WINDOWED:
                    if a.count >= a.threshold:
                        a.fired = True
                    self._count_injection(name)
                    return a
                if a.count >= a.threshold:
                    a.fired = True
                    self._count_injection(name)
                    return a
        return None

    def _armed(self, name: str) -> _Action | None:
        """The identity-matched action of a CONTINUOUS kind, else None."""
        with self._lock:
            for a in self._actions:
                if a.name != name:
                    continue
                if a.executor is not None and a.executor != self._executor_id:
                    continue
                if a.incarnation is not None and a.incarnation != self._incarnation:
                    continue
                if a.role is not None and a.role != self._role:
                    continue
                return a
        return None

    def delay_ms(self, name: str = "delay_net") -> int:
        """Injected per-send latency (``delay_net:ms=M`` on the control/data
        planes, ``slow_peer:ms=M`` on the collective peer plane), 0 when
        unarmed.  Metered once at first delay (flight event) and per
        delayed send (``faultinject.delayed_sends`` counter) — the caller
        sleeps."""
        a = self._armed(name)
        if a is None:
            return 0
        with self._lock:
            first = not a.fired
            a.fired = True
            a.count += 1
        if first:
            self._count_injection(name)
        return a.threshold

    def stall_secs(self) -> float:
        """Seconds the ``stall_collective`` gray fault wants this process to
        go silent for, when its round counter fires NOW; 0.0 otherwise."""
        a = self._tick("stall_collective")
        if a is None:
            return 0.0
        return float(a.extra.get("secs", 300))

    def _flap_window(self, a: _Action) -> tuple[int, bool]:
        """(window index since arming, is this a DOWN window)."""
        period = max(1, a.threshold)
        cycle = int((time.monotonic() - self._t0) // period)
        return cycle, cycle % 2 == 1

    def flap_down(self) -> bool:
        """True while inside a flap DOWN window (liveness pings swallowed);
        each down window is metered once."""
        a = self._armed("flap")
        if a is None:
            return False
        cycle, down = self._flap_window(a)
        if down:
            with self._lock:
                count = a.hb_cycle != cycle
                a.hb_cycle = cycle
            if count:
                self._count_injection("flap")
        return down

    def flap_sever(self) -> bool:
        """True exactly once per flap DOWN window on the data plane: the
        window's first data-carrying op severs its connection; the rest of
        the window (and every even window) passes — the re-admit phase."""
        a = self._armed("flap")
        if a is None:
            return False
        cycle, down = self._flap_window(a)
        if not down:
            return False
        with self._lock:
            if a.sever_cycle == cycle:
                return False
            a.sever_cycle = cycle
        return True

    @staticmethod
    def _count_injection(name: str) -> None:
        """Meter the fired fault (telemetry): chaos tests assert recovery
        counters against these, and a soak run's report shows how many
        faults it actually exercised.  A ``kill`` SIGKILLs before the next
        heartbeat can ship the count — that loss is the fault's own point
        (which is exactly why the flight recorder dumps to DISK before a
        kill: see ``batch_consumed``)."""
        from tensorflowonspark_tpu import telemetry
        from tensorflowonspark_tpu.telemetry import trace as ttrace

        telemetry.counter("faultinject.injected_total").inc()
        telemetry.counter(f"faultinject.injected.{name}").inc()
        ttrace.event("fault", action=name, pid=os.getpid())


_PLAN: FaultPlan | None = None
# Flight-recorder postmortem path (node_main sets it from the cluster's
# log_dir): a `kill` dumps the process's recent spans + events here in the
# instant before SIGKILL — the ONE artifact a kill cannot destroy, since
# SIGKILL forecloses every in-memory channel (heartbeats, deregister).
_FLIGHT_DUMP_PATH: str | None = None
_FLIGHT_DUMP_NODE: str = ""


def set_flight_dump(path: str | None, node: str = "") -> None:
    """Where (and as whom) this process should dump its flight recorder if
    a ``kill`` fault fires."""
    global _FLIGHT_DUMP_PATH, _FLIGHT_DUMP_NODE
    _FLIGHT_DUMP_PATH = path
    _FLIGHT_DUMP_NODE = node


def init_from_env(force: bool = False) -> None:
    """Parse ``TOS_FAULTINJECT`` (call after per-node env is applied)."""
    global _PLAN
    if _PLAN is not None and not force:
        return
    from tensorflowonspark_tpu.utils.envtune import env_str

    spec = env_str("TOS_FAULTINJECT", "")
    if not spec:
        _PLAN = None
        return
    _PLAN = FaultPlan.parse(spec)
    logger.warning("fault injection armed: %s=%r", ENV_VAR, spec)


def set_identity(executor_id: int, incarnation: int = 0,
                 role: str = "") -> None:
    if _PLAN is not None:
        _PLAN.set_identity(executor_id, incarnation, role=role)


def _sigkill_self() -> None:
    """SIGKILL this process — the most brutal death available: no atexit,
    no deregister, no flush, exactly what a preempted VM looks like.  The
    one concession: the flight recorder dumps to disk first (a real
    preemption grants no such grace, but the dump is the postmortem
    artifact the chaos tests and operators read — and it costs
    microseconds)."""
    logger.warning("fault injection: SIGKILL self (pid %d)", os.getpid())
    if _FLIGHT_DUMP_PATH:
        try:
            from tensorflowonspark_tpu.telemetry import trace as ttrace

            ttrace.dump_flight(_FLIGHT_DUMP_PATH, node=_FLIGHT_DUMP_NODE)
        except Exception:  # noqa: BLE001 - the kill must still fire
            logger.warning("flight dump before kill failed", exc_info=True)
    os.kill(os.getpid(), signal.SIGKILL)


def batch_consumed() -> None:
    """Hook: one feed batch fully consumed by the map_fun; ``kill`` fires
    here with SIGKILL (see :func:`_sigkill_self`)."""
    if _PLAN is not None and _PLAN._tick("kill"):
        _sigkill_self()


def collective_round() -> None:
    """Hook: mid-collective — called once per all-reduce, after the first
    chunk exchange (``collective/ops.py``); ``kill_collective`` SIGKILLs
    here, dying with partial chunks on the wire and peers blocked in the
    same round (the poisoned-round case incarnation fencing + the
    generation barrier exist for).  ``stall_collective`` fires at the same
    seam but SLEEPS instead of dying — the gray failure: partial chunks in
    flight, heartbeats still flowing, peers blocked on a member that is
    slow, not dead (the case quorum eviction exists for)."""
    if _PLAN is None:
        return
    if _PLAN._tick("kill_collective"):
        _sigkill_self()
    secs = _PLAN.stall_secs()
    if secs > 0:
        logger.warning("fault injection: stalling collective for %.1fs "
                       "(gray failure; pid %d)", secs, os.getpid())
        time.sleep(secs)
        logger.warning("fault injection: collective stall over (pid %d)",
                       os.getpid())


def peer_send_delay() -> None:
    """Hook: about to ship a chunk frame on the collective peer plane
    (``collective/transport.PeerTransport.send``); ``slow_peer:ms=M``
    sleeps M milliseconds here — the degraded-NIC gray fault."""
    if _PLAN is None:
        return
    ms = _PLAN.delay_ms("slow_peer")
    if ms:
        from tensorflowonspark_tpu import telemetry

        telemetry.counter("faultinject.delayed_sends").inc()
        time.sleep(ms / 1000.0)


def drop_heartbeat() -> bool:
    """Hook: about to send a liveness ping; True = swallow it (the counted
    ``drop_heartbeats`` action, or a ``flap`` DOWN window)."""
    if _PLAN is None:
        return False
    return bool(_PLAN._tick("drop_heartbeats")) or _PLAN.flap_down()


def data_op() -> None:
    """Hook: a data-carrying op (feed / infer_send) reached the node's data
    server; ``sever`` (or the first op of a ``flap`` DOWN window) raises so
    the connection closes with no reply."""
    if _PLAN is None:
        return
    if _PLAN._tick("sever"):
        raise FaultInjected("severing data-plane connection (TOS_FAULTINJECT)")
    if _PLAN.flap_sever():
        raise FaultInjected("flap window severing data-plane connection "
                            "(TOS_FAULTINJECT)")


def coordinator_op() -> bool:
    """Hook: a control-plane request reached the coordinator's dispatcher;
    True = ``kill_coordinator`` fires now (the server crash()es itself —
    the journaled-recovery path owns what happens next)."""
    return _PLAN is not None and bool(_PLAN._tick("kill_coordinator"))


def bad_model(candidate: bool) -> tuple[bool, float]:
    """Hook: one serving micro-batch is about to be answered
    (``serving/loop.py``); returns ``(corrupt_outputs, extra_latency_secs)``.
    Fires only while the replica serves a rollout CANDIDATE bundle
    (``candidate`` — the reload control item carried the bit), so the
    injected regression models a bad export, never a bad fleet: primary
    replicas keep answering correctly while the canary cohort degrades."""
    if _PLAN is None or not candidate:
        return False, 0.0
    a = _PLAN._armed("bad_model")
    if a is None:
        return False, 0.0
    with _PLAN._lock:
        first = not a.fired
        a.fired = True
        a.count += 1
    if first:
        _PLAN._count_injection("bad_model")
    return bool(a.threshold), float(a.extra.get("ms", 0.0)) / 1e3


def tenant_charge_mult(tenant: str) -> int:
    """Hook: the serving admission path is about to charge ``tenant``'s
    token bucket (``serving/tenancy.py``); returns the charge multiplier
    (1 = unarmed).  ``hot_tenant:mult=K,tenant=T`` makes tenant T's real
    load present as K× its rate budget — the deterministic stand-in for a
    flooding client."""
    if _PLAN is None:
        return 1
    a = _PLAN._armed("hot_tenant")
    if a is None:
        return 1
    target = a.extra.get("tenant", "")
    if target and target != tenant:
        return 1
    with _PLAN._lock:
        first = not a.fired
        a.fired = True
        a.count += 1
    if first:
        _PLAN._count_injection("hot_tenant")
    return max(1, a.threshold)


def net_delay() -> None:
    """Hook: about to send on the control plane (or serve a data op);
    ``delay_net:ms=M`` sleeps M milliseconds here — injected wire latency
    for the armed process."""
    if _PLAN is None:
        return
    ms = _PLAN.delay_ms()
    if ms:
        from tensorflowonspark_tpu import telemetry

        telemetry.counter("faultinject.delayed_sends").inc()
        time.sleep(ms / 1000.0)
