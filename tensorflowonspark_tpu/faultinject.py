"""Deterministic, env-gated fault injection for the recovery paths.

The elastic-recovery layer (``supervisor.py``, the partition ledger in
``cluster.py``, incarnation fencing in ``coordinator.py``) is only trustworthy
if every recovery path runs in fast tier-1 tests — not just in soak runs that
happen to hit a flake.  This module plants three chaos hooks at the exact
seams a real failure would hit, all disabled unless ``TOS_FAULTINJECT`` is
set (typically via ``per_node_env``, so one node of a test cluster misbehaves
deterministically while its peers stay healthy):

- ``kill`` — SIGKILL this node after its map_fun consumed N feed batches
  (hook: ``feeding.DataFeed.next_batch``).  Models an OOM kill / preemption
  mid-epoch: no deregister, no error report, just silence.
- ``drop_heartbeats`` — swallow the first K liveness pings (hook: the
  heartbeat loop in ``node.py``).  Models a network partition: the process
  lives on as a *zombie* the coordinator has declared dead, which is exactly
  what incarnation fencing exists for.
- ``sever`` — abruptly close the node's data-plane connection on the M-th
  data-carrying op (hook: ``dataserver.DataServer``).  Models a mid-partition
  socket loss with the node still healthy; the driver must requeue and refeed.
- ``kill_collective`` — SIGKILL this node inside its N-th collective
  all-reduce, after the first chunk exchange (hook: ``collective/ops.py``).
  Models a preemption mid-gradient-exchange: partial chunks in flight,
  peers blocked in the same round — survivors must abort at the generation
  barrier and the restart must rejoin (``collective/group.py``).

Spec grammar (``TOS_FAULTINJECT``): semicolon-separated actions, each
``name:key=value,key=value`` —

    TOS_FAULTINJECT="kill:after_batches=3,incarnation=0"
    TOS_FAULTINJECT="drop_heartbeats:count=8;sever:after_data_ops=2"

Common keys: ``executor=E`` fires only on that executor id (ids are assigned
at registration, so per-node targeting usually rides ``per_node_env``
instead); ``incarnation=I`` fires only at that node incarnation — the idiom
for "die once": a restarted node re-parses the same env but its incarnation
moved on, so the fault stays disarmed.  Counters are plain in-process
counts — same schedule every run.
"""

from __future__ import annotations

import logging
import os
import signal
import threading

logger = logging.getLogger(__name__)

ENV_VAR = "TOS_FAULTINJECT"


class FaultInjected(Exception):
    """Raised by hooks that simulate infrastructure faults (e.g. ``sever``);
    handlers treat it as the fault itself, never as a handler bug."""


class _Action:
    __slots__ = ("name", "threshold", "executor", "incarnation", "fired", "count")

    def __init__(self, name: str, threshold: int,
                 executor: int | None, incarnation: int | None):
        self.name = name
        self.threshold = threshold
        self.executor = executor
        self.incarnation = incarnation
        self.fired = False
        self.count = 0


class FaultPlan:
    """Parsed ``TOS_FAULTINJECT`` spec with deterministic counters."""

    _KEYS = {"kill": "after_batches",
             "drop_heartbeats": "count",
             "sever": "after_data_ops",
             # SIGKILL mid-collective: fires inside the Nth all-reduce, after
             # the first chunk exchange (ops.py), so partial gradient chunks
             # are genuinely in flight when the process dies — the round the
             # generation-barrier rejoin must fence and survive
             "kill_collective": "after_rounds"}
    # one-shot actions fire once when the counter REACHES the threshold;
    # windowed actions fire on EVERY call until the threshold is spent
    # (drop_heartbeats swallows the first K pings — one dropped ping would
    # never outlast the driver's dead-node timeout)
    _WINDOWED = frozenset({"drop_heartbeats"})

    def __init__(self, actions: list[_Action]):
        self._lock = threading.Lock()
        self._actions = actions
        self._executor_id: int | None = None
        self._incarnation = 0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        actions: list[_Action] = []
        for chunk in spec.split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            name, _, rest = chunk.partition(":")
            name = name.strip()
            if name not in cls._KEYS:
                raise ValueError(f"unknown fault action {name!r} in {spec!r}")
            kv = {}
            for pair in filter(None, (p.strip() for p in rest.split(","))):
                k, _, v = pair.partition("=")
                kv[k.strip()] = int(v)
            threshold = kv.pop(cls._KEYS[name], 1)
            executor = kv.pop("executor", None)
            incarnation = kv.pop("incarnation", None)
            if kv:
                raise ValueError(f"unknown keys {sorted(kv)} for fault {name!r}")
            actions.append(_Action(name, threshold, executor, incarnation))
        return cls(actions)

    def set_identity(self, executor_id: int, incarnation: int = 0) -> None:
        with self._lock:
            self._executor_id = executor_id
            self._incarnation = incarnation

    def _tick(self, name: str) -> bool:
        """Advance the named action's counter; True when it fires this call."""
        with self._lock:
            for a in self._actions:
                if a.name != name or a.fired:
                    continue
                if a.executor is not None and a.executor != self._executor_id:
                    continue
                if a.incarnation is not None and a.incarnation != self._incarnation:
                    continue
                a.count += 1
                if a.name in self._WINDOWED:
                    if a.count >= a.threshold:
                        a.fired = True
                    self._count_injection(name)
                    return True
                if a.count >= a.threshold:
                    a.fired = True
                    self._count_injection(name)
                    return True
        return False

    @staticmethod
    def _count_injection(name: str) -> None:
        """Meter the fired fault (telemetry): chaos tests assert recovery
        counters against these, and a soak run's report shows how many
        faults it actually exercised.  A ``kill`` SIGKILLs before the next
        heartbeat can ship the count — that loss is the fault's own point
        (which is exactly why the flight recorder dumps to DISK before a
        kill: see ``batch_consumed``)."""
        from tensorflowonspark_tpu import telemetry
        from tensorflowonspark_tpu.telemetry import trace as ttrace

        telemetry.counter("faultinject.injected_total").inc()
        telemetry.counter(f"faultinject.injected.{name}").inc()
        ttrace.event("fault", action=name, pid=os.getpid())


_PLAN: FaultPlan | None = None
# Flight-recorder postmortem path (node_main sets it from the cluster's
# log_dir): a `kill` dumps the process's recent spans + events here in the
# instant before SIGKILL — the ONE artifact a kill cannot destroy, since
# SIGKILL forecloses every in-memory channel (heartbeats, deregister).
_FLIGHT_DUMP_PATH: str | None = None
_FLIGHT_DUMP_NODE: str = ""


def set_flight_dump(path: str | None, node: str = "") -> None:
    """Where (and as whom) this process should dump its flight recorder if
    a ``kill`` fault fires."""
    global _FLIGHT_DUMP_PATH, _FLIGHT_DUMP_NODE
    _FLIGHT_DUMP_PATH = path
    _FLIGHT_DUMP_NODE = node


def init_from_env(force: bool = False) -> None:
    """Parse ``TOS_FAULTINJECT`` (call after per-node env is applied)."""
    global _PLAN
    if _PLAN is not None and not force:
        return
    from tensorflowonspark_tpu.utils.envtune import env_str

    spec = env_str("TOS_FAULTINJECT", "")
    if not spec:
        _PLAN = None
        return
    _PLAN = FaultPlan.parse(spec)
    logger.warning("fault injection armed: %s=%r", ENV_VAR, spec)


def set_identity(executor_id: int, incarnation: int = 0) -> None:
    if _PLAN is not None:
        _PLAN.set_identity(executor_id, incarnation)


def _sigkill_self() -> None:
    """SIGKILL this process — the most brutal death available: no atexit,
    no deregister, no flush, exactly what a preempted VM looks like.  The
    one concession: the flight recorder dumps to disk first (a real
    preemption grants no such grace, but the dump is the postmortem
    artifact the chaos tests and operators read — and it costs
    microseconds)."""
    logger.warning("fault injection: SIGKILL self (pid %d)", os.getpid())
    if _FLIGHT_DUMP_PATH:
        try:
            from tensorflowonspark_tpu.telemetry import trace as ttrace

            ttrace.dump_flight(_FLIGHT_DUMP_PATH, node=_FLIGHT_DUMP_NODE)
        except Exception:  # noqa: BLE001 - the kill must still fire
            logger.warning("flight dump before kill failed", exc_info=True)
    os.kill(os.getpid(), signal.SIGKILL)


def batch_consumed() -> None:
    """Hook: one feed batch fully consumed by the map_fun; ``kill`` fires
    here with SIGKILL (see :func:`_sigkill_self`)."""
    if _PLAN is not None and _PLAN._tick("kill"):
        _sigkill_self()


def collective_round() -> None:
    """Hook: mid-collective — called once per all-reduce, after the first
    chunk exchange (``collective/ops.py``); ``kill_collective`` SIGKILLs
    here, dying with partial chunks on the wire and peers blocked in the
    same round (the poisoned-round case incarnation fencing + the
    generation barrier exist for)."""
    if _PLAN is not None and _PLAN._tick("kill_collective"):
        _sigkill_self()


def drop_heartbeat() -> bool:
    """Hook: about to send a liveness ping; True = swallow it."""
    return _PLAN is not None and _PLAN._tick("drop_heartbeats")


def data_op() -> None:
    """Hook: a data-carrying op (feed / infer_send) reached the node's data
    server; ``sever`` raises so the connection closes with no reply."""
    if _PLAN is not None and _PLAN._tick("sever"):
        raise FaultInjected("severing data-plane connection (TOS_FAULTINJECT)")
