"""Per-host node runtime — the ``TFSparkNode`` replacement.

Reference (``tensorflowonspark/TFSparkNode.py:~140-420``): a Spark task on
each executor derives its executor id, allocates GPUs into
``CUDA_VISIBLE_DEVICES``, starts TFManager queues, registers with the
reservation server, writes ``TF_CONFIG``, optionally spawns TensorBoard, then
invokes the user ``map_fun(args, ctx)``.

TPU-native redesign (BASELINE.json:5, SURVEY.md §7.1-3):
- the coordinator *assigns* ``executor_id``/role at registration (race-free,
  replacing partition-id derivation and ``gpu_info.py`` GPU-pick retries);
- instead of ``CUDA_VISIBLE_DEVICES`` the node receives **mesh coordinates**:
  its process index and the global device mesh layout; accelerator visibility
  is whatever JAX exposes on this host (TPU chips are per-host hardware, not
  a shared pool to race over);
- instead of ``TF_CONFIG`` + ``tf.train.Server``, multi-host XLA is set up
  via ``jax.distributed.initialize`` (SPMD over ICI/DCN) when
  ``jax_distributed`` is enabled;
- ``map_fun`` runs in the node process's main thread — there is no Spark task
  slot to give back, so the reference's background-process fork
  (``TFSparkNode.py:~300-420``) and its cross-process manager queues are
  unnecessary.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
import sys
import threading
import time
import traceback
from typing import Any, Callable, Sequence

from tensorflowonspark_tpu.coordinator import CoordinatorClient
from tensorflowonspark_tpu.dataserver import DataServer
from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues
from tensorflowonspark_tpu.marker import EndOfFeed
from tensorflowonspark_tpu.utils import paths as _paths
from tensorflowonspark_tpu.utils.net import local_ip

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeConfig:
    """Everything a node process needs to join the cluster."""

    coordinator_addr: tuple[str, int]
    authkey: bytes
    map_fun: Callable[[Any, "NodeContext"], Any]
    tf_args: Any = None
    queues: Sequence[str] = ("input", "output", "error")
    input_qnames: Sequence[str] = ("input",)
    # "streaming" (driver streams rows) or "direct" (the feed carries shard
    # PATHS and ctx.get_data_feed returns the node-side ingest pipeline).
    input_mode: str = "streaming"
    queue_capacity: int = 1024
    feed_timeout: float = 600.0
    reservation_timeout: float = 120.0
    default_fs: str = ""
    working_dir: str = ""
    log_dir: str = ""
    tensorboard: bool = False
    jax_distributed: bool = False
    heartbeat_interval: float = 2.0
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    # Position in the launcher's process list; registered back to the
    # coordinator so the driver can map executor_id -> process handle
    # (pids don't work for that: over ssh transports the local handle's pid
    # is the ssh client, not the remote node).
    launch_index: int = -1
    # >= 0: this process is a supervised RESTART re-registering into the
    # named (dead) executor slot; it adopts the slot's bumped incarnation,
    # fencing out its predecessor (supervisor.py).
    replace_executor_id: int = -1
    # Decode options for the data-service tier (cluster.run(ingest_opts=...)):
    # keyword args for ingest.service.IngestService — schema=, chunk_records=,
    # readers=, cache_bytes=, shuffle=, ...  Only read by processes the
    # coordinator assigns the "ingest" role (role-aware dispatch below);
    # carried on EVERY config because role assignment is registration-order,
    # so any launched process may become an ingest worker.
    ingest_opts: dict | None = None


class NodeContext:
    """The ``ctx`` handed to user ``map_fun`` (reference ``TFNodeContext``,
    ``TFSparkNode.py:~27-60``), extended with TPU mesh facilities."""

    def __init__(
        self,
        executor_id: int,
        job_name: str,
        task_index: int,
        num_executors: int,
        cluster_info: list[dict],
        queues: FeedQueues,
        config: NodeConfig,
        client: CoordinatorClient,
        stop_event: threading.Event | None = None,
        incarnation: int = 0,
    ):
        self.executor_id = executor_id
        self.job_name = job_name
        self.task_index = task_index
        # 0 for a first-launch node; a supervised restart adopts its slot's
        # bumped generation (map_funs can key restart-only behaviour on it,
        # e.g. "resume from the latest checkpoint").
        self.incarnation = incarnation
        self.num_executors = num_executors
        self.cluster_info = cluster_info
        self.queues = queues
        self.default_fs = config.default_fs
        self.working_dir = config.working_dir or os.getcwd()
        self.log_dir = config.log_dir
        self.tf_args = config.tf_args
        self._config = config
        self._client = client
        self._cons_client = None
        self._cons_pending = False
        # shared with the heartbeat thread, which starts before this context
        # exists (liveness must not wait for jax init / first compiles)
        self.stop_requested = stop_event if stop_event is not None else threading.Event()

    @property
    def is_restart(self) -> bool:
        """True when this node is a supervised restart of a dead predecessor
        — the cue to resume from the latest checkpoint
        (``checkpoint.restore_for_restart``) before re-entering the feed."""
        return self.incarnation > 0

    # -- data plane ----------------------------------------------------------

    def get_data_feed(
        self,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict | None = None,
        **ingest_opts,
    ):
        """Reference: ``TFNode.DataFeed(ctx.mgr, ...)`` (``TFNode.py:~250``).

        The feed-source switch: on a STREAMING cluster this is the
        driver-streamed ``DataFeed``; on a DIRECT cluster the same call
        returns an :class:`~tensorflowonspark_tpu.ingest.IngestFeed` — the
        node-side reader pipeline over the shard paths the ledger assigns —
        so one map_fun body serves both input modes.  ``ingest_opts``
        (``decode=``, ``readers=``, ``verify=``, ...) configure the
        pipeline and are DIRECT-only; see :meth:`get_ingest_feed`.
        """
        if self._config.input_mode == "direct":
            return self.get_ingest_feed(
                train_mode=train_mode, qname_in=qname_in, qname_out=qname_out,
                input_mapping=input_mapping, **ingest_opts)
        if ingest_opts:
            raise TypeError(
                f"ingest options {sorted(ingest_opts)} need InputMode.DIRECT "
                "(alias TENSORFLOW); this cluster runs InputMode.STREAMING "
                "(alias SPARK), whose feed carries driver-streamed rows")
        return DataFeed(self.queues, train_mode, qname_in, qname_out, input_mapping,
                        stop_event=self.stop_requested)

    def get_ingest_feed(
        self,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict | None = None,
        readers: int | None = None,
        decode=None,
        chunk_records: int = 256,
        verify: bool = True,
        prefetch: int | None = None,
        autotune: bool | None = None,
        zerocopy=None,
        schema=None,
        binary_features=None,
    ):
        """DIRECT-mode feed: shard paths (or sub-shard spans) in, decoded
        record batches out.

        Records from plain shards are zero-copy ``memoryview`` slices by
        default (``zerocopy`` overrides ``TOS_INGEST_ZEROCOPY``; views are
        valid until their batch retires — see the ``IngestFeed`` decode
        contract).  ``decode`` runs per record inside the reader threads
        and ALWAYS receives ``bytes`` — the pre-existing contract (e.g.
        ``lambda rec: dfutil.from_example(rec, schema)``); ``None`` yields
        the raw payloads.  ``schema`` (a ``dfutil.Schema``)
        switches to COLUMNAR Example decode instead: batches arrive as
        ``{column: ndarray-view}`` dicts materialized from contiguous
        column buffers in the reader pool (mutually exclusive with
        ``decode``).  ``readers``/``prefetch``/``autotune`` override the
        ``TOS_INGEST_*`` knobs; ``verify=False`` skips CRC checks for
        trusted local data.
        """
        from tensorflowonspark_tpu.ingest import IngestFeed

        return IngestFeed(
            self.queues, train_mode, qname_in, qname_out, input_mapping,
            stop_event=self.stop_requested, readers=readers, decode=decode,
            chunk_records=chunk_records, verify=verify, prefetch=prefetch,
            autotune=autotune, zerocopy=zerocopy, schema=schema,
            binary_features=binary_features)

    def job_manifest(self) -> dict:
        """The driver-published description of the current DIRECT-mode feed
        (shard/partition/epoch counts — what ``cluster.train(path)``
        enumerated), for map_funs that want progress denominators.  Empty
        until a DIRECT train publishes one."""
        return self._client.manifest()

    # -- path plumbing -------------------------------------------------------

    def absolute_path(self, path: str) -> str:
        """Reference: ``TFNode.hdfs_path(ctx, path)`` (``TFNode.py:~30-70``)."""
        return _paths.absolute_path(path, self.default_fs, self.working_dir)

    # -- mesh / SPMD ---------------------------------------------------------

    def make_mesh(self, **axis_sizes: int):
        """Build a ``jax.sharding.Mesh`` over this process's visible devices.

        The TPU replacement for ``TFNode.start_cluster_server``
        (``TFNode.py:~80-150``): no server objects — just a named mesh that
        jit-compiled SPMD programs shard over (XLA collectives over ICI).
        """
        from tensorflowonspark_tpu.parallel.mesh import make_mesh

        return make_mesh(**axis_sizes)

    # -- global consensus (sync SPMD end-of-data, SURVEY.md §7.3-1) ----------

    @property
    def num_data_nodes(self) -> int:
        """Nodes that participate in the trainer data plane — everything but
        the evaluator sidecar and the data-service (ingest) tier, which
        never joins trainer consensus/collectives."""
        return sum(1 for m in self.cluster_info
                   if m["job_name"] not in ("evaluator", "ingest"))

    def all_done(self, done: bool, timeout: float = 300.0) -> bool:
        """Control-plane all-reduce: True only when *every* data node is done.

        Sync data-parallel training cannot let one host run out of data early
        (SURVEY.md §5.8-3); call this each epoch/partition boundary.  Scoped
        to data nodes — the evaluator never sees the feed and must not be
        counted, or the reduce would deadlock.
        """
        name = self._client.next_collective_name("all_done")
        return bool(self._client.reduce(name, bool(done), kind="all", timeout=timeout,
                                        count=self.num_data_nodes))

    def all_done_begin(self, done: bool, timeout: float = 300.0):
        """Pipelined ``all_done``: vote now, read the result later via the
        returned zero-arg callable.

        The per-step end-of-data consensus would otherwise cost one blocking
        control-plane RTT per global step (VERDICT r4 weak #2); with the
        pipelined form an *active* host votes, runs its training step while
        the rendezvous resolves, and reads the result at the top of the next
        round.  Votes MUST stay one-per-round on every host (same generation
        sequence as ``all_done`` — the two share a name counter, so hosts
        may mix sync and pipelined calls freely as long as each host makes
        exactly one per round).  Runs on a dedicated coordinator connection
        so a pending vote never blocks heartbeats/update_meta/barriers."""
        if self._cons_pending:
            # The previous pipelined vote was abandoned un-resolved (an
            # exception skipped its result() call): its reply is unread and
            # the connection lock is still held — drop the connection and
            # start fresh rather than self-deadlocking on acquire.  The
            # abandoned generation will surface as a peer-side timeout.
            self._reset_consensus_client()
        name = self._client.next_collective_name("all_done")
        finish = self._consensus_client().reduce_begin(
            name, bool(done), kind="all", timeout=timeout,
            count=self.num_data_nodes)
        self._cons_pending = True

        def result() -> bool:
            out = bool(finish())
            self._cons_pending = False
            return out

        return result

    def _consensus_client(self):
        """Lazy dedicated connection for the end-of-data consensus (its
        pipelined votes hold the client lock from begin to finish)."""
        if self._cons_client is None:
            self._cons_client = CoordinatorClient(self._config.coordinator_addr,
                                                  authkey=self._config.authkey)
            self._cons_client.set_identity(self.executor_id, self.incarnation)
        return self._cons_client

    def _reset_consensus_client(self) -> None:
        """Drop the consensus connection (e.g. a pipelined vote was
        abandoned mid-flight, leaving an unread reply on the socket)."""
        if self._cons_client is not None:
            try:
                self._cons_client._sock.close()
            except OSError:  # toslint: allow-silent(best-effort close of an already-abandoned socket)
                pass
            self._cons_client = None
        self._cons_pending = False

    # -- cross-host collectives (tensor plane over the cluster wire) ---------

    def collective_group(self, name: str = "train", world: int | None = None,
                         timeout: float | None = None):
        """Handle for cluster-wide tensor collectives (ring all-reduce /
        broadcast / all-gather on numpy arrays) — the gradient-exchange
        plane of ``cluster.train(..., mode="sync")``.

        Call :meth:`~tensorflowonspark_tpu.collective.CollectiveGroup.form`
        before the first collective; on a supervised restart pass the
        restored checkpoint step so the group's ``sync_state`` can level
        everyone (``ctx.is_restart`` is the cue).  ``world`` defaults to
        the data nodes (the evaluator sidecar never joins collectives —
        same exclusion as ``all_done``/``barrier(group='data')``).  Peer
        traffic rides each node's registered data-plane port; the
        rendezvous and generation barriers ride a dedicated coordinator
        connection, so incarnation fencing applies end to end.
        """
        from tensorflowonspark_tpu.collective import CollectiveGroup

        me = next((m for m in self.cluster_info
                   if m["executor_id"] == self.executor_id), None)
        if me is None or not me.get("data_port"):
            raise RuntimeError(
                "this node has no registered data_port; collective groups "
                "ride the data-plane wire and need one")
        return CollectiveGroup(
            coordinator_addr=self._config.coordinator_addr,
            authkey=self._config.authkey,
            executor_id=self.executor_id,
            world=int(world) if world else self.num_data_nodes,
            host=me["host"], data_port=int(me["data_port"]),
            name=name, incarnation=self.incarnation, timeout=timeout)

    def any_done(self, done: bool, timeout: float = 300.0) -> bool:
        name = self._client.next_collective_name("any_done")
        return bool(self._client.reduce(name, bool(done), kind="any", timeout=timeout,
                                        count=self.num_data_nodes))

    def barrier(self, name: str = "user", timeout: float = 300.0, group: str = "all") -> None:
        """Block until all participants arrive; ``group='data'`` excludes the
        evaluator (use it in code paths the evaluator never runs)."""
        count = self.num_data_nodes if group == "data" else None
        self._client.barrier(f"{name}:{_next_barrier_id()}", self.executor_id, timeout, count=count)

    def update_meta(self, patch: dict) -> None:
        """Publish metadata to the driver's ``cluster_info`` view (the same
        channel the TensorBoard URL uses) — e.g. device facts or results a
        test/driver wants to observe after shutdown."""
        self._client.update_meta(self.executor_id, patch)

    # -- telemetry -----------------------------------------------------------

    @property
    def metrics(self):
        """This process's telemetry registry — the ``map_fun``-facing metrics
        surface.  Anything recorded here rides the heartbeat piggyback into
        ``cluster.metrics()`` / the run report, e.g.::

            ctx.metrics.gauge("train.steps_per_sec").set(rate)
            ctx.metrics.counter("train.samples").inc(n)
            with ctx.metrics.timed("train.step_secs"): ...
        """
        from tensorflowonspark_tpu import telemetry

        return telemetry.get_registry()


_barrier_counter = [0]


def _next_barrier_id() -> int:
    _barrier_counter[0] += 1
    return _barrier_counter[0]


def _apply_jax_env_config() -> None:
    """Re-assert env-var JAX config onto ``jax.config``.

    JAX reads ``JAX_PLATFORMS``/``JAX_NUM_CPU_DEVICES``/
    ``JAX_CPU_COLLECTIVES_IMPLEMENTATION`` at import; but a site hook (e.g. a
    vendor PJRT plugin registered from sitecustomize) may have imported jax at
    interpreter startup and *overridden* the config before ``config.env`` was
    applied — and under ``LocalLauncher`` the env itself lands only inside
    ``node_main``.  Backends initialize lazily, so forcing the config here
    (before any ``jax.devices()`` call) is still early enough.

    If jax is NOT yet imported there is nothing to repair — the (just
    applied) env vars are honoured at first import — and importing it here
    would tax every node ~3s whether or not its map_fun ever computes.
    """
    if "jax" not in sys.modules:
        return
    import jax

    plats = os.environ.get("JAX_PLATFORMS")
    if plats and jax.config.jax_platforms != plats:
        jax.config.update("jax_platforms", plats)
    n = os.environ.get("JAX_NUM_CPU_DEVICES")
    if n and jax.config.jax_num_cpu_devices != int(n):
        jax.config.update("jax_num_cpu_devices", int(n))
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")
    if impl and jax.config.jax_cpu_collectives_implementation != impl:
        jax.config.update("jax_cpu_collectives_implementation", impl)


def _start_tensorboard(log_dir: str) -> tuple[subprocess.Popen | None, str | None]:
    """Spawn TensorBoard on a free port (reference ``TFSparkNode.py:~300-330``)."""
    try:
        from tensorflowonspark_tpu.utils.net import find_free_port

        port = find_free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tensorboard.main", "--logdir", log_dir,
             "--port", str(port), "--bind_all"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return proc, f"http://{local_ip()}:{port}"
    except Exception:
        logger.warning("could not launch tensorboard", exc_info=True)
        return None, None


def node_main(config: NodeConfig) -> int:
    """Entry point of one node process; returns a process exit code."""
    for k, v in config.env.items():
        os.environ[k] = v
    _apply_jax_env_config()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [node %(process)d] %(name)s: %(message)s",
        force=True,
    )
    from tensorflowonspark_tpu import faultinject

    # Chaos hooks arm only AFTER per-node env landed (per_node_env is how a
    # test makes exactly one node of a cluster misbehave).
    faultinject.init_from_env(force=True)

    client = CoordinatorClient(config.coordinator_addr, authkey=config.authkey)
    queues = FeedQueues(config.queues, config.queue_capacity)
    server = DataServer(queues, config.authkey, config.feed_timeout)
    data_port = server.start()

    from tensorflowonspark_tpu import tpu_info

    # jax.distributed.initialize must run before anything initialises the XLA
    # backend, and device_summary() does (jax.devices()).  In distributed
    # mode register a placeholder and fill in real hardware via update_meta
    # right after initialize.
    device_meta = ({"platform": "pending_distributed_init"}
                   if config.jax_distributed else tpu_info.device_summary())
    ident = client.register({"host": local_ip(), "data_port": data_port,
                             "pid": os.getpid(), "device": device_meta,
                             "launch_index": config.launch_index},
                            replace=(config.replace_executor_id
                                     if config.replace_executor_id >= 0 else None))
    executor_id = ident["executor_id"]
    incarnation = int(ident.get("incarnation", 0))
    # Every control-plane message from here carries this identity, so a
    # zombie predecessor of this slot (or this process, once IT is declared
    # dead) is fenced by the coordinator instead of racing its replacement.
    client.set_identity(executor_id, incarnation)
    # chaos identity includes the assigned ROLE: `role=ingest` filters let
    # a cluster-wide TOS_FAULTINJECT spec target exactly the data-service
    # tier even though role assignment is registration-order
    faultinject.set_identity(executor_id, incarnation,
                             role=ident["job_name"])
    if config.log_dir:
        # chaos-kill postmortem: a `kill` fault dumps this process's flight
        # recorder (recent spans + events) next to the job logs before the
        # SIGKILL — the one record of the node's last seconds that survives
        faultinject.set_flight_dump(
            os.path.join(config.log_dir, f"flight_node{executor_id}.json"),
            node=f"node{executor_id}")
    cluster_info = client.await_cluster(timeout=config.reservation_timeout)

    # Heartbeats must start IMMEDIATELY after registration — before
    # jax.distributed.initialize and before map_fun's first XLA compiles
    # (20-40s on a real chip): the driver's dead-node monitor flags any node
    # silent past its window, and a healthy-but-compiling node must never
    # look dead.  Own connection: the main client's socket can be tied up
    # for minutes inside a blocking barrier/reduce, which would starve
    # liveness pings and block the driver's stop signal.
    stop_requested = threading.Event()

    def _heartbeat_loop() -> None:
        nonlocal incarnation
        from tensorflowonspark_tpu import telemetry
        from tensorflowonspark_tpu.telemetry import trace as ttrace
        from tensorflowonspark_tpu.utils.envtune import env_float

        # Heartbeats are load-bearing for liveness (the driver's monitor
        # flags silent nodes dead) AND for the client-side SELF-FENCE
        # (ISSUE 13): a node that cannot reach the coordinator for longer
        # than TOS_COORDINATOR_GRACE_SECS must not keep computing as a
        # zombie — once the driver's death-declaration window expires, a
        # replacement may own this slot, and split-brain writes (outputs,
        # checkpoints) are exactly what incarnation fencing exists to
        # prevent.  Timeline on sustained silence:
        #   0 .. grace      — redial every interval (a supervised
        #                     coordinator restart lands well inside this);
        #   grace ..        — PARK: the feeds stop taking new work
        #                     ("parked" queue state) until a successful
        #                     ping re-admits us (or a fenced reply says
        #                     stop, i.e. re-registration owns the slot);
        #   4 x grace       — give up: force end-of-feed and exit (the
        #                     driver is gone for good).
        # The heartbeat channel dials single-shot with a BOUNDED call
        # timeout so a blackholed (packets dropped, not refused)
        # coordinator surfaces as a timeout this loop can count, instead
        # of wedging the liveness thread forever — the zombie asymmetry
        # this satellite closes.
        grace = env_float("TOS_COORDINATOR_GRACE_SECS",
                          max(12.0, 6.0 * config.heartbeat_interval))
        tracer = ttrace.get_tracer()
        hb_client = None
        parked = False
        ever_ok = False
        last_ok = time.monotonic()
        metrics_state: dict | None = None
        while not stop_requested.is_set():
            if faultinject.drop_heartbeat():
                # Chaos hook: swallow this liveness ping (models a network
                # partition — the process lives on as a zombie the driver
                # will declare dead; incarnation fencing handles the rest).
                time.sleep(config.heartbeat_interval)
                continue
            payload: dict | None = None
            trace_payload: dict | None = None
            stop = False
            try:
                if hb_client is None:
                    hb_client = CoordinatorClient(
                        config.coordinator_addr, authkey=config.authkey,
                        connect_timeout=3.0, connect_attempts=1,
                        call_timeout=max(5.0, min(grace, 15.0)))
                    hb_client.set_identity(executor_id, incarnation)
                # Compact telemetry delta piggybacks on the ping (absolute
                # cumulative values, changed keys only): the cluster metrics
                # transport costs zero extra round-trips, and a delta lost
                # with a failed ping is re-sent implicitly by the next one.
                # The trace delta (new spans + flight events, stamped with
                # the current clock-offset estimate) rides the same ping.
                if telemetry.enabled():
                    payload, metrics_state = telemetry.collect_changed(
                        metrics_state)
                trace_payload = tracer.collect_delta()
                stop = hb_client.heartbeat(executor_id,
                                           metrics=payload or None,
                                           trace=trace_payload)
                # feed the round-trip's clock estimate back to the tracer
                # (best-RTT midpoint wins; used by export + flight dumps)
                if hb_client.last_clock_offset is not None:
                    tracer.note_clock(hb_client.last_clock_offset,
                                      hb_client.last_rtt)
                ever_ok = True
                last_ok = time.monotonic()
                if hb_client.incarnation != incarnation:
                    # READMITTED after a gray-failure eviction: the
                    # coordinator handed this channel the slot's bumped
                    # incarnation.  Propagate to the process's other
                    # identity holders NOW — the main client may sit idle
                    # for minutes (its next round-trip would also relearn),
                    # and faultinject keys per-incarnation arming off it.
                    incarnation = hb_client.incarnation
                    client.set_identity(executor_id, incarnation)
                    faultinject.set_identity(executor_id, incarnation,
                                             role=ident["job_name"])
                    logger.warning("node %d adopted incarnation %d after "
                                   "readmission", executor_id, incarnation)
                if hb_client.last_evicted:
                    # EVICTED from the collective group at quorum (gray
                    # failure): park — no new ledger work while benched;
                    # keep heartbeating (the pings ARE the probation
                    # health probe the coordinator readmits on).
                    if not parked:
                        parked = True
                        queues.compare_and_set("state", "running", "parked")
                        ttrace.event("evicted_parked", executor=executor_id)
                        logger.warning(
                            "node %d evicted from its collective group "
                            "(quorum of straggler-suspicion votes); parked "
                            "in probation until readmitted", executor_id)
                elif parked:
                    # re-admitted: the coordinator (possibly a journal-
                    # recovered one at a bumped epoch, possibly after an
                    # eviction probation) answered our ping without fencing
                    # or benching us — resume taking ledger work.
                    # compare_and_set: a feed that TERMINATED while parked
                    # keeps its fast-drain state (stop beats park).
                    parked = False
                    queues.compare_and_set("state", "parked", "running")
                    ttrace.event("readmit", executor=executor_id)
                    logger.warning("coordinator re-admitted node %d; "
                                   "unparked", executor_id)
            except Exception:
                # the delta that rode the failed ping may be lost: drop the
                # dedupe state so the next successful ping re-sends a full
                # snapshot (values are absolute — re-sending is idempotent),
                # give the drained span samples back to their outboxes, and
                # give the trace delta back to the tracer — spans/flight
                # events are the parts of a delta that are NOT re-derivable
                metrics_state = None
                if payload:
                    telemetry.get_registry().restore_recent(payload)
                tracer.restore_delta(trace_payload)
                if hb_client is not None:
                    try:
                        hb_client.close()
                    except OSError:  # toslint: allow-silent(socket already dead; a fresh dial follows)
                        pass
                    hb_client = None
                silent = time.monotonic() - last_ok
                # a channel that NEVER connected fails fast at one grace —
                # the driver's monitor declares this node dead at
                # TOS_DEAD_NODE_TIMEOUT with a generic death error, so the
                # specific report below must beat the 4x-grace ladder
                # (riding out a coordinator restart window still fits: the
                # supervisor backoff is well under one grace)
                give_up_at = grace if not ever_ok else 4.0 * grace
                if silent > give_up_at:
                    logger.error(
                        "coordinator unreachable for %.0fs (budget %.0fs, "
                        "TOS_COORDINATOR_GRACE_SECS=%.0fs); forcing "
                        "end-of-feed", silent, give_up_at, grace)
                    if not ever_ok:
                        # never had a liveness channel at all: a clean exit
                        # would deregister and silently drop this node's
                        # partitions — report through the main client
                        # (thread-safe) so train()/shutdown() raise
                        try:
                            client.report_error(
                                executor_id,
                                "heartbeat channel never connected; node "
                                "cannot participate in liveness tracking")
                        except Exception:
                            logger.debug("could not deliver the heartbeat-"
                                         "channel failure report either",
                                         exc_info=True)
                    _enter_stop_state()
                    return
                if not parked and silent > grace:
                    # SELF-FENCE: past the grace the driver has (or soon
                    # will have) declared us dead and re-fed our work —
                    # stop accepting new ledger work and park until a
                    # heartbeat round-trip re-admits (or fences) us.
                    # compare_and_set: never clobber a 'terminating' feed's
                    # fast-drain state — a stopped node has nothing to fence.
                    parked = True
                    queues.compare_and_set("state", "running", "parked")
                    ttrace.event("self_fence", executor=executor_id,
                                 silent_secs=round(silent, 1))
                    logger.warning(
                        "coordinator unreachable for %.1fs (> "
                        "TOS_COORDINATOR_GRACE_SECS=%.0fs); node %d "
                        "self-fenced: parked, no new ledger work until "
                        "re-admitted", silent, grace, executor_id)
            if stop:
                # Driver asked us to stop: unblock any DataFeed consumer so
                # map_fun can exit (zombie-free teardown, SURVEY.md §7.3-5).
                _enter_stop_state()
                return
            time.sleep(config.heartbeat_interval)

    def _enter_stop_state() -> None:
        from tensorflowonspark_tpu.dataserver import _force_put

        stop_requested.set()
        # fast-drain: in-flight and future driver feed puts return
        # "terminating" instead of blocking on a consumer that may be
        # wedged in user code (never in the feed again)
        queues.set("state", "terminating")
        for qname in config.input_qnames:
            _force_put(queues.get_queue(qname), EndOfFeed())

    hb = threading.Thread(target=_heartbeat_loop, daemon=True, name="heartbeat")
    hb.start()

    tb_proc = None
    # The chief is always executor 0 whatever its role is named (master_node
    # lets users rename it), so key on id, not on the name.
    if config.tensorboard and executor_id == 0 and config.log_dir:
        tb_proc, tb_url = _start_tensorboard(config.log_dir)
        if tb_url:
            client.update_meta(executor_id, {"tb_url": tb_url})

    if config.jax_distributed and ident["job_name"] not in ("evaluator",
                                                            "ingest"):
        # Real multi-host SPMD: one JAX process per host over DCN.  The chief
        # picks a free port on its own host and distributes it through a
        # control-plane max-reduce (everyone else contributes -1), so no node
        # guesses at unreserved ports (SURVEY.md §5.2 race class).
        #
        # DATA NODES ONLY: the evaluator is a sidecar excluded from every
        # collective by design (consensus, barriers — and crucially orbax,
        # whose save/restore run sync_global_processes over the WHOLE jax
        # process group: an evaluator inside the group would deadlock every
        # collective checkpoint save).  Role assignment puts the evaluator
        # last, so data nodes are the contiguous ids 0..N_data-1 that
        # jax.distributed requires.
        import jax

        from tensorflowonspark_tpu.utils.net import bound_socket

        num_data = sum(1 for m in cluster_info
                       if m["job_name"] not in ("evaluator", "ingest"))
        # The chief HOLDS the port bound through the whole reduce (the long,
        # unbounded wait for peers) and releases it only at handoff to
        # jax.distributed's coordinator service — no bind-then-release window
        # a concurrent process could squat in (SURVEY.md §5.2 race class;
        # SO_REUSEADDR lets jax re-bind immediately).
        sock = bound_socket() if executor_id == 0 else None
        port = sock.getsockname()[1] if sock is not None else -1
        port = int(client.reduce("jax_coordinator_port", port, kind="max",
                                 timeout=config.reservation_timeout,
                                 count=num_data))
        chief_host = cluster_info[0]["host"]
        if sock is not None:
            sock.close()  # handoff: jax's coordinator binds it next
        jax.distributed.initialize(
            coordinator_address=f"{chief_host}:{port}",
            num_processes=num_data,
            process_id=executor_id,
        )
        client.update_meta(executor_id, {"device": tpu_info.device_summary()})
    elif config.jax_distributed:
        # evaluator in a distributed job: local backend only (lazy); report
        # what this host exposes
        client.update_meta(executor_id, {"device": tpu_info.device_summary()})

    ctx = NodeContext(
        executor_id=executor_id,
        job_name=ident["job_name"],
        task_index=ident["task_index"],
        num_executors=len(cluster_info),
        cluster_info=cluster_info,
        queues=queues,
        config=config,
        client=client,
        stop_event=stop_requested,
        incarnation=incarnation,
    )

    # Role-aware dispatch: a process the coordinator assigned the "ingest"
    # role runs the data-service worker loop instead of the user map_fun —
    # role assignment is registration-order, so the dispatch must key on
    # the ASSIGNED role, never on which config launched the process.
    if ident["job_name"] == "ingest":
        from tensorflowonspark_tpu.ingest.service import ingest_worker_main

        effective_map_fun = ingest_worker_main
    else:
        effective_map_fun = config.map_fun

    exit_code = 0
    try:
        logger.info("node %d (%s:%d) invoking map_fun", executor_id, ident["job_name"], ident["task_index"])
        from tensorflowonspark_tpu import telemetry

        with telemetry.timed("node.map_fun_secs"):
            effective_map_fun(config.tf_args, ctx)
    except Exception:
        tb = traceback.format_exc()
        logger.error("map_fun failed:\n%s", tb)
        try:
            client.report_error(executor_id, tb)
        except Exception:
            # the error still reaches the driver: the silent heartbeat
            # (no deregister follows a failed report) flags this node dead
            logger.debug("could not report map_fun failure to the "
                         "coordinator", exc_info=True)
        exit_code = 1
    finally:
        ctx.stop_requested.set()
        server.stop()
        if tb_proc is not None:
            tb_proc.terminate()
        try:
            # Deliberate exit (normal completion, or error already reported
            # above): tell the driver to stop liveness-tracking this node so
            # its monitor never mistakes the exit for a death.  The final
            # telemetry snapshot rides along — metrics recorded after the
            # last heartbeat (tail batches, the map_fun span itself) must
            # still reach the driver's cluster view.
            from tensorflowonspark_tpu import telemetry
            from tensorflowonspark_tpu.telemetry import trace as ttrace

            # The tracer drain is single-consumer: wait for the heartbeat
            # thread (the in-run consumer) to see the stop flag before the
            # final drain, else a failed in-flight ping could restore_delta
            # AFTER collect_final and strand those spans (or rewind a ring
            # cursor mid-drain).  A wedged ping forfeits the final trace
            # rather than racing for it — metrics stay safe either way
            # (absolute values, idempotent).
            hb.join(config.heartbeat_interval + 10.0)
            final_metrics = (telemetry.collect_changed(None)[0]
                             if telemetry.enabled() else None)
            client.deregister(executor_id, metrics=final_metrics or None,
                              trace=(ttrace.collect_final()
                                     if not hb.is_alive() else None))
        except Exception:
            logger.debug("deregister failed during teardown (driver may "
                         "flag this exit as a death)", exc_info=True)
        client.close()
    return exit_code
