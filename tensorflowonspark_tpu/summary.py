"""TensorBoard event-file writer — hand-rolled Event/Summary protos.

The reference's only observability surface is TensorBoard (SURVEY.md §5.1):
user ``map_fun``s write summaries via TF and ``TFCluster.run(tensorboard=True)``
spawns the viewer.  Here nodes can write scalars without TF: an event file is
a TFRecord stream of ``Event`` protos, which we encode with the same varint
helpers as ``example.py``:

    Event   { double wall_time = 1; int64 step = 2;
              oneof { string file_version = 3; Summary summary = 5; } }
    Summary { repeated Value value = 1; }
    Value   { string tag = 1; float simple_value = 2; }

TensorBoard's scalar dashboard reads exactly this subset.
"""

from __future__ import annotations

import os
import struct
import time

from tensorflowonspark_tpu.example import _write_len_delimited, _write_varint
from tensorflowonspark_tpu.tfrecord import RecordWriter
from tensorflowonspark_tpu.utils.paths import resolve_uri

_F64 = struct.Struct("<d")
_F32 = struct.Struct("<f")


def _encode_value(tag: str, value: float) -> bytes:
    out = bytearray()
    _write_len_delimited(out, 1, tag.encode("utf-8"))
    _write_varint(out, (2 << 3) | 5)  # field 2, 32-bit
    out += _F32.pack(float(value))
    return bytes(out)


def _encode_event(wall_time: float, step: int, scalars: dict[str, float] | None,
                  file_version: str | None = None) -> bytes:
    out = bytearray()
    _write_varint(out, (1 << 3) | 1)  # field 1, 64-bit double
    out += _F64.pack(wall_time)
    _write_varint(out, (2 << 3) | 0)  # field 2, varint
    _write_varint(out, int(step))
    if file_version is not None:
        _write_len_delimited(out, 3, file_version.encode("utf-8"))
    if scalars:
        summary = bytearray()
        for tag, value in scalars.items():
            _write_len_delimited(summary, 1, _encode_value(tag, value))
        _write_len_delimited(out, 5, bytes(summary))
    return bytes(out)


class SummaryWriter:
    """Write TensorBoard scalar events (one file per writer)."""

    def __init__(self, log_dir: str, filename_suffix: str = ""):
        log_dir = resolve_uri(log_dir)
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{time.time():.0f}.{os.getpid()}{filename_suffix}"
        self._writer = RecordWriter(os.path.join(log_dir, fname))
        # TensorBoard requires a leading file_version event.
        self._writer.write(_encode_event(time.time(), 0, None, file_version="brain.Event:2"))
        self._writer.flush()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._writer.write(_encode_event(time.time(), step, {tag: value}))

    def add_scalars(self, scalars: dict[str, float], step: int) -> None:
        self._writer.write(_encode_event(time.time(), step, scalars))

    def flush(self) -> None:
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
