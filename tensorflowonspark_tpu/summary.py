"""TensorBoard event-file writer — hand-rolled Event/Summary protos.

The reference's only observability surface is TensorBoard (SURVEY.md §5.1):
user ``map_fun``s write summaries via TF and ``TFCluster.run(tensorboard=True)``
spawns the viewer.  Here nodes can write scalars without TF: an event file is
a TFRecord stream of ``Event`` protos, which we encode with the same varint
helpers as ``example.py``:

    Event   { double wall_time = 1; int64 step = 2;
              oneof { string file_version = 3; Summary summary = 5; } }
    Summary { repeated Value value = 1; }
    Value   { string tag = 1; float simple_value = 2; }

TensorBoard's scalar dashboard reads exactly this subset.
"""

from __future__ import annotations

import atexit
import os
import struct
import time

from tensorflowonspark_tpu.example import _write_len_delimited, _write_varint
from tensorflowonspark_tpu.tfrecord import RecordWriter
from tensorflowonspark_tpu.utils.paths import resolve_uri

_F64 = struct.Struct("<d")
_F32 = struct.Struct("<f")


def _encode_value(tag: str, value: float) -> bytes:
    out = bytearray()
    _write_len_delimited(out, 1, tag.encode("utf-8"))
    _write_varint(out, (2 << 3) | 5)  # field 2, 32-bit
    out += _F32.pack(float(value))
    return bytes(out)


def _encode_event(wall_time: float, step: int, scalars: dict[str, float] | None,
                  file_version: str | None = None) -> bytes:
    out = bytearray()
    _write_varint(out, (1 << 3) | 1)  # field 1, 64-bit double
    out += _F64.pack(wall_time)
    _write_varint(out, (2 << 3) | 0)  # field 2, varint
    _write_varint(out, int(step))
    if file_version is not None:
        _write_len_delimited(out, 3, file_version.encode("utf-8"))
    if scalars:
        summary = bytearray()
        for tag, value in scalars.items():
            _write_len_delimited(summary, 1, _encode_value(tag, value))
        _write_len_delimited(out, 5, bytes(summary))
    return bytes(out)


class SummaryWriter:
    """Write TensorBoard scalar events (one file per writer).

    Crash-robust by default: nodes in an elastic cluster get killed mid-run
    (supervised restarts, ``TOS_FAULTINJECT`` kills, preemption), and an
    event file cut inside a buffered record is truncated garbage from the
    last flush onward.  So the writer (a) flushes at *record boundaries* on
    a ``flush_secs`` cadence — a hard kill can only cost the last few
    seconds of scalars, never leave a half-written record the OS already
    had; (b) registers an ``atexit`` close so orderly teardowns (SIGTERM
    handlers, interpreter exit with the writer still open) always land a
    complete file; (c) makes ``close()`` idempotent, so atexit after an
    explicit close (or the context manager) is a no-op.
    """

    def __init__(self, log_dir: str, filename_suffix: str = "",
                 flush_secs: float = 5.0):
        log_dir = resolve_uri(log_dir)
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{time.time():.0f}.{os.getpid()}{filename_suffix}"
        self._writer = RecordWriter(os.path.join(log_dir, fname))
        self._flush_secs = max(0.0, float(flush_secs))
        self._closed = False
        # TensorBoard requires a leading file_version event.
        self._writer.write(_encode_event(time.time(), 0, None, file_version="brain.Event:2"))
        self._writer.flush()
        self._last_flush = time.monotonic()
        atexit.register(self.close)

    def _wrote_record(self) -> None:
        if time.monotonic() - self._last_flush >= self._flush_secs:
            self.flush()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._writer.write(_encode_event(time.time(), step, {tag: value}))
        self._wrote_record()

    def add_scalars(self, scalars: dict[str, float], step: int) -> None:
        self._writer.write(_encode_event(time.time(), step, scalars))
        self._wrote_record()

    def flush(self) -> None:
        if not self._closed:
            self._writer.flush()
            self._last_flush = time.monotonic()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._writer.close()
        # a closed writer needs no interpreter-exit hook (and unregistering
        # keeps long-lived processes from accumulating dead callbacks)
        atexit.unregister(self.close)

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
