"""Process launchers — the Spark-role replacement for process placement.

In the reference, Spark places one long-running task per executor
(``sc.parallelize(...).foreachPartition(TFSparkNode.run(...))``,
``TFCluster.py:~340-360``) and YARN/Hops provisions the hosts.  Here a
launcher backend owns process placement (SURVEY.md §7.1-4):

- ``LocalLauncher`` — N node processes on this machine via multiprocessing
  (the test/dev path, mirroring the reference's ``local-cluster[N,...]``
  test trick, SURVEY.md §4).
- ``SubprocessLauncher`` — N node processes as fresh OS subprocesses, each
  with its own environment.  Required for per-process accelerator
  visibility (``TPU_VISIBLE_CHIPS`` / ``JAX_NUM_CPU_DEVICES``) and for
  ``jax.distributed`` runs, where env must be in place *before* the child
  interpreter starts (site hooks may import jax at startup).
- ``TPUPodLauncher`` — placement across the hosts of a TPU pod slice; one
  node process per TPU-VM host, spawned over a pluggable transport
  (default: ``ssh``; ``transport='local'`` runs every "host" on this
  machine for single-box pods and tests).  Composes
  ``tpu_info.chip_visibility_env`` + ``bounds_from_coords`` so each
  process sees exactly its chip slice.

All launchers expose the same surface consumed by ``cluster.TPUCluster``:
``launch(configs, log_dir)``, ``processes`` (handles with ``.exitcode``),
``join(timeout)``, ``alive()``, ``terminate()``.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import shlex
import subprocess
import sys
import time
from typing import Callable, Sequence

import cloudpickle

from tensorflowonspark_tpu.node import NodeConfig


def _child_entry(payload: bytes, log_path: str | None) -> None:
    """Module-level child target (picklable under the 'spawn' start method)."""
    if log_path:
        f = open(log_path, "a", buffering=1)
        os.dup2(f.fileno(), sys.stdout.fileno())
        os.dup2(f.fileno(), sys.stderr.fileno())
    config: NodeConfig = cloudpickle.loads(payload)
    from tensorflowonspark_tpu.node import node_main

    sys.exit(node_main(config))


class _RespawnMixin:
    """Shared supervised-restart scaffolding: launch-time config capture and
    the reap-then-respawn of one slot.  Subclasses provide ``_spawn_one`` and
    a ``self._procs`` list of handles exposing
    ``is_alive/terminate/kill/join``."""

    def _remember_launch(self, configs: Sequence["NodeConfig"],
                         log_dir: str | None) -> None:
        self._configs = list(configs)
        self._log_dir = log_dir

    @property
    def configs(self) -> list["NodeConfig"]:
        """The per-slot NodeConfigs of the most recent launch()."""
        return list(self._configs)

    def respawn(self, index: int, config: "NodeConfig | None" = None) -> None:
        """Replace the process at ``index`` with a fresh one (supervised
        restart path).  Reaps the predecessor FIRST — terminate, then kill —
        so a zombie (alive but fenced) can never share the slot's ports or
        accelerators with its replacement; the old handle (and its exit
        code) is dropped, keeping shutdown's exit-code audit about the
        processes that finished the job."""
        old = self._procs[index]
        if old.is_alive():
            old.terminate()
            old.join(5.0)
            if old.is_alive():
                old.kill()
        old.join(5.0)
        self._procs[index] = self._spawn_one(index, config or self._configs[index])

    def spawn_more(self, configs: Sequence["NodeConfig"]) -> None:
        """Append fresh node processes to a LIVE launch (cluster.resize
        scale-out): each config's ``launch_index`` must equal its position
        in the extended process list — the registration-time key the driver
        uses to map executor ids back to process handles."""
        for offset, config in enumerate(configs):
            expect = len(self._procs) + offset
            if config.launch_index != expect:
                raise ValueError(
                    f"spawn_more config at position {offset} has "
                    f"launch_index {config.launch_index}, expected {expect}")
        for config in configs:
            self._configs.append(config)
            try:
                self._procs.append(self._spawn_one(config.launch_index, config))
            except Exception:
                # keep _configs and _procs the same length: a later
                # spawn_more validates launch_index against len(_procs),
                # and a dangling config would desynchronize them for good
                self._configs.pop()
                raise


class LocalLauncher(_RespawnMixin):
    """Spawn node processes on the local host.

    Uses the 'spawn' start method: forking a process after JAX/XLA has
    initialized in the driver is unsafe, and spawn matches how real TPU-VM
    hosts start fresh Python processes.  ``map_fun`` travels via cloudpickle
    (the same closure-shipping contract Spark gave the reference).

    Env caveat: ``config.env`` is applied inside ``node_main`` — after the
    child interpreter (and any site hooks) started.  Vars that must be seen
    at interpreter startup (``JAX_PLATFORMS`` under a sitecustomize that
    imports jax, ``TPU_VISIBLE_CHIPS``) need ``SubprocessLauncher``.
    """

    def __init__(self, env: dict[str, str] | None = None):
        self.env = dict(env or {})
        self._procs: list[mp.Process] = []
        self._configs: list[NodeConfig] = []
        self._log_dir: str | None = None

    def launch(self, configs: Sequence[NodeConfig], log_dir: str | None = None) -> None:
        # Re-launchable: a fresh cluster must not inherit handles of a
        # previous run (launch_index -> process mapping relies on positions
        # matching THIS launch's configs).  Leftovers still alive — e.g. a
        # prior run that raised before shutdown — are terminated, not
        # silently orphaned holding ports/accelerators.
        if any(p.is_alive() for p in self._procs):
            self.terminate()
        self._procs = []
        self._remember_launch(configs, log_dir)
        for i, config in enumerate(configs):
            config.env = {**self.env, **config.env}
            self._procs.append(self._spawn_one(i, config))

    def _spawn_one(self, i: int, config: NodeConfig) -> mp.Process:
        ctx = mp.get_context("spawn")
        log_path = os.path.join(self._log_dir, f"node_{i}.log") if self._log_dir else None
        payload = cloudpickle.dumps(config)
        p = ctx.Process(target=_child_entry, args=(payload, log_path), name=f"tpu-node-{i}")
        p.daemon = False
        p.start()
        return p

    @property
    def processes(self) -> list[mp.Process]:
        return list(self._procs)

    def join(self, timeout: float | None = None) -> bool:
        """Join all node processes; True if all exited within the timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            p.join(remaining)
        return all(p.exitcode is not None for p in self._procs)

    def alive(self) -> list[int]:
        return [i for i, p in enumerate(self._procs) if p.is_alive()]

    def terminate(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(5.0)
            if p.is_alive():
                p.kill()


class PopenHandle:
    """Adapt ``subprocess.Popen`` to the ``mp.Process``-ish handle surface
    (``exitcode``/``is_alive``/``join``/``terminate``/``kill``) that
    ``TPUCluster.shutdown`` consumes."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def exitcode(self) -> int | None:
        return self.proc.poll()

    def is_alive(self) -> bool:
        return self.proc.poll() is None

    def join(self, timeout: float | None = None) -> None:
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:  # toslint: allow-silent(mp.Process.join contract: a timed-out join returns with the process still alive)
            pass

    def terminate(self) -> None:
        if self.is_alive():
            self.proc.terminate()

    def kill(self) -> None:
        if self.is_alive():
            self.proc.kill()


def _node_command() -> list[str]:
    """The command line that runs one node from a stdin payload.

    ``node_entry`` is a dedicated module NOT imported by the package
    ``__init__`` — running ``-m`` on a module that is also imported as a
    package attribute would execute it twice as two distinct module objects
    (runpy's 'found in sys.modules' hazard)."""
    return [sys.executable, "-m", "tensorflowonspark_tpu.node_entry"]


def _pythonpath_env() -> dict[str, str]:
    """PYTHONPATH that reproduces the driver's ``sys.path`` in a fresh local
    interpreter, so cloudpickled map_funs resolve their defining modules
    (and this package itself imports from a source checkout).  The same
    contract Spark gave the reference by shipping the driver's PYTHONPATH /
    egg to executors; ``multiprocessing`` spawn does it implicitly for
    ``LocalLauncher``."""
    entries = [p for p in sys.path if p and os.path.isdir(p)]
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if pkg_parent not in entries:
        entries.append(pkg_parent)
    return {"PYTHONPATH": os.pathsep.join(entries)}


class SubprocessLauncher(_RespawnMixin):
    """Spawn node processes as fresh OS subprocesses with per-node env.

    Each child runs ``python -m tensorflowonspark_tpu.launcher`` and reads
    its cloudpickled ``NodeConfig`` from stdin.  ``config.env`` is merged
    into the *OS-level* environment of the child, so interpreter-startup
    consumers (PJRT plugins registered from sitecustomize, libtpu chip
    visibility) see it — the property ``LocalLauncher`` cannot provide.
    """

    def __init__(self, env: dict[str, str] | None = None):
        self.env = dict(env or {})
        self._procs: list[PopenHandle] = []
        self._configs: list[NodeConfig] = []
        self._log_dir: str | None = None

    def launch(self, configs: Sequence[NodeConfig], log_dir: str | None = None) -> None:
        if any(p.is_alive() for p in self._procs):
            self.terminate()  # re-launchable (see LocalLauncher.launch)
        self._procs = []
        self._remember_launch(configs, log_dir)
        for i, config in enumerate(configs):
            config.env = {**self.env, **config.env}
            self._procs.append(self._spawn_one(i, config))

    def _spawn_one(self, i: int, config: NodeConfig) -> PopenHandle:
        child_env = {**os.environ, **_pythonpath_env(), **config.env}
        if self._log_dir:
            log_f = open(os.path.join(self._log_dir, f"node_{i}.log"), "ab", buffering=0)
        else:
            log_f = None
        payload = cloudpickle.dumps(config)
        proc = subprocess.Popen(
            _node_command(),
            stdin=subprocess.PIPE,
            stdout=log_f if log_f else None,
            stderr=subprocess.STDOUT if log_f else None,
            env=child_env,
        )
        proc.stdin.write(payload)
        proc.stdin.close()
        if log_f is not None:
            log_f.close()  # child holds its own fd now
        return PopenHandle(proc)

    @property
    def processes(self) -> list[PopenHandle]:
        return list(self._procs)

    def join(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            p.join(remaining)
        return all(p.exitcode is not None for p in self._procs)

    def alive(self) -> list[int]:
        return [i for i, p in enumerate(self._procs) if p.is_alive()]

    def terminate(self) -> None:
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            p.join(5.0)
            if p.is_alive():
                p.kill()


class TPUPodLauncher(_RespawnMixin):
    """Placement across the hosts of a TPU pod slice.

    One node process per TPU-VM host; each process sees that host's chips
    (or an explicit slice of them) and joins the global mesh via
    ``jax.distributed`` (``NodeConfig.jax_distributed=True`` is forced).

    Transports:
    - ``'ssh'`` (default): ``ssh <host> env K=V... python -m
      tensorflowonspark_tpu.launcher`` with the pickled config streamed over
      stdin.  Requires passwordless ssh and the package importable on the
      remote host — the TPU-VM idiom (reference parity:
      ``TFCluster.py:~340-360`` used Spark's executor placement instead).
    - ``'local'``: every "host" is this machine; used for single-host
      multi-process pods and for tests.
    - a callable ``transport(host, command, env) -> subprocess.Popen`` for
      custom fabrics (GKE exec, tpu-vm ssh wrappers, ...).

    ``chip_slices`` optionally gives each host's chip ids (e.g. two
    processes splitting one host's 4 chips: ``[[0, 1], [2, 3]]``); the env
    is then derived via ``tpu_info.chip_visibility_env``, with process
    bounds from ``tpu_info.bounds_from_coords`` when ``chip_coords`` (the
    discovered per-chip mesh coordinates) is supplied.  Without slices,
    each process sees everything its host exposes — the common whole-host
    pod layout.
    """

    def __init__(
        self,
        hosts: Sequence[str],
        transport: str | Callable = "ssh",
        env: dict[str, str] | None = None,
        chip_slices: Sequence[Sequence[int]] | None = None,
        chip_coords: Sequence[Sequence[Sequence[int]]] | None = None,
        platform: str = "tpu",
        simulate_chips: int | None = None,
    ):
        if chip_slices is not None and len(chip_slices) != len(hosts):
            raise ValueError("chip_slices must have one entry per host")
        self.hosts = list(hosts)
        self.transport = transport
        self.env = dict(env or {})
        self.chip_slices = [list(s) for s in chip_slices] if chip_slices else None
        self.chip_coords = chip_coords
        self.platform = platform
        self.simulate_chips = simulate_chips
        self._procs: list[PopenHandle] = []
        self._configs: list[NodeConfig] = []
        self._log_dir: str | None = None

    # -- env composition -----------------------------------------------------

    def host_env(self, index: int) -> dict[str, str]:
        """The accelerator-visibility env for host ``index``."""
        from tensorflowonspark_tpu import tpu_info

        env = dict(self.env)
        if self.chip_slices is not None:
            bounds = None
            if self.chip_coords is not None:
                bounds = tpu_info.bounds_from_coords(self.chip_coords[index])
            env.update(tpu_info.chip_visibility_env(
                self.chip_slices[index], platform=self.platform,
                simulate_chips=self.simulate_chips, bounds=bounds))
        elif self.platform == "cpu":
            env.update(tpu_info.chip_visibility_env(
                (), platform="cpu", simulate_chips=self.simulate_chips))
        return env

    # -- spawning ------------------------------------------------------------

    def _spawn(self, host: str, env: dict[str, str], payload: bytes,
               log_f) -> PopenHandle:
        command = _node_command()
        if callable(self.transport):
            proc = self.transport(host, command, env)
        elif self.transport == "local":
            proc = subprocess.Popen(
                command, stdin=subprocess.PIPE,
                stdout=log_f if log_f else None,
                stderr=subprocess.STDOUT if log_f else None,
                env={**os.environ, **_pythonpath_env(), **env})
        elif self.transport == "ssh":
            # ssh joins argv into ONE remote shell line, so every env value
            # and command token must be shell-quoted (XLA_FLAGS routinely
            # holds spaces; unquoted values would also be an injection hole).
            env_prefix = ["env"] + [
                shlex.quote(f"{k}={v}") for k, v in sorted(env.items())]
            remote = env_prefix + [shlex.quote(c) for c in command]
            proc = subprocess.Popen(
                ["ssh", "-o", "BatchMode=yes", host] + remote,
                stdin=subprocess.PIPE,
                stdout=log_f if log_f else None,
                stderr=subprocess.STDOUT if log_f else None)
        else:
            raise ValueError(f"unknown transport {self.transport!r}")
        proc.stdin.write(payload)
        proc.stdin.close()
        return PopenHandle(proc)

    def launch(self, configs: Sequence[NodeConfig], log_dir: str | None = None) -> None:
        if len(configs) != len(self.hosts):
            raise ValueError(
                f"pod launcher got {len(configs)} configs for {len(self.hosts)} hosts")
        if any(p.is_alive() for p in self._procs):
            self.terminate()  # re-launchable (see LocalLauncher.launch)
        self._procs = []
        self._remember_launch(configs, log_dir)
        for i, (host, config) in enumerate(zip(self.hosts, configs)):
            config.jax_distributed = True  # a pod IS a jax.distributed job
            config.env = {**self.host_env(i), **config.env}
            self._procs.append(self._spawn_one(i, config))

    def _spawn_one(self, i: int, config: NodeConfig) -> PopenHandle:
        log_f = None
        if self._log_dir:
            log_f = open(os.path.join(self._log_dir, f"node_{i}.log"), "ab", buffering=0)
        payload = cloudpickle.dumps(config)
        try:
            return self._spawn(self.hosts[i], config.env, payload, log_f)
        finally:
            if log_f is not None:
                log_f.close()

    def respawn(self, index: int, config: NodeConfig | None = None) -> None:
        """A pod is one ``jax.distributed`` job — a restarted process cannot
        rejoin the live XLA world, so there is nothing a per-slot respawn
        could correctly do (``cluster.run`` refuses ``elastic`` with this
        launcher up front; this guard catches direct callers)."""
        raise NotImplementedError(
            "TPUPodLauncher cannot respawn a single slot of a live "
            "jax.distributed pod; relaunch the whole pod instead")

    def spawn_more(self, configs: Sequence[NodeConfig]) -> None:
        """A pod's process count is fixed by its jax.distributed world size;
        ``cluster.resize`` refuses distributed jobs up front — this guard
        catches direct callers."""
        raise NotImplementedError(
            "TPUPodLauncher cannot grow a live jax.distributed pod; "
            "relaunch the pod at the new size instead")

    @property
    def processes(self) -> list[PopenHandle]:
        return list(self._procs)

    def join(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            p.join(remaining)
        return all(p.exitcode is not None for p in self._procs)

    def alive(self) -> list[int]:
        return [i for i, p in enumerate(self._procs) if p.is_alive()]

    def terminate(self) -> None:
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            p.join(5.0)
            if p.is_alive():
                p.kill()


