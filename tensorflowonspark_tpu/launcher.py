"""Process launchers — the Spark-role replacement for process placement.

In the reference, Spark places one long-running task per executor
(``sc.parallelize(...).foreachPartition(TFSparkNode.run(...))``,
``TFCluster.py:~340-360``) and YARN/Hops provisions the hosts.  Here a
launcher backend owns process placement (SURVEY.md §7.1-4):

- ``LocalLauncher`` — N node processes on this machine (the test/dev path,
  mirroring the reference's ``local-cluster[N,...]`` test trick, SURVEY.md §4).
- ``TPUPodLauncher`` — placement across TPU-VM hosts of a pod slice; each
  host runs one node process that owns that host's chips.  Requires an
  out-of-band transport (ssh/GKE); scaffolded, not implemented in-repo.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import sys
from typing import Sequence

import cloudpickle

from tensorflowonspark_tpu.node import NodeConfig


def _child_entry(payload: bytes, log_path: str | None) -> None:
    """Module-level child target (picklable under the 'spawn' start method)."""
    if log_path:
        f = open(log_path, "a", buffering=1)
        os.dup2(f.fileno(), sys.stdout.fileno())
        os.dup2(f.fileno(), sys.stderr.fileno())
    config: NodeConfig = cloudpickle.loads(payload)
    from tensorflowonspark_tpu.node import node_main

    sys.exit(node_main(config))


class LocalLauncher:
    """Spawn node processes on the local host.

    Uses the 'spawn' start method: forking a process after JAX/XLA has
    initialized in the driver is unsafe, and spawn matches how real TPU-VM
    hosts start fresh Python processes.  ``map_fun`` travels via cloudpickle
    (the same closure-shipping contract Spark gave the reference).
    """

    def __init__(self, env: dict[str, str] | None = None):
        self.env = dict(env or {})
        self._procs: list[mp.Process] = []

    def launch(self, configs: Sequence[NodeConfig], log_dir: str | None = None) -> None:
        ctx = mp.get_context("spawn")
        for i, config in enumerate(configs):
            config.env = {**self.env, **config.env}
            log_path = os.path.join(log_dir, f"node_{i}.log") if log_dir else None
            payload = cloudpickle.dumps(config)
            p = ctx.Process(target=_child_entry, args=(payload, log_path), name=f"tpu-node-{i}")
            p.daemon = False
            p.start()
            self._procs.append(p)

    @property
    def processes(self) -> list[mp.Process]:
        return list(self._procs)

    def join(self, timeout: float | None = None) -> bool:
        """Join all node processes; True if all exited within the timeout."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        for p in self._procs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            p.join(remaining)
        return all(p.exitcode is not None for p in self._procs)

    def alive(self) -> list[int]:
        return [i for i, p in enumerate(self._procs) if p.is_alive()]

    def terminate(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(5.0)
            if p.is_alive():
                p.kill()


class TPUPodLauncher:
    """Placement across the hosts of a TPU pod slice (scaffold).

    One node process per TPU-VM host; each process sees that host's chips and
    joins the global mesh via ``jax.distributed`` (``NodeConfig.jax_distributed``).
    Transport (ssh / GKE Jobset / queued resources) is deployment-specific and
    injected as a ``spawn_fn(host, command) -> handle``.
    """

    def __init__(self, hosts: list[str], spawn_fn=None):
        self.hosts = hosts
        self.spawn_fn = spawn_fn

    def launch(self, configs, log_dir=None):  # pragma: no cover - needs a pod
        if self.spawn_fn is None:
            raise NotImplementedError(
                "TPUPodLauncher needs a spawn_fn (ssh/GKE transport); "
                "use LocalLauncher for single-host runs"
            )
        for host, config in zip(self.hosts, configs):
            payload = cloudpickle.dumps(config)
            self.spawn_fn(host, payload)
