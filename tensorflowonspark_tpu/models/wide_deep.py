"""Wide-and-deep for Criteo-style CTR data — parity config 4
(BASELINE.json:10: "Spark ML Pipeline TFEstimator/TFModel, wide-and-deep on
Criteo"; reference ``examples/criteo/``).

TPU-native design: one ``[B, 13 + 26]`` feature matrix per batch — 13
numeric columns and 26 categorical columns (already integerized; hashed
mod ``vocab_size`` here, the in-graph equivalent of the reference's
feature-column hash buckets).  The wide path is a linear model over the
one-hot categorical space implemented as embedding-gathers (a [B,26]
gather, not a [B, vocab] one-hot matmul — HBM-friendly); the deep path is
embeddings + MLP, whose matmuls ride the MXU in bf16.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.registry import register

NUM_NUMERIC = 13
NUM_CATEGORICAL = 26


class WideDeep(nn.Module):
    vocab_size: int = 100_003  # per-column hash-bucket count (prime)
    embed_dim: int = 16
    hidden: Sequence[int] = (256, 128, 64)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        """x: [B, 39] float32; cols 0..12 numeric, 13..38 categorical ids."""
        numeric = x[:, :NUM_NUMERIC].astype(self.compute_dtype)
        cat = jnp.mod(x[:, NUM_NUMERIC:].astype(jnp.int32), self.vocab_size)
        # Disjoint id space per column so one embedding table serves all 26
        # (single large gather beats 26 small ones on TPU).
        offsets = jnp.arange(NUM_CATEGORICAL, dtype=jnp.int32) * self.vocab_size
        flat_ids = cat + offsets[None, :]

        # Wide: linear-in-one-hot == per-id scalar weight, summed.
        wide_table = self.param(
            "wide_weights", nn.initializers.zeros, (NUM_CATEGORICAL * self.vocab_size, 1))
        wide = jnp.sum(jnp.take(wide_table, flat_ids, axis=0)[..., 0], axis=1, keepdims=True)
        wide = wide + nn.Dense(1, dtype=jnp.float32, name="wide_numeric")(
            x[:, :NUM_NUMERIC])

        # Deep: embeddings + MLP.
        embed_table = self.param(
            "embeddings", nn.initializers.normal(0.01),
            (NUM_CATEGORICAL * self.vocab_size, self.embed_dim))
        emb = jnp.take(embed_table, flat_ids, axis=0)  # [B, 26, D]
        deep = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1).astype(self.compute_dtype), numeric], axis=-1)
        for h in self.hidden:
            deep = nn.relu(nn.Dense(h, dtype=self.compute_dtype)(deep))
        deep = nn.Dense(1, dtype=jnp.float32, name="deep_head")(deep)
        return (wide + deep)[:, 0]  # [B] logits


@register("wide_deep")
def build_wide_deep(config: dict) -> WideDeep:
    return WideDeep(
        vocab_size=config.get("vocab_size", 100_003),
        embed_dim=config.get("embed_dim", 16),
        hidden=tuple(config.get("hidden", (256, 128, 64))),
        compute_dtype=jnp.bfloat16 if config.get("bf16", True) else jnp.float32,
    )


def init_params(model: WideDeep, rng: jax.Array):
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy = jnp.zeros((1, NUM_NUMERIC + NUM_CATEGORICAL), jnp.float32)
    return jit_init(model, rng, dummy)["params"]


def make_loss_fn(model: WideDeep):
    """Binary cross-entropy on {0,1} click labels."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["features"])
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.mean(optax_sigmoid_bce(logits, labels))
        preds = (logits > 0).astype(jnp.float32)
        return loss, {"accuracy": jnp.mean((preds == labels).astype(jnp.float32))}

    return loss_fn


def optax_sigmoid_bce(logits: jax.Array, labels: jax.Array) -> jax.Array:
    import optax

    return optax.sigmoid_binary_cross_entropy(logits, labels)


def synthetic_criteo(n: int, seed: int = 0) -> list[dict]:
    """Learnable synthetic CTR rows: label correlates with numeric col 0 and
    categorical col 13 parity."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n):
        numeric = rng.rand(NUM_NUMERIC).astype(np.float32)
        cat = rng.randint(0, 1000, NUM_CATEGORICAL).astype(np.float32)
        label = int((numeric[0] + (cat[0] % 2) * 0.5) > 0.75)
        rows.append({"features": np.concatenate([numeric, cat]), "label": label})
    return rows


def batch_to_arrays(items: list) -> dict:
    """(features, label) tuples or row dicts -> batch arrays."""
    if isinstance(items[0], dict):
        feats = np.stack([np.asarray(r["features"], np.float32) for r in items])
        labels = np.asarray([r["label"] for r in items], np.int32)
    else:
        feats = np.stack([np.asarray(f, np.float32) for f, _ in items])
        labels = np.asarray([l for _, l in items], np.int32)
    return {"features": feats, "label": labels}
