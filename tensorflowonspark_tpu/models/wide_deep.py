"""Wide-and-deep for Criteo-style CTR data — parity config 4
(BASELINE.json:10: "Spark ML Pipeline TFEstimator/TFModel, wide-and-deep on
Criteo"; reference ``examples/criteo/``).

TPU-native design: one ``[B, 13 + 26]`` feature matrix per batch — 13
numeric columns and 26 categorical columns (already integerized; hashed
mod ``vocab_size`` here, the in-graph equivalent of the reference's
feature-column hash buckets).  The wide path is a linear model over the
one-hot categorical space implemented as embedding-gathers (a [B,26]
gather, not a [B, vocab] one-hot matmul — HBM-friendly); the deep path is
embeddings + MLP, whose matmuls ride the MXU in bf16.

Memory math — why ``vocab_size`` MUST be plumbed, not defaulted: the table
row count is ``26 * vocab_size``, so the default ``vocab_size=100_003``
allocates ``2,600,078 x 16`` float32 embeddings (~166 MB) plus the wide
column (~10 MB), and Adam's two moment slots triple that to ~530 MB —
before a single batch.  A test that builds the default config to score ten
rows pays all of it.  Every entry point therefore takes ``vocab_size``
from the model config (``HasModelConfig`` in the pipeline layer carries it
from Params to the map_fun); tests use a small prime like 1009 (~1.7 MB of
tables).  Above one host's memory the answer is :class:`WideDeepDense` +
the sharded embedding tier (``tensorflowonspark_tpu/embedding/``): the
fused table lives OUTSIDE the flax params, range-sharded across nodes.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.registry import register

NUM_NUMERIC = 13
NUM_CATEGORICAL = 26


class WideDeep(nn.Module):
    vocab_size: int = 100_003  # per-column hash-bucket count (prime)
    embed_dim: int = 16
    hidden: Sequence[int] = (256, 128, 64)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        """x: [B, 39] float32; cols 0..12 numeric, 13..38 categorical ids."""
        numeric = x[:, :NUM_NUMERIC].astype(self.compute_dtype)
        cat = jnp.mod(x[:, NUM_NUMERIC:].astype(jnp.int32), self.vocab_size)
        # Disjoint id space per column so one embedding table serves all 26
        # (single large gather beats 26 small ones on TPU).
        offsets = jnp.arange(NUM_CATEGORICAL, dtype=jnp.int32) * self.vocab_size
        flat_ids = cat + offsets[None, :]

        # Wide: linear-in-one-hot == per-id scalar weight, summed.
        wide_table = self.param(
            "wide_weights", nn.initializers.zeros, (NUM_CATEGORICAL * self.vocab_size, 1))
        wide = jnp.sum(jnp.take(wide_table, flat_ids, axis=0)[..., 0], axis=1, keepdims=True)
        wide = wide + nn.Dense(1, dtype=jnp.float32, name="wide_numeric")(
            x[:, :NUM_NUMERIC])

        # Deep: embeddings + MLP.
        embed_table = self.param(
            "embeddings", nn.initializers.normal(0.01),
            (NUM_CATEGORICAL * self.vocab_size, self.embed_dim))
        emb = jnp.take(embed_table, flat_ids, axis=0)  # [B, 26, D]
        deep = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1).astype(self.compute_dtype), numeric], axis=-1)
        for h in self.hidden:
            deep = nn.relu(nn.Dense(h, dtype=self.compute_dtype)(deep))
        deep = nn.Dense(1, dtype=jnp.float32, name="deep_head")(deep)
        return (wide + deep)[:, 0]  # [B] logits


@register("wide_deep")
def build_wide_deep(config: dict) -> WideDeep:
    return WideDeep(
        vocab_size=config.get("vocab_size", 100_003),
        embed_dim=config.get("embed_dim", 16),
        hidden=tuple(config.get("hidden", (256, 128, 64))),
        compute_dtype=jnp.bfloat16 if config.get("bf16", True) else jnp.float32,
    )


class WideDeepDense(nn.Module):
    """The DENSE half of wide-and-deep: everything except the tables.

    The fused embedding table (one row per flat categorical id, laid out
    ``[embed_dim deep floats | 1 wide weight]``) lives outside the flax
    params in the sharded embedding tier; this module consumes the rows a
    :class:`~tensorflowonspark_tpu.embedding.ShardedTable` lookup already
    gathered.  The math mirrors :class:`WideDeep` term for term (same
    reduction and dtype-cast order), and the param NAMES match
    (``wide_numeric`` / ``Dense_i`` / ``deep_head``) so flax's path-based
    RNG folds give the dense weights the same init streams.
    """

    vocab_size: int = 100_003  # for id-space checks + export config only
    embed_dim: int = 16
    hidden: Sequence[int] = (256, 128, 64)
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, rows):
        """x: [B, 39] raw features; rows: [B, 26, embed_dim + 1] gathered
        fused-table rows (last column = wide weight)."""
        numeric = x[:, :NUM_NUMERIC].astype(self.compute_dtype)
        wide = jnp.sum(rows[..., -1].astype(jnp.float32), axis=1,
                       keepdims=True)
        wide = wide + nn.Dense(1, dtype=jnp.float32, name="wide_numeric")(
            x[:, :NUM_NUMERIC])
        emb = rows[..., :self.embed_dim]
        deep = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1).astype(self.compute_dtype), numeric],
            axis=-1)
        for h in self.hidden:
            deep = nn.relu(nn.Dense(h, dtype=self.compute_dtype)(deep))
        deep = nn.Dense(1, dtype=jnp.float32, name="deep_head")(deep)
        return (wide + deep)[:, 0]


@register("wide_deep_dense")
def build_wide_deep_dense(config: dict) -> WideDeepDense:
    return WideDeepDense(
        vocab_size=config.get("vocab_size", 100_003),
        embed_dim=config.get("embed_dim", 16),
        hidden=tuple(config.get("hidden", (256, 128, 64))),
        compute_dtype=jnp.bfloat16 if config.get("bf16", True) else jnp.float32,
    )


def table_total_rows(config: dict) -> int:
    """Fused-table row count for a wide_deep config (26 disjoint column
    id spaces)."""
    return NUM_CATEGORICAL * int(config.get("vocab_size", 100_003))


def flat_categorical_ids(features: np.ndarray, vocab_size: int) -> np.ndarray:
    """[B, 39] raw features -> [B, 26] int64 fused-table ids (same mod +
    per-column offset the monolithic module applies in-graph)."""
    cat = np.mod(features[:, NUM_NUMERIC:].astype(np.int64), vocab_size)
    offsets = np.arange(NUM_CATEGORICAL, dtype=np.int64) * vocab_size
    return cat + offsets[None, :]


def init_dense_params(model: WideDeepDense, rng: jax.Array):
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy_x = jnp.zeros((1, NUM_NUMERIC + NUM_CATEGORICAL), jnp.float32)
    dummy_rows = jnp.zeros((1, NUM_CATEGORICAL, model.embed_dim + 1),
                           jnp.float32)
    return jit_init(model, rng, dummy_x, dummy_rows)["params"]


def make_sharded_grad_fn(model: WideDeepDense):
    """Jitted ``(params, rows, batch) -> ((loss, aux), (dense_g, row_g))``.

    ``row_g`` is the gradient w.r.t. the gathered fused rows — per-POSITION
    rows ([B, 26, D+1]); ``ShardedTable.apply_gradients`` dedups and
    scatter-adds them back to the owning shards.
    """

    def loss_fn(params, rows, batch):
        logits = model.apply({"params": params}, batch["features"], rows)
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.mean(optax_sigmoid_bce(logits, labels))
        preds = (logits > 0).astype(jnp.float32)
        return loss, {"accuracy": jnp.mean((preds == labels).astype(jnp.float32))}

    return jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True))


def init_params(model: WideDeep, rng: jax.Array):
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy = jnp.zeros((1, NUM_NUMERIC + NUM_CATEGORICAL), jnp.float32)
    return jit_init(model, rng, dummy)["params"]


def make_loss_fn(model: WideDeep):
    """Binary cross-entropy on {0,1} click labels."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["features"])
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.mean(optax_sigmoid_bce(logits, labels))
        preds = (logits > 0).astype(jnp.float32)
        return loss, {"accuracy": jnp.mean((preds == labels).astype(jnp.float32))}

    return loss_fn


def optax_sigmoid_bce(logits: jax.Array, labels: jax.Array) -> jax.Array:
    import optax

    return optax.sigmoid_binary_cross_entropy(logits, labels)


def synthetic_criteo(n: int, seed: int = 0) -> list[dict]:
    """Learnable synthetic CTR rows: label correlates with numeric col 0 and
    categorical col 13 parity."""
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n):
        numeric = rng.rand(NUM_NUMERIC).astype(np.float32)
        cat = rng.randint(0, 1000, NUM_CATEGORICAL).astype(np.float32)
        label = int((numeric[0] + (cat[0] % 2) * 0.5) > 0.75)
        rows.append({"features": np.concatenate([numeric, cat]), "label": label})
    return rows


def batch_to_arrays(items: list) -> dict:
    """(features, label) tuples or row dicts -> batch arrays."""
    if isinstance(items[0], dict):
        feats = np.stack([np.asarray(r["features"], np.float32) for r in items])
        labels = np.asarray([r["label"] for r in items], np.int32)
    else:
        feats = np.stack([np.asarray(f, np.float32) for f, _ in items])
        labels = np.asarray([l for _, l in items], np.int32)
    return {"features": feats, "label": labels}
