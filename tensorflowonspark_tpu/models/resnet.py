"""ResNet family (ResNet-50 flagship) — parity config 3 (BASELINE.json:9).

Reference: ``examples/imagenet/resnet`` ran TF-Keras ResNet-50 under
``MultiWorkerMirroredStrategy`` (NCCL all-reduce).  TPU-native redesign:

- bfloat16 activations / float32 params + batch stats — the MXU-friendly
  mixed-precision recipe (conv/matmul FLOPs run on the systolic array in
  bf16; the optimizer and normalization statistics stay in f32 for
  stability).
- NHWC layout (XLA:TPU's native conv layout; no transposes).
- Plain ``flax.linen.BatchNorm`` over the sharded batch axis: under
  ``jit`` + GSPMD a reduction over a dp-sharded axis compiles to a global
  (cross-replica) reduction over ICI automatically — the reference needed
  SyncBatchNorm machinery for this; here it falls out of the sharding.
- No data-dependent control flow; static shapes throughout, so the whole
  train step compiles to one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.registry import register
from tensorflowonspark_tpu.parallel.dp import accuracy, cross_entropy_loss


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (v1.5: stride
    on the 3x3, matching the reference Keras application and modern recipes)."""

    filters: int
    strides: int = 1
    compute_dtype: Any = jnp.bfloat16
    norm_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype)
        # Norm activations in bf16 (halves the HBM traffic of the most
        # bandwidth-bound op in the net); the batch mean/var reductions and
        # the running stats stay f32 inside flax regardless of this dtype.
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.norm_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branches start as identity,
        # which stabilises large-batch training (the standard TPU recipe).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(residual + y.astype(residual.dtype))


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    compute_dtype: Any = jnp.bfloat16
    norm_dtype: Any = jnp.bfloat16
    # "imagenet": 7x7/2 stem + 3x3/2 maxpool (224px inputs);
    # "cifar": 3x3/1 stem, no pool (32px inputs — the reference's cifar10
    # example family, ``examples/cifar10``);
    # "space_to_depth": the MLPerf stem optimization — input rearranged
    # [N,H,W,3] -> [N,H/2,W/2,12] (2x2 blocks stacked into channels) and the
    # 7x7/2 conv replaced by an equivalent-receptive-field 4x4/1 conv.  Same
    # output shape as "imagenet"; 4x more input channels feed the MXU's
    # 128-lane tiles far better than C=3, removing most of the stem cost
    # (PERF_NOTES.md "what would move it").  Opt-in: weights are not
    # interchangeable with the classic stem.
    stem: str = "imagenet"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.compute_dtype)
        if self.stem == "cifar":
            x = nn.Conv(self.width, (3, 3), use_bias=False,
                        dtype=self.compute_dtype, name="conv_init")(x)
        elif self.stem == "space_to_depth":
            n, h, w, c = x.shape
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            x = nn.Conv(self.width, (4, 4), use_bias=False,
                        dtype=self.compute_dtype, name="conv_init")(x)
        else:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                        dtype=self.compute_dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.norm_dtype, name="bn_init")(x)
        x = nn.relu(x)
        if self.stem != "cifar":
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(self.width * (2 ** stage), strides,
                                    self.compute_dtype,
                                    self.norm_dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def _dtypes(config: dict) -> dict:
    bf16 = config.get("bf16", True)
    return {
        "compute_dtype": jnp.bfloat16 if bf16 else jnp.float32,
        "norm_dtype": jnp.bfloat16 if bf16 and config.get("bf16_norm", True)
                      else jnp.float32,
    }


@register("resnet50")
def build_resnet50(config: dict) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        num_classes=config.get("num_classes", 1000),
        width=config.get("width", 64),
        stem=config.get("stem", "imagenet"),
        **_dtypes(config),
    )


@register("resnet18")
def build_resnet18(config: dict) -> ResNet:
    """Smaller sibling for tests/CI (same code path, 4x fewer blocks)."""
    return ResNet(
        stage_sizes=(2, 2, 2, 2),
        num_classes=config.get("num_classes", 1000),
        width=config.get("width", 64),
        **_dtypes(config),
    )


@register("resnet_cifar")
def build_resnet_cifar(config: dict) -> ResNet:
    """CIFAR-size ResNet (bottleneck, 3x3 stem, no maxpool) — the TPU
    counterpart of the reference's ``examples/cifar10`` model family.
    ``depth_blocks`` n gives 9n+2 layers (default n=3 → ResNet-29)."""
    n = config.get("depth_blocks", 3)
    return ResNet(
        stage_sizes=(n, n, n),
        num_classes=config.get("num_classes", 10),
        width=config.get("width", 16),
        stem="cifar",
        **_dtypes(config),
    )


def init_variables(model: ResNet, rng: jax.Array, image_size: int = 224):
    """Init {'params', 'batch_stats'} with a single dummy image (jitted,
    see ``registry.jit_init``)."""
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    return jit_init(model, rng, dummy, train=True)


def make_loss_fn(model: ResNet, weight_decay: float = 1e-4):
    """Loss over (params, batch_stats) with BN-stat mutation.

    Returns ``loss_fn(params, batch_stats, batch) -> (loss, (new_stats, aux))``
    suitable for ``make_bn_train_step``.
    """

    def loss_fn(params, batch_stats, batch):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"], train=True, mutable=["batch_stats"],
        )
        loss = cross_entropy_loss(logits, batch["label"])
        # L2 on conv/dense kernels only (standard recipe: no decay on BN).
        l2 = sum(jnp.sum(jnp.square(p)) for p in jax.tree.leaves(params)
                 if p.ndim > 1)
        loss = loss + weight_decay * 0.5 * l2
        return loss, (mutated["batch_stats"], {"accuracy": accuracy(logits, batch["label"])})

    return loss_fn


def synthetic_imagenet(n: int, image_size: int = 224, num_classes: int = 1000,
                       seed: int = 0) -> list[tuple[np.ndarray, int]]:
    """Deterministic synthetic images for hermetic benchmarks/tests."""
    rng = np.random.RandomState(seed)
    return [
        (rng.rand(image_size, image_size, 3).astype(np.float32), int(i % num_classes))
        for i in range(n)
    ]


def batch_to_arrays(items: list) -> dict:
    images = np.stack([np.asarray(img, np.float32) for img, _ in items])
    labels = np.asarray([l for _, l in items], np.int32)
    return {"image": images, "label": labels}
