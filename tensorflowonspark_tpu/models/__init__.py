"""Model zoo: the reference's example model families, rebuilt in Flax.

Reference ``examples/``: mnist (CNN), imagenet/inception (Inception-v3),
resnet (ResNet-50), criteo (wide-and-deep).  SURVEY.md §6 parity configs.
"""
