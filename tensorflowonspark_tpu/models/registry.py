"""Model registry: name → builder, so exported bundles can be re-instantiated
for inference from their JSON config alone (the SavedModel-signature
analogue used by ``checkpoint.load_bundle_cached`` and the pipeline layer)."""

from __future__ import annotations

import logging
from typing import Callable

logger = logging.getLogger(__name__)

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(builder: Callable):
        _REGISTRY[name] = builder
        return builder

    return deco


def build(config: dict):
    """Instantiate a model from a bundle config ``{"model": name, ...}``."""
    name = config.get("model")
    if name not in _REGISTRY:
        # model modules self-register on import; pull them in lazily, each on
        # its own so one missing family doesn't skip the rest
        import importlib

        for mod in ("linear", "mnist", "resnet", "inception", "wide_deep",
                    "transformer"):
            try:
                importlib.import_module(f"tensorflowonspark_tpu.models.{mod}")
            except ImportError:
                # a family with a missing optional dep stays unregistered;
                # the KeyError below lists what IS available
                logger.debug("model family %s unavailable", mod, exc_info=True)
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](config)


def jit_init(model, rng, *example_args, **init_kwargs):
    """``model.init`` as ONE jitted (persistently cacheable) program.

    Eager ``model.init`` compiles every layer op individually — tens of
    seconds of sequential tiny XLA:CPU compiles for deep nets on test
    boxes; jitting collapses it to a single cached compile.  All model
    modules' ``init_params``/``init_variables`` helpers route through here.
    """
    import jax

    return jax.jit(lambda r, a: model.init(r, *a, **init_kwargs))(
        rng, example_args)


def build_apply(config: dict) -> Callable:
    """Build a jitted ``apply(variables, x)`` for a bundle config.

    ``variables`` may be a bare params pytree or a full flax variables dict
    (``{"params": ..., "batch_stats": ...}`` for BN models, which are applied
    in inference mode).
    """
    import inspect

    import jax

    model = build(config)
    takes_train = "train" in inspect.signature(model.__call__).parameters

    def apply_fn(variables, x):
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        if takes_train:
            return model.apply(variables, x, train=False)
        return model.apply(variables, x)

    return jax.jit(apply_fn)
