"""Model registry: name → builder, so exported bundles can be re-instantiated
for inference from their JSON config alone (the SavedModel-signature
analogue used by ``checkpoint.load_bundle_cached`` and the pipeline layer)."""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(builder: Callable):
        _REGISTRY[name] = builder
        return builder

    return deco


def build(config: dict):
    """Instantiate a model from a bundle config ``{"model": name, ...}``."""
    name = config.get("model")
    if name not in _REGISTRY:
        # model modules self-register on import; pull them in lazily
        from tensorflowonspark_tpu.models import mnist  # noqa: F401

        try:
            from tensorflowonspark_tpu.models import resnet  # noqa: F401
            from tensorflowonspark_tpu.models import inception  # noqa: F401
            from tensorflowonspark_tpu.models import wide_deep  # noqa: F401
            from tensorflowonspark_tpu.models import transformer  # noqa: F401
        except ImportError:
            pass
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](config)


def build_apply(config: dict) -> Callable:
    """Build a jitted ``apply(params, x)`` for a bundle config."""
    import jax

    model = build(config)
    return jax.jit(lambda params, x: model.apply({"params": params}, x))
