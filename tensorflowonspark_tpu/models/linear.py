"""Minimal dense model (``{"model": "linear"}``) — flax-free, instant to
build and jit, used by the serving subsystem's tests and microbench where
the model under the gateway must cost microseconds, not compiles.

Params are a plain ``{"w": [in_dim, out_dim], "b": [out_dim]}`` tree, so a
bundle re-export with scaled weights is a one-liner — exactly what the
hot-reload tests need to observe a swap through changed predictions.
"""

from __future__ import annotations

import numpy as np

from tensorflowonspark_tpu.models.registry import register


class Linear:
    """`y = x @ w + b`; ``apply`` matches the registry's flax-style calling
    convention (``model.apply({"params": tree}, x)``)."""

    def __init__(self, config: dict):
        self.in_dim = int(config.get("in_dim", 16))
        self.out_dim = int(config.get("out_dim", self.in_dim))

    def __call__(self, x):  # registry's signature probe only (no 'train' arg)
        raise NotImplementedError("use model.apply(variables, x)")

    def apply(self, variables, x):
        p = variables["params"]
        return x @ p["w"] + p["b"]


@register("linear")
def build_linear(config: dict) -> Linear:
    return Linear(config)


def init_params(config: dict, scale: float = 1.0) -> dict:
    """Deterministic params: a (possibly rectangular) identity times
    ``scale`` — predictions are analytically checkable (`y == scale * x`
    when in_dim == out_dim), which the serving tests rely on."""
    model = Linear(config)
    return {
        "w": (np.eye(model.in_dim, model.out_dim) * scale).astype(np.float32),
        "b": np.zeros((model.out_dim,), np.float32),
    }
