"""Decoder-only transformer LM — the long-context / parallelism flagship.

The reference's model zoo stops at CNNs and wide-and-deep (its ``examples/``
tree; SURVEY.md §5.7 records that sequence length is never a sharded axis
there).  This family exists because long-context and model parallelism are
first-class in the TPU build:

- attention runs the Pallas flash kernel (``ops/attention.py``) on TPU, or
  ring/Ulysses sequence parallelism (``parallel/sp.py``) when a mesh with an
  ``sp`` axis is supplied;
- param layouts follow ``parallel/tp.TRANSFORMER_TP_RULES`` (Megatron
  column/row parallel over ``tp``, optionally composed with fsdp);
- the FFN can be a dense SwiGLU or an expert-parallel MoE
  (``parallel/ep.MoEMLP``) over ``ep``.

Pre-norm RMSNorm + RoPE, bf16 compute / f32 params — the standard
MXU-friendly recipe.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.models.registry import register
from tensorflowonspark_tpu.ops.attention import flash_attention
from tensorflowonspark_tpu.parallel.tp import constrain

BATCH = ("dp", "fsdp")


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary embedding, ``x: [B, S, H, D]``, ``positions: [S]``."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-math.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon)
        return (norm * scale).astype(x.dtype)


class Attention(nn.Module):
    n_heads: int
    d_head: int
    rope_theta: float = 10000.0
    attn_impl: str = "auto"       # auto | pallas | xla | reference | ring | ulysses
    mesh: Optional[Any] = None    # required for ring/ulysses
    compute_dtype: Any = jnp.bfloat16
    decode: bool = False          # autoregressive single-token mode (KV cache)
    max_decode_len: int = 0

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        h, dh = self.n_heads, self.d_head
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (h, dh), axis=-1, use_bias=False, name=name,
            dtype=self.compute_dtype)
        q, k, v = dense("q_proj")(x), dense("k_proj")(x), dense("v_proj")(x)
        if self.decode:
            return self._decode_step(x, q, k, v)
        positions = jnp.arange(s)
        q = apply_rope(q, positions, self.rope_theta)
        k = apply_rope(k, positions, self.rope_theta)
        q = constrain(q, P(BATCH, "sp", "tp", None))
        k = constrain(k, P(BATCH, "sp", "tp", None))
        v = constrain(v, P(BATCH, "sp", "tp", None))
        if self.attn_impl in ("ring", "ulysses"):
            if self.mesh is None:
                raise ValueError("ring/ulysses attention needs mesh=")
            from tensorflowonspark_tpu.parallel.sp import (
                sequence_parallel_attention,
            )
            out = sequence_parallel_attention(self.mesh, q, k, v, causal=True,
                                              impl=self.attn_impl)
        else:
            impl = None if self.attn_impl == "auto" else self.attn_impl
            out = flash_attention(q, k, v, causal=True, impl=impl)
        out = nn.DenseGeneral(x.shape[-1], axis=(-2, -1), use_bias=False,
                              name="o_proj", dtype=self.compute_dtype)(out)
        return out

    def _decode_step(self, x, q, k, v):
        """``s`` tokens through a static-size KV cache (``cache`` collection).

        Handles BOTH serving phases with one code path and static shapes
        (the cache is ``[B, max_decode_len, H, D]``; masking does the rest):

        - **prefill** (``s == prompt_len``): the whole prompt runs in ONE
          forward, writing cache slots ``[cur, cur+s)`` — queries attend
          causally within the slab and to everything before it;
        - **decode** (``s == 1``): the classic single-token step.

        So a serving loop issues O(1) compiled calls for the prompt (one
        prefill shape + one decode shape) instead of O(prompt_len) — the
        standard prefill/decode split of TPU serving stacks.
        """
        if self.max_decode_len <= 0:
            raise ValueError("decode mode needs max_decode_len > 0")
        b, s, h, dh = q.shape
        L = self.max_decode_len
        ck = self.variable("cache", "k", jnp.zeros, (b, L, h, dh),
                           self.compute_dtype)
        cv = self.variable("cache", "v", jnp.zeros, (b, L, h, dh),
                           self.compute_dtype)
        idx = self.variable("cache", "index",
                            lambda: jnp.zeros((), jnp.int32))
        cur = idx.value
        pos = cur + jnp.arange(s)  # RoPE positions of this slab
        q = apply_rope(q, pos, self.rope_theta)
        k = apply_rope(k, pos, self.rope_theta)
        ck.value = jax.lax.dynamic_update_slice(ck.value, k, (0, cur, 0, 0))
        cv.value = jax.lax.dynamic_update_slice(cv.value, v, (0, cur, 0, 0))
        idx.value = cur + s
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            ck.value.astype(jnp.float32))
        logits = logits / math.sqrt(dh)
        # query at slab offset i sees cache positions <= cur + i
        mask = (jnp.arange(L)[None, None, None, :]
                <= cur + jnp.arange(s)[None, None, :, None])
        logits = jnp.where(mask, logits, -1e30)
        weights = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights,
                         cv.value.astype(jnp.float32))
        out = out.astype(self.compute_dtype)
        return nn.DenseGeneral(x.shape[-1], axis=(-2, -1), use_bias=False,
                               name="o_proj", dtype=self.compute_dtype)(out)


class SwiGLU(nn.Module):
    d_ff: int
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, use_bias=False, name=name, dtype=self.compute_dtype)
        gate = jax.nn.silu(dense(self.d_ff, "gate_proj")(x))
        up = dense(self.d_ff, "up_proj")(x)
        h = constrain(gate * up, P(BATCH, "sp", "tp"))
        return dense(x.shape[-1], "down_proj")(h)


class Block(nn.Module):
    n_heads: int
    d_head: int
    d_ff: int
    n_experts: int = 0
    moe_top_k: int = 2
    rope_theta: float = 10000.0
    attn_impl: str = "auto"
    mesh: Optional[Any] = None
    compute_dtype: Any = jnp.bfloat16
    decode: bool = False
    max_decode_len: int = 0

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.n_heads, self.d_head, self.rope_theta,
                          self.attn_impl, self.mesh, self.compute_dtype,
                          self.decode, self.max_decode_len,
                          name="attn")(RMSNorm(name="attn_norm")(x))
        x = constrain(x, P(BATCH, "sp", None))
        if self.n_experts:
            from tensorflowonspark_tpu.parallel.ep import MoEMLP

            ffn = MoEMLP(x.shape[-1], self.d_ff, self.n_experts,
                         self.moe_top_k, compute_dtype=self.compute_dtype,
                         name="moe")
        else:
            ffn = SwiGLU(self.d_ff, self.compute_dtype, name="mlp")
        x = x + ffn(RMSNorm(name="mlp_norm")(x))
        return constrain(x, P(BATCH, "sp", None))


class Transformer(nn.Module):
    """Decoder-only LM.  ``__call__(input_ids: [B, S]) -> logits [B, S, V]``."""

    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    d_head: int = 0          # 0 ⇒ d_model // n_heads
    d_ff: int = 0            # 0 ⇒ 4 * d_model
    n_experts: int = 0       # 0 ⇒ dense FFN
    moe_top_k: int = 2
    rope_theta: float = 10000.0
    attn_impl: str = "auto"
    mesh: Optional[Any] = None
    compute_dtype: Any = jnp.bfloat16
    decode: bool = False
    max_decode_len: int = 0
    # Return final_norm hidden states instead of logits (the lm_head matmul
    # is then fused into a blockwise loss — see ops/xent.py).  Init with the
    # default model so lm_head params exist; apply may skip them.
    return_hidden: bool = False
    # Rematerialize each block's activations in the backward pass
    # (jax.checkpoint): activation memory drops from O(n_layers) residuals
    # to O(1) per block at ~1/3 extra FLOPs — the standard long-context /
    # large-batch trade on HBM-bound TPUs.
    remat: bool = False

    @nn.compact
    def __call__(self, input_ids):
        dh = self.d_head or self.d_model // self.n_heads
        dff = self.d_ff or 4 * self.d_model
        emb = nn.Embed(self.vocab_size, self.d_model, name="embed",
                       dtype=self.compute_dtype)
        x = emb(input_ids)
        x = constrain(x, P(BATCH, "sp", None))
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.n_layers):
            x = block_cls(self.n_heads, dh, dff, self.n_experts, self.moe_top_k,
                          self.rope_theta, self.attn_impl, self.mesh,
                          self.compute_dtype, self.decode, self.max_decode_len,
                          name=f"block_{i}")(x)
        x = RMSNorm(name="final_norm")(x)
        if self.return_hidden:
            return x
        logits = nn.Dense(self.vocab_size, use_bias=False, name="lm_head",
                          dtype=self.compute_dtype)(x)
        return constrain(logits.astype(jnp.float32), P(BATCH, "sp", None))


@register("transformer")
def build_transformer(config: dict) -> Transformer:
    return Transformer(
        vocab_size=int(config.get("vocab_size", 32000)),
        d_model=int(config.get("d_model", 512)),
        n_layers=int(config.get("n_layers", 4)),
        n_heads=int(config.get("n_heads", 8)),
        d_head=int(config.get("d_head", 0)),
        d_ff=int(config.get("d_ff", 0)),
        n_experts=int(config.get("n_experts", 0)),
        moe_top_k=int(config.get("moe_top_k", 2)),
        rope_theta=float(config.get("rope_theta", 10000.0)),
        attn_impl=config.get("attn_impl", "auto"),
        compute_dtype=jnp.bfloat16 if config.get("bf16", True) else jnp.float32,
        remat=bool(config.get("remat", False)),
    )


def pad_batch(token_lists, seq_len: int, pad_id: int = 0):
    """Ragged token lists → ``{"input_ids": [B,S], "loss_mask": [B,S]}``.

    The mask marks REAL tokens; ``make_loss_fn`` averages the next-token
    loss over real target positions only, so padding contributes nothing to
    the LM loss (causal attention keeps real positions blind to right-pads).
    Sequences longer than ``seq_len`` are truncated.

    MoE caveat: the expert router (``parallel/ep.py``) runs over ALL
    positions — pad tokens still occupy capacity slots and enter the
    load-balance aux statistics.  For ``n_experts > 0`` training prefer
    packing sequences back-to-back over padding ragged ones.
    """
    import numpy as np

    b = len(token_lists)
    ids = np.full((b, seq_len), pad_id, np.int32)
    mask = np.zeros((b, seq_len), np.float32)
    for i, toks in enumerate(token_lists):
        n = min(len(toks), seq_len)
        ids[i, :n] = np.asarray(toks[:n], np.int32)
        mask[i, :n] = 1.0
    return {"input_ids": ids, "loss_mask": mask}


def pack_batch(token_lists, seq_len: int, eos_id: int, pad_id: int = 0,
               n_rows: int | None = None):
    """Greedy sequence packing — the padding-free alternative to ``pad_batch``.

    Documents are laid back-to-back (each terminated by ``eos_id``) into
    fixed ``seq_len`` rows, first-fit: a document goes into the first row
    with room, else opens a new row; documents longer than ``seq_len``-1 are
    split across rows (GPT-style chunking).  Returns ``{"input_ids": [B,S],
    "loss_mask": [B,S]}`` where the mask marks real tokens (EOS included —
    predicting document ends is part of the LM task; only tail padding is
    masked out).

    The natural row count is CONTENT-DEPENDENT — under a jitted train loop
    a varying ``B`` means a recompile per new shape, and ``B`` must divide
    the batch mesh axes.  Pass ``n_rows`` to fix the batch dimension: short
    packs are padded with all-masked rows, and a pack that needs more than
    ``n_rows`` rows raises (size your budget from the token count:
    ``n_rows >= ceil(sum(len(d)+1) / seq_len)`` plus fragmentation slack).

    Semantics note: this is standard dense packing WITHOUT attention
    resetting — tokens may attend across document boundaries within a row
    (the usual GPT pretraining trade; the EOS token is the separator signal).
    For MoE models this is the recommended input shape: pad tokens occupy
    expert capacity, packed tokens don't (see ``pad_batch``'s caveat).
    """
    import numpy as np

    rows: list[list[int]] = []
    for toks in token_lists:
        doc = list(toks) + [eos_id]
        placed = False
        for row in rows:
            if len(row) + len(doc) <= seq_len:
                row.extend(doc)
                placed = True
                break
        if not placed:
            while len(doc) > seq_len:
                rows.append(doc[:seq_len])
                doc = doc[seq_len:]
            if doc:
                rows.append(doc)
    if n_rows is not None:
        if len(rows) > n_rows:
            raise ValueError(
                f"pack needs {len(rows)} rows of {seq_len} but n_rows={n_rows}; "
                "raise n_rows or feed fewer tokens per pack")
        rows.extend([] for _ in range(n_rows - len(rows)))
    b = len(rows)
    ids = np.full((b, seq_len), pad_id, np.int32)
    mask = np.zeros((b, seq_len), np.float32)
    for i, row in enumerate(rows):
        ids[i, : len(row)] = np.asarray(row, np.int32)
        mask[i, : len(row)] = 1.0
    return {"input_ids": ids, "loss_mask": mask}


def greedy_generate(model: Transformer, params, prompt_ids, max_new_tokens: int,
                    max_decode_len: int = 0, temperature: float = 0.0,
                    top_k: int = 0, seed: int = 0,
                    eos_id: int | None = None, pad_id: int = 0):
    """Autoregressive decoding through the static KV cache.

    ``prompt_ids: [B, S] int32`` → ``[B, S + max_new_tokens]``.  Serving
    runs in the standard two phases against a static ``[B, L, H, D]`` cache
    (``Attention._decode_step``): one chunked PREFILL forward over the whole
    prompt, then ONE-token decode steps — two compiled programs total,
    regardless of prompt length.  No reference counterpart (its models are
    CNNs); this exists because the LM family is first-class here.

    ``temperature == 0`` (default) is greedy argmax; ``> 0`` samples from
    ``softmax(logits / temperature)``, optionally truncated to the
    ``top_k`` most likely tokens.  Sampling is deterministic under ``seed``.

    ``eos_id`` enables early stopping: a row that emits it keeps its EOS and
    produces ``pad_id`` from then on, and the loop exits once EVERY row has
    finished (possibly before ``max_new_tokens``, so the returned width
    varies).  The per-row masking happens host-side between steps — the
    compiled decode step itself stays batch-static, so no recompiles.
    """
    import numpy as np

    b, s = prompt_ids.shape
    L = max_decode_len or (s + max_new_tokens)
    if L < s + max_new_tokens:
        raise ValueError(f"max_decode_len {L} < prompt {s} + new {max_new_tokens}")
    dmodel = model.clone(decode=True, max_decode_len=L, return_hidden=False)
    # flax init RUNS the decode step, so the returned cache already holds the
    # dummy token with index=1 — zero it to get a genuinely empty cache.
    cache = jax.tree.map(jnp.zeros_like, dmodel.init(
        jax.random.PRNGKey(0), jnp.zeros((b, 1), jnp.int32))["cache"])

    @jax.jit
    def step(params, cache, tok):
        # params is an ARGUMENT, not a closure capture: captured arrays
        # would be baked into the executable as constants (a second copy
        # of the weights in HBM for the serving loop).
        logits, mutated = dmodel.apply({"params": params, "cache": cache},
                                       tok, mutable=["cache"])
        return mutated["cache"], logits[:, -1]

    @jax.jit
    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / temperature
        if top_k:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    key = jax.random.PRNGKey(seed)
    tokens = [np.asarray(prompt_ids[:, i]) for i in range(s)]
    # Chunked prefill: ONE forward over the whole prompt populates the KV
    # cache and yields the last position's logits — O(1) compiled calls
    # (one [B,S] prefill program + one [B,1] decode program) instead of the
    # O(S) sequential single-token steps of the naive loop.
    cache, logits = step(params, cache, jnp.asarray(prompt_ids, jnp.int32))
    finished = np.zeros((b,), bool)
    for _ in range(max_new_tokens):
        key, sub = jax.random.split(key)
        nxt = np.asarray(pick(logits, sub))
        if eos_id is not None:
            nxt = np.where(finished, pad_id, nxt)
        tokens.append(nxt)
        if eos_id is not None:
            finished |= nxt == eos_id
            if finished.all():
                break
        cache, logits = step(params, cache, jnp.asarray(nxt[:, None]))
    return np.stack(tokens, axis=1)


def make_loss_fn(model: Transformer, aux_loss_coef: float = 0.01,
                 vocab_chunk: int = 0, router_z_coef: float = 1e-3):
    """Next-token LM loss.  Batch: ``{"input_ids": [B, S] int32}`` (targets
    are inputs shifted left; final position predicts a discarded token).
    MoE auxiliary losses are collected from the ``aux_loss`` sow:
    ``load_balance`` leaves weighted by ``aux_loss_coef`` and ``router_z``
    leaves (ST-MoE z-loss) by ``router_z_coef``.

    ``vocab_chunk > 0`` fuses the lm_head matmul into a blockwise
    cross-entropy (``ops/xent.py``): the ``[B, S, V]`` logits are never
    materialized — the HBM-dominant op at large vocab.  Not for
    tensor-parallel vocab-sharded heads (use the dense path there)."""

    def _reduce(nll, batch, updates):
        mask = batch.get("loss_mask")
        if mask is not None:
            mask = mask[:, 1:].astype(jnp.float32)
            loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        else:
            loss = jnp.mean(nll)
        aux = jnp.asarray(0.0)
        z = jnp.asarray(0.0)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                updates.get("aux_loss", {}))[0]:
            if any("router_z" in str(p) for p in path):
                z = z + leaf
            else:
                aux = aux + leaf
        total = loss + aux_loss_coef * aux + router_z_coef * z
        return total, {"lm_loss": loss, "aux_loss": aux, "router_z_loss": z}

    if vocab_chunk:
        from tensorflowonspark_tpu.ops.xent import blockwise_cross_entropy

        hidden_model = model.clone(return_hidden=True)

        def fused_loss_fn(params, batch):
            ids = batch["input_ids"]
            h, updates = hidden_model.apply({"params": params}, ids,
                                            mutable=["aux_loss"])
            b, s, d = h.shape
            h = h[:, :-1].reshape(b * (s - 1), d)
            targets = ids[:, 1:].reshape(-1)
            nll = blockwise_cross_entropy(
                h, params["lm_head"]["kernel"].astype(h.dtype), targets,
                chunk=vocab_chunk)
            return _reduce(nll.reshape(b, s - 1), batch, updates)

        return fused_loss_fn

    def loss_fn(params, batch):
        ids = batch["input_ids"]
        logits, updates = model.apply({"params": params}, ids,
                                      mutable=["aux_loss"])
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
        targets = ids[:, 1:]
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return _reduce(nll, batch, updates)

    return loss_fn
