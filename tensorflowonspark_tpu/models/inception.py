"""Inception-v3 — parity config 5 (BASELINE.json:11: "Inception-v3 streaming
inference via TFCluster.inference RDD→TPU"; reference
``examples/imagenet/inception/``).

Faithful Inception-v3 topology (stem → 3xA → B → 4xC → D → 2xE → pool →
head, Szegedy et al. 2015) in Flax, TPU-first: bf16 activations/f32 BN,
NHWC, every conv+BN+relu fused by XLA into MXU-friendly blocks.  The 299x299
input of the reference is kept as the default but any size >= 75 works
(fully-convolutional until the global pool).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.registry import register


class ConvBN(nn.Module):
    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: str = "SAME"
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.compute_dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                         epsilon=1e-3, dtype=jnp.float32)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        b1 = cb(64, (1, 1))(x, train)
        b5 = cb(48, (1, 1))(x, train)
        b5 = cb(64, (5, 5))(b5, train)
        b3 = cb(64, (1, 1))(x, train)
        b3 = cb(96, (3, 3))(b3, train)
        b3 = cb(96, (3, 3))(b3, train)
        bp = cb(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        b3 = cb(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        bd = cb(64, (1, 1))(x, train)
        bd = cb(96, (3, 3))(bd, train)
        bd = cb(96, (3, 3), strides=(2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        c7 = self.channels_7x7
        b1 = cb(192, (1, 1))(x, train)
        b7 = cb(c7, (1, 1))(x, train)
        b7 = cb(c7, (1, 7))(b7, train)
        b7 = cb(192, (7, 1))(b7, train)
        bd = cb(c7, (1, 1))(x, train)
        bd = cb(c7, (7, 1))(bd, train)
        bd = cb(c7, (1, 7))(bd, train)
        bd = cb(c7, (7, 1))(bd, train)
        bd = cb(192, (1, 7))(bd, train)
        bp = cb(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        b3 = cb(192, (1, 1))(x, train)
        b3 = cb(320, (3, 3), strides=(2, 2), padding="VALID")(b3, train)
        b7 = cb(192, (1, 1))(x, train)
        b7 = cb(192, (1, 7))(b7, train)
        b7 = cb(192, (7, 1))(b7, train)
        b7 = cb(192, (3, 3), strides=(2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        b1 = cb(320, (1, 1))(x, train)
        b3 = cb(384, (1, 1))(x, train)
        b3 = jnp.concatenate(
            [cb(384, (1, 3))(b3, train), cb(384, (3, 1))(b3, train)], axis=-1)
        bd = cb(448, (1, 1))(x, train)
        bd = cb(384, (3, 3))(bd, train)
        bd = jnp.concatenate(
            [cb(384, (1, 3))(bd, train), cb(384, (3, 1))(bd, train)], axis=-1)
        bp = cb(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype)
        x = x.astype(self.compute_dtype)
        # stem
        x = cb(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = cb(32, (3, 3), padding="VALID")(x, train)
        x = cb(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cb(80, (1, 1), padding="VALID")(x, train)
        x = cb(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # mixed 5b, 5c, 5d
        x = InceptionA(32, self.compute_dtype)(x, train)
        x = InceptionA(64, self.compute_dtype)(x, train)
        x = InceptionA(64, self.compute_dtype)(x, train)
        # mixed 6a
        x = InceptionB(self.compute_dtype)(x, train)
        # mixed 6b-6e
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7, self.compute_dtype)(x, train)
        # mixed 7a
        x = InceptionD(self.compute_dtype)(x, train)
        # mixed 7b, 7c
        x = InceptionE(self.compute_dtype)(x, train)
        x = InceptionE(self.compute_dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


@register("inception_v3")
def build_inception_v3(config: dict) -> InceptionV3:
    return InceptionV3(
        num_classes=config.get("num_classes", 1000),
        compute_dtype=jnp.bfloat16 if config.get("bf16", True) else jnp.float32,
    )


def init_variables(model: InceptionV3, rng: jax.Array, image_size: int = 299):
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    return jit_init(model, rng, dummy, train=True)


def synthetic_images(n: int, image_size: int = 299, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.RandomState(seed)
    return [rng.rand(image_size, image_size, 3).astype(np.float32) for _ in range(n)]
