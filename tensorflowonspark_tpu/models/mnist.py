"""MNIST CNN — the reference's flagship example family
(``examples/mnist/**``: parity configs 1 and 2, BASELINE.json:7-8).

A small convnet in Flax; bfloat16 activations on TPU with float32 params
(the standard mixed-precision recipe: MXU-friendly compute, stable optimizer
state).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.registry import register
from tensorflowonspark_tpu.parallel.dp import accuracy, cross_entropy_loss


class MnistCNN(nn.Module):
    num_classes: int = 10
    features: tuple = (32, 64)
    dense: int = 256
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        for feat in self.features:
            x = nn.Conv(feat, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense, dtype=self.compute_dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@register("mnist_cnn")
def build_mnist(config: dict) -> MnistCNN:
    return MnistCNN(
        num_classes=config.get("num_classes", 10),
        features=tuple(config.get("features", (32, 64))),
        dense=config.get("dense", 256),
        compute_dtype=jnp.bfloat16 if config.get("bf16") else jnp.float32,
    )


def init_params(model: MnistCNN, rng: jax.Array, image_shape=(28, 28, 1)):
    from tensorflowonspark_tpu.models.registry import jit_init

    dummy = jnp.zeros((1, *image_shape), jnp.float32)
    return jit_init(model, rng, dummy)["params"]


def make_loss_fn(model: MnistCNN):
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["image"])
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"accuracy": accuracy(logits, batch["label"])}

    return loss_fn


def synthetic_mnist(n: int, seed: int = 0) -> list[tuple[np.ndarray, int]]:
    """Deterministic learnable synthetic digits: class k lights up stripe k.

    Keeps tests/examples hermetic (no dataset download in this environment);
    the task is linearly separable so a few steps of SGD visibly reduce loss.
    """
    rng = np.random.RandomState(seed)
    samples = []
    for i in range(n):
        label = i % 10
        img = rng.rand(28, 28, 1).astype(np.float32) * 0.1
        img[label * 2 : label * 2 + 2, :, 0] += 1.0
        samples.append((img, label))
    return samples


def batch_to_arrays(items: list) -> dict:
    """Convert a list of (image, label) samples into a batch dict."""
    images = np.stack([np.asarray(i, np.float32).reshape(28, 28, 1) for i, _ in items])
    labels = np.asarray([l for _, l in items], np.int32)
    return {"image": images, "label": labels}
