"""On-demand g++ build of the native libraries (shared helper).

pybind11 is not available in this environment, so every native component is
a plain C-ABI shared library built with the baked-in compiler and consumed
via ctypes.  Concurrent node processes may race to build: compile into a
temp file and ``os.replace`` (atomic) so every racer ends with a whole
library.
"""

from __future__ import annotations

import os
import subprocess
import tempfile

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          os.pardir, "_native_build")


def build_native_lib(src_path: str, lib_name: str,
                     extra_flags: tuple = ()) -> str:
    cache = os.path.abspath(_CACHE_DIR)
    os.makedirs(cache, exist_ok=True)
    lib_path = os.path.join(cache, lib_name)
    if (os.path.exists(lib_path)
            and os.path.getmtime(lib_path) >= os.path.getmtime(src_path)):
        return lib_path
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src_path,
             "-o", tmp, *extra_flags],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, lib_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return lib_path
