// Shared-memory SPSC ring buffer — the native same-host data-plane transport.
//
// The reference's in-host data plane was a multiprocessing.managers proxy
// queue between the pyspark worker and the TF process (TFManager.py,
// SURVEY.md §3.2): every sample paid a pickle + TCP-loopback + proxy hop.
// This is the TPU build's native equivalent: a single-producer /
// single-consumer byte ring in POSIX shared memory, lock-free (C++11
// acquire/release atomics), with records framed [u32 len][payload].  The
// Python side (shm_ring.py) moves pickled items through it when feeder and
// node share a host; cross-host feeding stays on the TCP DataServer.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 shm_ring.cc -o libshm_ring.so
//
// SPSC contract: exactly one pusher thread and one popper thread per ring.
// The DataClient/DataServer pairing guarantees this (one driver feed stream
// per node; replies on a second ring in the other direction).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  uint64_t capacity;              // data region size in bytes
  std::atomic<uint64_t> head;     // total bytes written (mod capacity = offset)
  std::atomic<uint64_t> tail;     // total bytes read
  std::atomic<uint32_t> closed;   // producer hung up
  uint32_t magic;
};

constexpr uint32_t kMagic = 0x544F5352;  // "TOSR"

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  int fd;
};

inline uint64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Adaptive wait: spin briefly, then sleep 50us — latency where it matters,
// no busy-burn while blocked on an empty/full ring.
inline void backoff(int iter) {
  if (iter < 64) return;
  timespec ts{0, 50 * 1000};
  nanosleep(&ts, nullptr);
}

void copy_in(Ring* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = r->hdr->capacity - off;
  if (first >= len) {
    memcpy(r->data + off, src, len);
  } else {
    memcpy(r->data + off, src, first);
    memcpy(r->data, src + first, len - first);
  }
}

void copy_out(Ring* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  uint64_t off = pos % r->hdr->capacity;
  uint64_t first = r->hdr->capacity - off;
  if (first >= len) {
    memcpy(dst, r->data + off, len);
  } else {
    memcpy(dst, r->data + off, first);
    memcpy(dst + first, r->data, len - first);
  }
}

}  // namespace

extern "C" {

// Create (creat=1) or attach (creat=0) a ring named `name` (shm_open name,
// must start with '/').  Returns an opaque handle or null.
void* tos_ring_open(const char* name, uint64_t capacity, int creat) {
  int fd = creat ? shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600)
                 : shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t map_len = sizeof(Header) + capacity;
  if (creat && ftruncate(fd, (off_t)map_len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!creat) {
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_len = st.st_size;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring;
  r->hdr = static_cast<Header*>(mem);
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  r->map_len = map_len;
  r->fd = fd;
  if (creat) {
    r->hdr->capacity = capacity;
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->closed.store(0, std::memory_order_relaxed);
    r->hdr->magic = kMagic;
  } else if (r->hdr->magic != kMagic) {
    munmap(mem, map_len);
    close(fd);
    delete r;
    return nullptr;
  }
  return r;
}

// Push one record assembled from TWO buffers (frame flag + payload) without
// requiring the caller to join them first — the zero-copy batched push path:
// Python hands the flag byte and the payload view separately and the only
// copy is the memcpy into the ring itself.  This is THE ring-commit
// implementation; the single-buffer push delegates here so the
// wait/backoff/closed/timeout protocol exists exactly once.
// 1 = ok, 0 = timeout, -1 = ring closed, -2 = too large.
int tos_ring_push2(void* h, const uint8_t* a, uint64_t alen,
                   const uint8_t* b, uint64_t blen, int timeout_ms) {
  Ring* r = static_cast<Ring*>(h);
  uint64_t len = alen + blen;
  uint64_t need = len + 4;
  if (need > r->hdr->capacity) return -2;
  uint64_t deadline = now_ms() + (uint64_t)timeout_ms;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  int iter = 0;
  for (;;) {
    if (r->hdr->closed.load(std::memory_order_acquire)) return -1;
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (r->hdr->capacity - (head - tail) >= need) break;
    if (timeout_ms >= 0 && now_ms() >= deadline) return 0;
    backoff(iter++);
  }
  uint8_t lenbuf[4] = {uint8_t(len), uint8_t(len >> 8), uint8_t(len >> 16),
                       uint8_t(len >> 24)};
  copy_in(r, head, lenbuf, 4);
  if (alen) copy_in(r, head + 4, a, alen);
  if (blen) copy_in(r, head + 4 + alen, b, blen);
  r->hdr->head.store(head + need, std::memory_order_release);
  return 1;
}

// Push one single-buffer record.  Same return codes as push2.
int tos_ring_push(void* h, const uint8_t* data, uint64_t len, int timeout_ms) {
  return tos_ring_push2(h, data, len, nullptr, 0, timeout_ms);
}

// Size of the next record without consuming it.
// >=0 = size, -1 = empty+closed (EOF), 0..: note 0-length records are legal,
// so empty-and-open is signalled by -3 (timeout) instead.
int64_t tos_ring_next_size(void* h, int timeout_ms) {
  Ring* r = static_cast<Ring*>(h);
  uint64_t deadline = now_ms() + (uint64_t)timeout_ms;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  int iter = 0;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (head - tail >= 4) {
      uint8_t lenbuf[4];
      copy_out(r, tail, lenbuf, 4);
      return (int64_t)(uint32_t(lenbuf[0]) | uint32_t(lenbuf[1]) << 8 |
                       uint32_t(lenbuf[2]) << 16 | uint32_t(lenbuf[3]) << 24);
    }
    if (r->hdr->closed.load(std::memory_order_acquire)) return -1;
    if (timeout_ms >= 0 && now_ms() >= deadline) return -3;
    backoff(iter++);
  }
}

// Pop one record into out (cap bytes).  >=0 = record size, -1 = EOF,
// -2 = out buffer too small (record left in place), -3 = timeout.
int64_t tos_ring_pop(void* h, uint8_t* out, uint64_t cap, int timeout_ms) {
  Ring* r = static_cast<Ring*>(h);
  int64_t size = tos_ring_next_size(h, timeout_ms);
  if (size < 0) return size;
  if ((uint64_t)size > cap) return -2;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  copy_out(r, tail + 4, out, (uint64_t)size);
  r->hdr->tail.store(tail + 4 + (uint64_t)size, std::memory_order_release);
  return size;
}

uint64_t tos_ring_capacity(void* h) {
  return static_cast<Ring*>(h)->hdr->capacity;
}

void tos_ring_close_write(void* h) {
  static_cast<Ring*>(h)->hdr->closed.store(1, std::memory_order_release);
}

int tos_ring_is_closed(void* h) {
  return (int)static_cast<Ring*>(h)->hdr->closed.load(std::memory_order_acquire);
}

uint64_t tos_ring_size(void* h) {
  Ring* r = static_cast<Ring*>(h);
  return r->hdr->head.load(std::memory_order_acquire) -
         r->hdr->tail.load(std::memory_order_acquire);
}

void tos_ring_detach(void* h) {
  Ring* r = static_cast<Ring*>(h);
  munmap(r->hdr, r->map_len);
  close(r->fd);
  delete r;
}

int tos_ring_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
