// Native data-path codec for tensorflowonspark_tpu.
//
// The reference delegated TFRecord I/O to the tensorflow-hadoop Java
// InputFormat and the TF C++ runtime (SURVEY.md §2.2); this is the TPU
// build's native equivalent for the host-side input pipeline: CRC-32C
// (Castagnoli) via slice-by-8, plus bulk record framing/unframing so Python
// touches each byte once.  Exposed through a minimal C ABI consumed with
// ctypes (no pybind11 in this environment).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 tfrecord_codec.cc -o libtfrecord_codec.so

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

uint32_t kTable[8][256];
bool kInit = false;

void init_tables() {
  if (kInit) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int k = 0; k < 8; ++k) crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    kTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = kTable[0][i];
    for (int s = 1; s < 8; ++s) {
      crc = kTable[0][crc & 0xFF] ^ (crc >> 8);
      kTable[s][i] = crc;
    }
  }
  kInit = true;
}

inline uint32_t le32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // little-endian hosts only (x86/ARM/TPU-VM)
}

}  // namespace

extern "C" {

// Raw CRC-32C of buf[0..n); crc is the running value (0 to start).
uint32_t tos_crc32c(const uint8_t* buf, size_t n, uint32_t crc) {
  init_tables();
  crc ^= 0xFFFFFFFFu;
  // slice-by-8 over aligned middle
  while (n >= 8) {
    crc ^= le32(buf);
    uint32_t hi = le32(buf + 4);
    crc = kTable[7][crc & 0xFF] ^ kTable[6][(crc >> 8) & 0xFF] ^
          kTable[5][(crc >> 16) & 0xFF] ^ kTable[4][crc >> 24] ^
          kTable[3][hi & 0xFF] ^ kTable[2][(hi >> 8) & 0xFF] ^
          kTable[1][(hi >> 16) & 0xFF] ^ kTable[0][hi >> 24];
    buf += 8;
    n -= 8;
  }
  while (n--) crc = kTable[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

static inline uint32_t masked(uint32_t crc) {
  return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

// Scan a buffer of framed TFRecords, verifying CRCs.
// Writes up to max_records (offset, length) pairs into out_off/out_len.
// Returns the number of records found; *consumed is the byte count of
// complete, valid records.  Returns -1 on corruption (crc mismatch),
// with *consumed = offset of the bad record.
int64_t tos_scan_records(const uint8_t* buf, size_t n, int verify,
                         uint64_t* out_off, uint64_t* out_len,
                         int64_t max_records, uint64_t* consumed) {
  init_tables();
  size_t pos = 0;
  int64_t count = 0;
  while (count < max_records) {
    if (n - pos < 12) break;
    uint64_t len;
    std::memcpy(&len, buf + pos, 8);
    uint32_t len_crc = le32(buf + pos + 8);
    if (verify && masked(tos_crc32c(buf + pos, 8, 0)) != len_crc) {
      *consumed = pos;
      return -1;
    }
    // Overflow-safe incomplete-record check: `len + 4` could wrap for a
    // corrupt length field when verify=0, turning an OOB read into a crash.
    const uint64_t avail = n - pos - 12;
    if (len > avail || avail - len < 4) break;  // incomplete record
    const uint8_t* data = buf + pos + 12;
    uint32_t data_crc = le32(data + len);
    if (verify && masked(tos_crc32c(data, len, 0)) != data_crc) {
      *consumed = pos;
      return -1;
    }
    out_off[count] = pos + 12;
    out_len[count] = len;
    ++count;
    pos += 12 + len + 4;
  }
  *consumed = pos;
  return count;
}

// Frame one record into out (which must hold 16 + n bytes).
// Returns the framed size.
uint64_t tos_frame_record(const uint8_t* data, uint64_t n, uint8_t* out) {
  init_tables();
  std::memcpy(out, &n, 8);
  uint32_t lc = masked(tos_crc32c(out, 8, 0));
  std::memcpy(out + 8, &lc, 4);
  std::memcpy(out + 12, data, n);
  uint32_t dc = masked(tos_crc32c(data, n, 0));
  std::memcpy(out + 12 + n, &dc, 4);
  return 16 + n;
}

}  // extern "C"
