"""Native C++ sources (built on demand by native_bindings)."""
