// Native batch parser for tf.train.Example records — the data-loader hot
// path (reference equivalent: record/Example decoding inside the
// tensorflow-hadoop jar / TF runtime, both native; here the per-record
// proto walk happens in C++ and Python sees whole columns).
//
// Wire subset handled (matches example.py, the pure-Python codec):
//   Example    { Features features = 1; }
//   Features   { map<string, Feature> feature = 1; }
//   Feature    { oneof kind { BytesList bytes_list = 1;
//                             FloatList float_list = 2;
//                             Int64List int64_list = 3; } }
//   BytesList  { repeated bytes value = 1; }
//   FloatList  { repeated float value = 1 }   // packed or repeated
//   Int64List  { repeated int64 value = 1 }   // packed or repeated
//
// API shape: two passes per (shard, feature) — tos_count_feature sizes the
// output, tos_fill_feature writes it — so Python allocates exact numpy
// buffers and each pass is ONE ctypes call over the whole shard.
//
// Build: g++ -O3 -shared -fPIC (see native/build.py).

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok;

  Reader(const uint8_t* ptr, size_t n) : p(ptr), end(ptr + n), ok(true) {}

  uint64_t varint() {
    uint64_t result = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      result |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return result;
      shift += 7;
      // reject overlong (>10 byte) varints BEFORE a >=64-bit shift (UB);
      // the canonical 10th byte shifts by 63, which is defined
      if (shift >= 64) break;
    }
    ok = false;
    return 0;
  }

  // Returns subspan for length-delimited fields.
  bool subspan(const uint8_t** sub, size_t* n) {
    uint64_t len = varint();
    // compare against remaining bytes, NOT p + len (which can overflow)
    if (!ok || len > static_cast<uint64_t>(end - p)) { ok = false; return false; }
    *sub = p;
    *n = static_cast<size_t>(len);
    p += len;
    return true;
  }

  bool skip(int wire) {
    switch (wire) {
      case 0: varint(); return ok;
      case 1: if (p + 8 > end) { ok = false; return false; } p += 8; return true;
      case 2: { const uint8_t* s; size_t n; return subspan(&s, &n); }
      case 5: if (p + 4 > end) { ok = false; return false; } p += 4; return true;
      default: ok = false; return false;
    }
  }

  bool done() const { return p >= end; }
};

// Find the named feature's kind payload inside one Example record.
// Returns: 1/2/3 = kind found, 0 = feature absent, -1 = parse error.
// Proto map semantics: when a key appears multiple times on the wire the
// LAST entry wins (matching the pure-Python decode_example fallback), so
// the walk continues to the end of the record instead of early-returning.
int find_feature(const uint8_t* rec, size_t rec_len,
                 const uint8_t* name, size_t name_len,
                 const uint8_t** kind_payload, size_t* kind_len) {
  int result_kind = 0;
  Reader ex(rec, rec_len);
  while (!ex.done()) {
    uint64_t key = ex.varint();
    if (!ex.ok) return -1;
    int field = static_cast<int>(key >> 3), wire = static_cast<int>(key & 7);
    if (field == 1 && wire == 2) {  // Features
      const uint8_t* fs; size_t fs_len;
      if (!ex.subspan(&fs, &fs_len)) return -1;
      Reader feats(fs, fs_len);
      while (!feats.done()) {
        uint64_t fkey = feats.varint();
        if (!feats.ok) return -1;
        int ff = static_cast<int>(fkey >> 3), fw = static_cast<int>(fkey & 7);
        if (ff == 1 && fw == 2) {  // one map entry
          const uint8_t* entry; size_t entry_len;
          if (!feats.subspan(&entry, &entry_len)) return -1;
          Reader e(entry, entry_len);
          const uint8_t* ename = nullptr; size_t ename_len = 0;
          const uint8_t* feat = nullptr; size_t feat_len = 0;
          while (!e.done()) {
            uint64_t ekey = e.varint();
            if (!e.ok) return -1;
            int ef = static_cast<int>(ekey >> 3), ew = static_cast<int>(ekey & 7);
            if (ef == 1 && ew == 2) {
              if (!e.subspan(&ename, &ename_len)) return -1;
            } else if (ef == 2 && ew == 2) {
              if (!e.subspan(&feat, &feat_len)) return -1;
            } else if (!e.skip(ew)) {
              return -1;
            }
          }
          if (ename && ename_len == name_len &&
              memcmp(ename, name, name_len) == 0 && feat) {
            // record this entry's kind; keep walking (last map entry wins)
            Reader f(feat, feat_len);
            bool matched = false;
            while (!f.done()) {
              uint64_t kkey = f.varint();
              if (!f.ok) return -1;
              int kf = static_cast<int>(kkey >> 3), kw = static_cast<int>(kkey & 7);
              if ((kf == 1 || kf == 2 || kf == 3) && kw == 2 && !matched) {
                if (!f.subspan(kind_payload, kind_len)) return -1;
                result_kind = kf;
                matched = true;
              } else if (!f.skip(kw)) {
                return -1;
              }
            }
            // Empty kind payloads (zero values) report as ABSENT: the
            // pure-Python fallback cannot recover the kind of an empty
            // feature either, so this keeps both paths identical (incl.
            // not raising a kind mismatch for a valueless feature).
            if (!matched || *kind_len == 0) result_kind = 0;
          }
        } else if (!feats.skip(fw)) {
          return -1;
        }
      }
    } else {
      if (!ex.skip(wire)) return -1;
    }
  }
  return result_kind;
}

// Walk a kind payload (BytesList/FloatList/Int64List body), invoking the
// sink for every value.  Handles packed and repeated primitive encodings.
template <typename BytesSink, typename FloatSink, typename IntSink>
bool walk_values(int kind, const uint8_t* body, size_t body_len,
                 const uint8_t* base, BytesSink on_bytes, FloatSink on_float,
                 IntSink on_int) {
  Reader r(body, body_len);
  while (!r.done()) {
    uint64_t key = r.varint();
    if (!r.ok) return false;
    int field = static_cast<int>(key >> 3), wire = static_cast<int>(key & 7);
    if (field != 1) { if (!r.skip(wire)) return false; continue; }
    if (kind == 1) {  // bytes values are length-delimited
      const uint8_t* v; size_t n;
      if (wire != 2 || !r.subspan(&v, &n)) return false;
      on_bytes(static_cast<uint64_t>(v - base), static_cast<uint64_t>(n));
    } else if (kind == 2) {  // floats: packed (wire 2) or repeated (wire 5)
      if (wire == 2) {
        const uint8_t* v; size_t n;
        if (!r.subspan(&v, &n) || n % 4) return false;
        for (size_t i = 0; i < n; i += 4) {
          float f;
          memcpy(&f, v + i, 4);
          on_float(f);
        }
      } else if (wire == 5) {
        if (r.p + 4 > r.end) return false;
        float f;
        memcpy(&f, r.p, 4);
        r.p += 4;
        on_float(f);
      } else {
        return false;
      }
    } else {  // int64: packed (wire 2) or repeated varints (wire 0)
      if (wire == 2) {
        const uint8_t* v; size_t n;
        if (!r.subspan(&v, &n)) return false;
        Reader pr(v, n);
        while (!pr.done()) {
          uint64_t raw = pr.varint();
          if (!pr.ok) return false;
          on_int(static_cast<int64_t>(raw));
        }
      } else if (wire == 0) {
        uint64_t raw = r.varint();
        if (!r.ok) return false;
        on_int(static_cast<int64_t>(raw));
      } else {
        return false;
      }
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Pass 1: per-record value counts for one feature across n records.
// counts[i] receives record i's value count (0 if absent).  Returns the
// total value count, or -1 on parse error, or -2 on kind mismatch with
// `expect_kind` (1 bytes / 2 float / 3 int64; 0 = accept any, and then
// *found_kind receives the first kind seen).
int64_t tos_count_feature(const uint8_t* buf, const uint64_t* offs,
                          const uint64_t* lens, int64_t n,
                          const uint8_t* name, uint64_t name_len,
                          int expect_kind, int* found_kind,
                          uint64_t* counts) {
  int64_t total = 0;
  int seen_kind = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* kp; size_t kl;
    int kind = find_feature(buf + offs[i], static_cast<size_t>(lens[i]), name,
                            static_cast<size_t>(name_len), &kp, &kl);
    if (kind < 0) return -1;
    if (kind == 0) { counts[i] = 0; continue; }
    if (expect_kind && kind != expect_kind) return -2;
    if (!seen_kind) seen_kind = kind;
    if (kind != seen_kind) return -2;  // heterogeneous column
    uint64_t c = 0;
    bool ok = walk_values(
        kind, kp, kl, buf,
        [&](uint64_t, uint64_t) { ++c; },
        [&](float) { ++c; },
        [&](int64_t) { ++c; });
    if (!ok) return -1;
    counts[i] = c;
    total += static_cast<int64_t>(c);
  }
  if (found_kind) *found_kind = seen_kind;
  return total;
}

// Pass 2: fill exactly-sized outputs.  For kind 1 (bytes), byte_offs/
// byte_lens receive spans relative to `buf`; for kind 2, f32_out; for
// kind 3, i64_out.  Caller sizes the arrays from pass 1.  Returns the
// number of values written or -1 on parse error.
int64_t tos_fill_feature(const uint8_t* buf, const uint64_t* offs,
                         const uint64_t* lens, int64_t n,
                         const uint8_t* name, uint64_t name_len, int kind,
                         float* f32_out, int64_t* i64_out,
                         uint64_t* byte_offs, uint64_t* byte_lens) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* kp; size_t kl;
    int got = find_feature(buf + offs[i], static_cast<size_t>(lens[i]), name,
                           static_cast<size_t>(name_len), &kp, &kl);
    if (got < 0) return -1;
    if (got == 0) continue;
    if (got != kind) return -1;
    bool ok = walk_values(
        kind, kp, kl, buf,
        [&](uint64_t o, uint64_t l) { byte_offs[w] = o; byte_lens[w] = l; ++w; },
        [&](float f) { f32_out[w++] = f; },
        [&](int64_t v) { i64_out[w++] = v; });
    if (!ok) return -1;
  }
  return w;
}

}  // extern "C"
