"""tensorflowonspark_tpu — a TPU-native distributed training/inference framework.

A brand-new framework with the capabilities of TensorFlowOnSpark
(reference: hopshadoop/TensorFlowOnSpark), redesigned TPU-first:

- Cluster lifecycle API (``TPUCluster.run/train/inference/shutdown``),
  replacing ``tensorflowonspark/TFCluster.py``.
- Per-host node runtime handing out TPU mesh coordinates instead of
  ``CUDA_VISIBLE_DEVICES``, replacing ``tensorflowonspark/TFSparkNode.py``.
- Streaming data plane (``DataFeed``) with end-of-partition semantics,
  replacing ``tensorflowonspark/TFNode.py`` + ``TFManager.py`` queues.
- TCP coordinator/rendezvous with barrier/reduce/heartbeat, replacing
  ``tensorflowonspark/reservation.py``.
- Sync SPMD data parallelism via ``jax.jit`` + shardings over a
  ``jax.sharding.Mesh`` (XLA collectives over ICI), replacing the
  ParameterServer / MultiWorkerMirrored (gRPC+NCCL) path.
- ML pipeline layer (``TPUEstimator``/``TPUModel``), replacing
  ``tensorflowonspark/pipeline.py``.
- TFRecord + tf.train.Example codec without a TensorFlow dependency,
  replacing ``tensorflowonspark/dfutil.py`` + the tensorflow-hadoop jar.
- Cluster-wide metrics + span tracing (``telemetry``): lock-free process
  registries piggybacked on control-plane heartbeats, aggregated into
  ``cluster.metrics()``, TensorBoard scalars, and an end-of-run report —
  replacing the reference's TensorBoard-subprocess-only observability.

See SURVEY.md for the reference layer map this package mirrors.
"""

__version__ = "0.4.0"

from tensorflowonspark_tpu import telemetry  # noqa: F401 - metrics/span API
from tensorflowonspark_tpu import ingest  # noqa: F401 - DIRECT-mode reader pipeline
from tensorflowonspark_tpu.cluster import InputMode, TPUCluster, run  # noqa: F401
from tensorflowonspark_tpu.feeding import DataFeed  # noqa: F401
from tensorflowonspark_tpu.launcher import (  # noqa: F401
    LocalLauncher,
    SubprocessLauncher,
    TPUPodLauncher,
)
from tensorflowonspark_tpu.data import PartitionedDataset  # noqa: F401
from tensorflowonspark_tpu.pipeline import (  # noqa: F401
    Namespace,
    TPUEstimator,
    TPUModel,
    TPUParams,
)

# Drop-in style aliases for users coming from TensorFlowOnSpark.
TFCluster = TPUCluster
TFEstimator = TPUEstimator
TFModel = TPUModel
TFParams = TPUParams
