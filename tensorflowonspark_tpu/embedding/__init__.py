"""Sharded embedding tier (ISSUE 19): model-parallel embedding tables.

BASELINE config 4 (wide-and-deep on Criteo) stresses embedding tables too
large to replicate per host.  The reference era answered with parameter-
server sparse updates (arxiv 1605.08695 §4.4); this tier is the modern
equivalent over the landed cluster machinery: tables range-sharded by row
id across the sync-training world (``sharding.py``), a forward path that
exchanges unique-id lookup requests and gathered rows via the sparse
all-to-all collective, a backward path that exact-sums gradient rows back
to their owning shards via the sparse reduce-scatter (``table.py``), and a
serving path with shards resident on gateway replicas (``serve.py``).
Everything rides the generation-fenced collective wire, so straggler
eviction and elastic rejoin carry over unchanged.
"""

from tensorflowonspark_tpu.embedding.sharding import (
    EmbeddingShard,
    ShardPlan,
    init_rows,
)
from tensorflowonspark_tpu.embedding.table import ShardedTable

__all__ = ["EmbeddingShard", "ShardPlan", "ShardedTable", "init_rows"]
