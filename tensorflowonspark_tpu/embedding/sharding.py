"""Range-shard plans and per-node shard state for model-parallel tables.

A :class:`ShardPlan` splits one logical ``[total_rows, dim]`` table into
``world`` contiguous id ranges — the model-parallel layout of the embedding
tier.  The plan is pure data (world+1 monotone bounds, like the dense
ring's ``_segment_bounds``), travels in the job manifest published by
``cluster.train(mode="sync", embedding=...)``, and is the ONE authority on
row ownership: the forward lookup partitions unique ids by it, the sparse
reduce-scatter scatters gradient rows back by it, and the serving router
fans lookup sub-requests by it.

Row init is deterministic and range-addressable (:func:`init_rows`): rows
are generated in fixed 4096-row blocks, each from its own counter-seeded
RNG, so any ``[lo, hi)`` slice is bit-identical whether materialized as one
table in one process or as shards across a world — the property the
sharded-vs-unsharded bit-for-bit equivalence test pins.

Durability: :class:`EmbeddingShard` saves/restores through the
``checkpoint.py`` shard helpers — per-range npz files committed by atomic
rename, with restore able to REASSEMBLE any requested range from whatever
shard files cover it, so a re-shard after eviction (world W -> W-1, new
bounds) restores each new shard from the old files.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

# Row-init block size: init is generated per 4096-row block from a
# counter-derived seed, so shard init cost is O(range), never O(table).
ROW_INIT_BLOCK = 4096


def even_bounds(total_rows: int, world: int) -> tuple[int, ...]:
    """World+1 monotone bounds splitting ``total_rows`` ids into ``world``
    near-equal contiguous ranges (same convention as the dense ring's
    segment bounds; empty ranges are legal on tiny tables)."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return tuple((total_rows * i) // world for i in range(world + 1))


def init_rows(total_rows: int, dim: int, lo: int, hi: int, *,
              seed: int = 0, scale: float = 0.01) -> np.ndarray:
    """Deterministic rows for the id range ``[lo, hi)`` of a logical
    ``[total_rows, dim]`` table: ``normal(0, scale)`` float32, generated in
    :data:`ROW_INIT_BLOCK`-row blocks each from ``RandomState(seed', block)``
    — any slicing of the table into ranges reproduces the same bytes."""
    if not (0 <= lo <= hi <= total_rows):
        raise ValueError(f"range [{lo}, {hi}) outside table [0, {total_rows})")
    if hi == lo:
        return np.empty((0, dim), np.float32)
    first, last = lo // ROW_INIT_BLOCK, (hi - 1) // ROW_INIT_BLOCK
    pieces = []
    for block in range(first, last + 1):
        b_lo = block * ROW_INIT_BLOCK
        n = min(ROW_INIT_BLOCK, total_rows - b_lo)
        # one independent stream per block: seeds fold the caller's seed so
        # two tables with different seeds never share rows
        rng = np.random.RandomState((seed * 2654435761 + block) % (2**31 - 1))
        rows = (rng.standard_normal((n, dim)) * scale).astype(np.float32)
        pieces.append(rows[max(lo - b_lo, 0):hi - b_lo])
    return pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=0)


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Immutable range-shard layout of one logical embedding table."""

    name: str
    total_rows: int
    dim: int
    bounds: tuple[int, ...]

    def __post_init__(self):
        b = tuple(int(x) for x in self.bounds)
        if len(b) < 2 or b[0] != 0 or b[-1] != self.total_rows:
            raise ValueError(
                f"bounds must run 0..total_rows ({self.total_rows}), got {b}")
        if any(b[i] > b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"bounds must be monotone, got {b}")
        object.__setattr__(self, "bounds", b)

    @classmethod
    def even(cls, name: str, total_rows: int, dim: int,
             world: int) -> "ShardPlan":
        return cls(name, int(total_rows), int(dim),
                   even_bounds(int(total_rows), int(world)))

    @property
    def world(self) -> int:
        return len(self.bounds) - 1

    def range_of(self, rank: int) -> tuple[int, int]:
        return self.bounds[rank], self.bounds[rank + 1]

    def rows_of(self, rank: int) -> int:
        lo, hi = self.range_of(rank)
        return hi - lo

    def owner_of(self, ids: np.ndarray) -> np.ndarray:
        """Owning rank per id (vectorized searchsorted over the interior
        bounds — the same mapping the sparse reduce-scatter applies)."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.total_rows):
            raise ValueError(
                f"ids outside table [0, {self.total_rows}) for plan "
                f"{self.name!r}")
        return np.searchsorted(np.asarray(self.bounds[1:-1], np.int64),
                               ids, side="right")

    def partition(self, ids: np.ndarray) -> list[np.ndarray]:
        """Per-owner index arrays into ``ids`` (rank-indexed list); an owner
        with no ids gets an empty index array — the empty-partition edge the
        sparse collectives ship as zero-row frames."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        owner = self.owner_of(ids)
        return [np.flatnonzero(owner == r) for r in range(self.world)]

    def to_manifest(self) -> dict:
        """JSON-safe manifest block (``cluster.train`` publishes this under
        the sync block; nodes rebuild with :meth:`from_manifest`)."""
        return {"name": self.name, "total_rows": self.total_rows,
                "dim": self.dim, "bounds": list(self.bounds)}

    @classmethod
    def from_manifest(cls, block: dict) -> "ShardPlan":
        return cls(str(block["name"]), int(block["total_rows"]),
                   int(block["dim"]), tuple(block["bounds"]))

    def reshard(self, world: int) -> "ShardPlan":
        """The same logical table laid out over a different world — the
        eviction/serve-time path (train W != serve replica count)."""
        return ShardPlan.even(self.name, self.total_rows, self.dim, world)


class EmbeddingShard:
    """One node's resident rows ``[lo, hi)`` of a sharded table.

    Plain numpy state + plain SGD row updates: adaptive optimizers would
    need sharded slot state per row (out of scope, documented in the README
    section); the dense half of the model keeps its optax optimizer.
    """

    def __init__(self, plan: ShardPlan, rank: int, rows: np.ndarray):
        lo, hi = plan.range_of(rank)
        rows = np.ascontiguousarray(np.asarray(rows, np.float32))
        if rows.shape != (hi - lo, plan.dim):
            raise ValueError(
                f"shard rows shape {rows.shape} != expected "
                f"{(hi - lo, plan.dim)} for rank {rank} of {plan.name!r}")
        self.plan = plan
        self.rank = int(rank)
        self.lo, self.hi = lo, hi
        self.rows = rows

    @classmethod
    def create(cls, plan: ShardPlan, rank: int, *, seed: int = 0,
               scale: float = 0.01,
               zero_cols: Sequence[int] = ()) -> "EmbeddingShard":
        """Deterministically initialize this rank's range (``init_rows``).
        ``zero_cols`` zeroes the named columns after init — the fused
        wide-and-deep table keeps its wide weights (last column) zeros-init
        like the reference's linear model."""
        lo, hi = plan.range_of(rank)
        rows = init_rows(plan.total_rows, plan.dim, lo, hi,
                         seed=seed, scale=scale)
        for c in zero_cols:
            rows[:, c] = 0.0
        return cls(plan, rank, rows)

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        """Gather rows for GLOBAL ids owned by this shard."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        if ids.size and (ids.min() < self.lo or ids.max() >= self.hi):
            raise ValueError(
                f"lookup ids outside shard [{self.lo}, {self.hi})")
        return self.rows[ids - self.lo]

    def apply_grad_rows(self, ids: np.ndarray, grad_rows: np.ndarray,
                        lr: float) -> None:
        """SGD row update for exact-summed UNIQUE ids (the sparse
        reduce-scatter's output): ``rows[id] -= lr * grad``."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        if ids.size == 0:
            return
        if ids.min() < self.lo or ids.max() >= self.hi:
            raise ValueError(
                f"grad ids outside shard [{self.lo}, {self.hi})")
        self.rows[ids - self.lo] -= np.float32(lr) * np.asarray(
            grad_rows, np.float32).reshape(ids.size, -1)

    # -- durability (checkpoint.py shard helpers) -----------------------------

    def save(self, model_dir: str, step: int) -> str:
        from tensorflowonspark_tpu.checkpoint import save_embedding_shard

        return save_embedding_shard(model_dir, self.plan.name, step,
                                    self.lo, self.hi, self.rows)

    def restore(self, model_dir: str, step: int) -> None:
        """Replace this shard's rows with the checkpointed range at
        ``step`` (reassembled across old shard files if the bounds moved)."""
        from tensorflowonspark_tpu.checkpoint import restore_embedding_shard

        self.rows = restore_embedding_shard(model_dir, self.plan.name, step,
                                            self.lo, self.hi, self.plan.dim)

    @classmethod
    def restore_at(cls, plan: ShardPlan, rank: int, model_dir: str,
                   step: int) -> "EmbeddingShard":
        from tensorflowonspark_tpu.checkpoint import restore_embedding_shard

        lo, hi = plan.range_of(rank)
        rows = restore_embedding_shard(model_dir, plan.name, step, lo, hi,
                                       plan.dim)
        return cls(plan, rank, rows)
