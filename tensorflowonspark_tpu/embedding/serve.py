"""Serving-side sharded embeddings: export, replica shards, lookup responder.

A sharded-table training job cannot export one ``params.npz`` — no process
ever holds the whole table.  Instead each training node commits its final
shard range into the export directory via the embedding-shard checkpoint
layout (``embed_<table>/step_<N>/shard_<lo>_<hi>.npz``) and the chief
writes the ordinary dense bundle whose config carries a
``"sharded_embedding"`` block naming the table geometry and final step.

At serve time the shards are RESIDENT on the gateway's replicas, re-sharded
over the serve world (which need not equal the train world — restore
reassembles any range from the committed files):

- each replica loads the dense bundle plus ITS range
  (:func:`load_serving_shard`) and runs a lookup responder thread on the
  dedicated ``embed``/``embed_out`` queue pair
  (:func:`embed_responder_loop`);
- the gateway's router fans per-owner unique-id lookup sub-requests to the
  responders, assembles the gathered rows, and ships the scoring replica
  one ``sharded_batch`` control item = raw rows + gathered fused-table
  rows; the replica applies the DENSE model (:func:`build_sharded_apply`)
  and answers with one result item, preserving the data plane's
  exactly-count invariant.

The serve cluster must be started with the extra queues:
``cluster.run(serving_loop, args, queues=("input", "output", "error",
"embed", "embed_out"))``.
"""

from __future__ import annotations

import logging
from typing import Callable

import numpy as np

from tensorflowonspark_tpu.embedding.sharding import EmbeddingShard, ShardPlan

logger = logging.getLogger(__name__)

# queue pair the lookup responders listen on (distinct from the scoring
# "input"/"output" pair: lookups from the router's fan-out must never
# interleave with batch rounds or the exactly-count collection breaks)
EMBED_QNAME_IN = "embed"
EMBED_QNAME_OUT = "embed_out"


def sharded_config_block(plan: ShardPlan, step: int) -> dict:
    """The ``"sharded_embedding"`` bundle-config block (geometry + the
    final checkpoint step the export committed)."""
    return {"name": plan.name, "total_rows": plan.total_rows,
            "dim": plan.dim, "step": int(step)}


def export_sharded_shard(export_dir: str, plan: ShardPlan, rank: int,
                         rows: np.ndarray, step: int) -> str:
    """One training node's half of a sharded export: commit its resident
    rows into the export dir under the shard-checkpoint layout."""
    from tensorflowonspark_tpu.checkpoint import save_embedding_shard

    lo, hi = plan.range_of(rank)
    return save_embedding_shard(export_dir, plan.name, step, lo, hi, rows)


def load_serving_shard(export_dir: str, block: dict, rank: int,
                       world: int) -> tuple[ShardPlan, EmbeddingShard]:
    """Load one serve replica's resident range: the train-time table
    re-sharded over the SERVE world (range reassembly makes train world !=
    serve world a non-event)."""
    from tensorflowonspark_tpu.checkpoint import restore_embedding_shard

    plan = ShardPlan.even(str(block["name"]), int(block["total_rows"]),
                          int(block["dim"]), int(world))
    lo, hi = plan.range_of(rank)
    rows = restore_embedding_shard(export_dir, plan.name, int(block["step"]),
                                   lo, hi, plan.dim)
    return plan, EmbeddingShard(plan, rank, rows)


def make_id_fn(config: dict) -> Callable:
    """Model-specific ``features -> [B, C] int64 table ids`` extractor for
    the router's fan-out, from the bundle config (the wide-and-deep family
    shares one fused-table id scheme: per-column mod + disjoint offsets)."""
    model = str(config.get("model", ""))
    if model in ("wide_deep", "wide_deep_dense"):
        from tensorflowonspark_tpu.models.wide_deep import (
            flat_categorical_ids,
        )

        vocab = int(config.get("vocab_size", 100_003))
        return lambda feats: flat_categorical_ids(
            np.asarray(feats, np.float32), vocab)
    raise ValueError(
        f"model {model!r} has no sharded-embedding id extractor")


def build_sharded_apply(config: dict) -> Callable:
    """Jitted ``apply(variables, x, rows)`` for the dense half of a sharded
    model (``build_apply``'s single-x contract can't carry the gathered
    rows; the ``sharded_batch`` handler in ``serving_loop`` calls this)."""
    import jax

    from tensorflowonspark_tpu.models.registry import build

    model = build(config)

    def apply_fn(variables, x, rows):
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        return model.apply(variables, x, rows)

    return jax.jit(apply_fn)


def embed_responder_loop(ctx, shard: EmbeddingShard) -> None:
    """Thread body: answer id-lookup sub-requests on the embed queue pair.

    Each router fan-out round is one item ``{"ids": <int64 array>}`` and
    expects exactly one result ``{"ids": ids, "rows": resident rows}``; the
    loop answers item-for-item in order, so coalesced rounds from several
    concurrent fan-outs still collect exactly-count.  EOF on the ``embed``
    queue (node shutdown puts EOF on every input queue) ends the loop.
    """
    feed = ctx.get_data_feed(train_mode=False, qname_in=EMBED_QNAME_IN,
                             qname_out=EMBED_QNAME_OUT)
    lookups = ctx.metrics.counter("serve.embed_lookups")
    rows_out = ctx.metrics.counter("serve.embed_rows")
    while not feed.should_stop():
        items = feed.next_batch(64)
        if not items:
            continue
        results = []
        for item in items:
            ids = np.asarray(item.get("ids"), dtype=np.int64).reshape(-1)
            rows = shard.lookup(ids)
            results.append({"ids": ids, "rows": rows})
            rows_out.inc(int(ids.size))
        lookups.inc(len(results))
        feed.batch_results(results, chunk=True)
