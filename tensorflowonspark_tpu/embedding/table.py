"""ShardedTable: the distributed lookup / sparse-update face of a shard.

One instance per training node wraps that node's :class:`EmbeddingShard`
plus the node's :class:`CollectiveGroup` and exposes exactly two data-path
operations:

``lookup(ids)``
    Forward path.  Dedups the batch's flat ids (``np.unique``, gated by
    ``TOS_EMBED_DEDUP``), partitions the unique ids by the shard plan, and
    runs TWO sparse all-to-alls: an id-request round (ids only, no rows)
    and a row-response round (each peer gathers its resident rows for the
    ids it was asked for and echoes them back).  Rows scatter back into
    unique-id order and expand through the inverse permutation — output is
    ``ids.shape + (dim,)``, exactly what a replicated-table gather would
    produce.

``apply_gradients(ids, grads, lr, scale)``
    Backward path.  Locally combines duplicate-position gradients into CSR
    form (one deterministic exact-sum kernel, ``combine_csr`` — the same
    kernel the reduce-scatter's owner side runs), sparse-reduce-scatters
    the rows to their owning shards, then each owner applies the
    world-scaled SGD row update.  Summation order is pinned (concat in
    rank order + unbuffered ``np.add.at``), which is what makes a sharded
    run bit-identical to a single-process replay of the same per-node
    batches.

With ``group=None`` (world 1) both paths degrade to purely local gathers
and updates over the full table — the reference path the equivalence test
compares against.
"""

from __future__ import annotations

import numpy as np

from tensorflowonspark_tpu.collective import ops as cops
from tensorflowonspark_tpu.embedding.sharding import EmbeddingShard, ShardPlan
from tensorflowonspark_tpu.utils.envtune import env_bool, env_int


class ShardedTable:
    """Distributed embedding table = local shard + sparse collectives."""

    def __init__(self, shard: EmbeddingShard, group=None):
        self.shard = shard
        self.plan: ShardPlan = shard.plan
        self.group = group
        if group is not None and group.world != self.plan.world:
            raise ValueError(
                f"plan world {self.plan.world} != collective world "
                f"{group.world} — build the plan from the formed group")
        # wire accounting for the bench: ids/rows actually exchanged vs the
        # dense alternative (whole-table all-reduce) — the algorithmic
        # headline a one-core box can still demonstrate.
        self.stats = {"lookups": 0, "ids_in": 0, "ids_sent": 0,
                      "rows_fetched": 0, "grad_rows_sent": 0, "updates": 0}

    @property
    def dim(self) -> int:
        return self.plan.dim

    # -- forward ------------------------------------------------------------

    def _dedup(self, flat: np.ndarray):
        if env_bool("TOS_EMBED_DEDUP", True):
            return np.unique(flat, return_inverse=True)
        return flat, np.arange(flat.size, dtype=np.int64)

    def lookup(self, ids) -> np.ndarray:
        """Gather rows for ``ids`` (any shape) -> ``ids.shape + (dim,)``."""
        ids = np.asarray(ids, dtype=np.int64)
        flat = ids.reshape(-1)
        uniq, inv = self._dedup(flat)
        self.stats["lookups"] += 1
        self.stats["ids_in"] += int(flat.size)
        uniq_rows = self._exchange_rows(uniq)
        return uniq_rows[inv].reshape(ids.shape + (self.dim,))

    def _exchange_rows(self, uniq: np.ndarray) -> np.ndarray:
        if self.group is None or self.plan.world == 1:
            self.stats["rows_fetched"] += int(uniq.size)
            return self.shard.lookup(uniq)
        idx = self.plan.partition(uniq)
        parts = [(uniq[idx[r]], None) for r in range(self.plan.world)]
        self.stats["ids_sent"] += int(
            sum(idx[r].size for r in range(self.plan.world)
                if r != self.shard.rank))
        # round 1: who needs what (ids only) — requests[src] is the id set
        # src wants from OUR shard, all inside [lo, hi) by construction
        requests = self.group.sparse_all_to_all(parts)
        resp = [(req_ids, self.shard.lookup(req_ids))
                for req_ids, _ in requests]
        # round 2: echo ids + resident rows; responses[r] comes back in the
        # exact order we asked (peers gather in request order), so rows
        # scatter straight through the partition index arrays
        responses = self.group.sparse_all_to_all(resp)
        out = np.empty((uniq.size, self.dim), np.float32)
        for r, (_, rows) in enumerate(responses):
            out[idx[r]] = rows
        self.stats["rows_fetched"] += int(uniq.size)
        return out

    # -- backward -----------------------------------------------------------

    def apply_gradients(self, ids, grads, *, lr: float,
                        scale: float = 1.0) -> int:
        """Scatter-add gradient rows to their owning shards and apply the
        SGD update there; ``scale`` (typically ``1/world``) multiplies the
        exact cross-node sum before the ``lr`` step.  Returns the number of
        unique rows this shard updated."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        g = np.ascontiguousarray(np.asarray(grads, np.float32)).reshape(
            ids.size, self.dim)
        uniq, acc = cops.combine_csr([ids], [g], self.dim)
        if self.group is None or self.plan.world == 1:
            got_ids, got_rows = uniq, acc
        else:
            self.stats["grad_rows_sent"] += int(
                uniq.size - self.plan.partition(uniq)[self.shard.rank].size)
            got_ids, got_rows = self.group.sparse_reduce_scatter(
                uniq, acc, self.plan.bounds)
        if np.float32(scale) != np.float32(1.0):
            got_rows = got_rows * np.float32(scale)
        self.shard.apply_grad_rows(got_ids, got_rows, lr)
        self.stats["updates"] += 1
        return int(got_ids.size)

    # -- durability ---------------------------------------------------------

    def checkpoint(self, model_dir: str, step: int) -> str:
        return self.shard.save(model_dir, step)

    def maybe_checkpoint(self, model_dir: str, step: int) -> bool:
        """Checkpoint this shard every ``TOS_EMBED_CKPT_EVERY`` steps
        (0 disables — explicit ``checkpoint()`` calls only)."""
        every = env_int("TOS_EMBED_CKPT_EVERY", 0, minimum=0)
        if every <= 0 or step % every != 0:
            return False
        self.shard.save(model_dir, step)
        return True

    def restore(self, model_dir: str, step: int) -> None:
        self.shard.restore(model_dir, step)
