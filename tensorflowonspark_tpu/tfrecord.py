"""TFRecord file codec with crc32c framing — no TensorFlow, no JVM.

Replaces the reference's dependency on the external ``tensorflow-hadoop`` jar
(``org.tensorflow.hadoop.io.TFRecord{File}InputFormat/OutputFormat``) used by
``tensorflowonspark/dfutil.py:~30-90`` for splittable TFRecord I/O, and the
TF runtime's own record reader (SURVEY.md §2.2).  The wire format is the
standard TFRecord framing:

    uint64 length (little-endian)
    uint32 masked_crc32c(length_bytes)
    byte   data[length]
    uint32 masked_crc32c(data)

crc32c is Castagnoli CRC-32 (poly 0x1EDC6F41, reflected 0x82F63B78).  A
table-driven pure-Python implementation is the fallback; the C++ extension in
``native/`` (slice-by-8) is used when built.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_MASK_DELTA = 0xA282EAD8


def _make_table() -> list[int]:
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _make_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# Swapped for the native implementation when available.
crc32c = _crc32c_py
_native = None


def _use_native() -> bool:
    """Try to switch hot paths to the C++ implementation; True on success."""
    global crc32c, _native
    try:
        from tensorflowonspark_tpu import native_bindings
    except Exception:
        return False
    crc32c = native_bindings.crc32c
    _native = native_bindings
    return True


NATIVE = _use_native()


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF)


def frame_record(data: bytes) -> bytes:
    """Encode one record with TFRecord framing."""
    if _native is not None:
        return _native.frame_record(data)
    length = _U64.pack(len(data))
    return length + _U32.pack(masked_crc32c(length)) + data + _U32.pack(masked_crc32c(data))


class RecordError(ValueError):
    pass


def scan_record_spans(buf: bytes, verify: bool = True,
                      name: str = "<buffer>") -> list[tuple[int, int]]:
    """(offset, length) payload spans of an in-memory PLAIN shard buffer
    (native whole-buffer scan when built, Python fallback otherwise).
    ``name`` labels errors.  The buffer-level half of ``read_record_spans``,
    exposed so callers that already hold the bytes (the ingest readers, one
    open per shard) never re-open the file."""
    if _native is not None:
        try:
            spans, consumed = _native.scan_records(buf, verify)
        except ValueError as e:
            raise RecordError(f"{name}: {e}") from None
        if consumed != len(buf):
            raise RecordError(f"{name}: truncated record at offset {consumed}")
        return [(int(o), int(n)) for o, n in spans]
    if not isinstance(buf, (bytes, bytearray)):
        # pure-Python fallback slices header/payload windows for the CRC
        # helper, which wants real bytes; one copy beats a copy per record
        buf = bytes(buf)
    spans = []
    pos = 0
    while pos < len(buf):
        if pos + 12 > len(buf):
            raise RecordError(f"{name}: truncated header at offset {pos}")
        (length,) = _U64.unpack_from(buf, pos)
        if verify and masked_crc32c(buf[pos:pos + 8]) != _U32.unpack_from(buf, pos + 8)[0]:
            raise RecordError(f"{name}: corrupt length crc at offset {pos}")
        start = pos + 12
        if start + length + 4 > len(buf):
            raise RecordError(f"{name}: truncated record at offset {pos}")
        if verify and masked_crc32c(buf[start:start + length]) != \
                _U32.unpack_from(buf, start + length)[0]:
            raise RecordError(f"{name}: corrupt data crc at offset {pos}")
        spans.append((start, length))
        pos = start + length + 4
    return spans


def record_views(buf, spans: list[tuple[int, int]]) -> list[memoryview]:
    """Zero-copy ``memoryview`` slices of ``buf`` over payload ``spans``.

    The view-producing half of the ingest fast path: one root view, one
    slice per record, no payload copies.  LIFETIME CONTRACT — each view
    pins the WHOLE shard buffer; holders must drop (or copy) their views
    when the chunk that delivered them is released, or a few retained
    records keep multi-MB buffers alive.  ``ingest`` enforces this in
    debug mode (``TOS_INGEST_ZEROCOPY=debug``) by releasing delivered
    views, making late access raise ``ValueError``.  Raw buffer slicing
    of shard files is confined here and in ``dfutil`` by the
    ``shard-io-discipline`` checker, so every view producer carries this
    contract.
    """
    root = memoryview(buf)
    return [root[off:off + length] for off, length in spans]


def walk_record_bounds(path: str, span_bytes: int) -> list[tuple[int, int]]:
    """Record-aligned ``(start, end)`` byte ranges of a PLAIN shard, each
    covering ~``span_bytes`` of file (the last may be smaller).

    The driver-side half of sub-shard work items: only record HEADERS are
    read (12 bytes per record, seek past payloads), so splitting a
    multi-GB shard costs header IO, not a full read — and no CRC work;
    verification happens node-side when the range is actually read.
    Raises :class:`RecordError` on a truncated header/record so a corrupt
    shard fails at enumeration, not mid-train.  Must not be called on
    gzip shards (no byte-addressable record boundaries exist there — see
    ``is_gzipped_shard``).
    """
    if span_bytes <= 0:
        raise ValueError(f"span_bytes must be positive, got {span_bytes}")
    size = os.path.getsize(path)
    bounds: list[tuple[int, int]] = []
    start = pos = 0
    with open(path, "rb") as f:
        while pos < size:
            if pos + 12 > size:
                raise RecordError(f"{path}: truncated header at offset {pos}")
            f.seek(pos)
            hdr = f.read(8)
            if len(hdr) < 8:
                raise RecordError(f"{path}: truncated header at offset {pos}")
            (length,) = _U64.unpack(hdr)
            nxt = pos + 12 + length + 4
            if nxt > size:
                raise RecordError(f"{path}: truncated record at offset {pos}")
            pos = nxt
            if pos - start >= span_bytes:
                bounds.append((start, pos))
                start = pos
    if pos > start:
        bounds.append((start, pos))
    return bounds


def map_span_range(path: str, start: int = 0, end: int | None = None,
                   verify: bool = True):
    """mmap-backed ``(buffer, spans)`` for a record-aligned byte range of a
    PLAIN shard (whole shard when ``end`` is None).

    The zero-copy twin of :func:`read_span_range`: the buffer is a
    ``memoryview`` over mapped pages, so the CRC scan and every record
    view read the page cache DIRECTLY — no copy of the range into process
    memory at all (``read()`` pays a full extra DRAM pass, which is what
    caps multi-node ingest of one shard on bandwidth-tight hosts).  The
    mapping lives exactly as long as the buffer/its views (refcounted);
    the ingest lifetime contract (views valid until chunk release) is
    unchanged.  Must not be used on gzip shards (caller probes first).
    """
    import mmap

    size = os.path.getsize(path)
    if end is None:
        end = size
    if not 0 <= start <= end <= size:
        raise ValueError(f"invalid span range [{start}, {end}) for {path} "
                         f"of size {size}")
    if start == end:
        return memoryview(b""), []
    aligned = (start // mmap.ALLOCATIONGRANULARITY) * mmap.ALLOCATIONGRANULARITY
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), end - aligned, prot=mmap.PROT_READ,
                       offset=aligned)
    if hasattr(mm, "madvise"):
        mm.madvise(mmap.MADV_SEQUENTIAL)
    buf = memoryview(mm)[start - aligned:]
    return buf, scan_record_spans(buf, verify,
                                  name=f"{path}[{start}:{end}]")


def map_record_spans(path: str, verify: bool = True):
    """Whole-shard :func:`map_span_range` with the gzip probe folded into
    the SAME open: the magic bytes are read off the mapped head, so the
    default zero-copy read path costs one ``open()`` per shard (on remote
    filesystems every extra open is a metadata round-trip).  Returns
    ``(buf, spans)`` for plain shards, ``(None, None)`` for gzip shards
    (no byte-addressable spans exist — the caller stream-decompresses).
    """
    import mmap

    size = os.path.getsize(path)
    if size == 0:
        return memoryview(b""), []
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    if _is_gzip_shard(mm[:12]):
        mm.close()
        return None, None
    if hasattr(mm, "madvise"):
        mm.madvise(mmap.MADV_SEQUENTIAL)
    buf = memoryview(mm)
    return buf, scan_record_spans(buf, verify, name=path)


def read_span_range(path: str, start: int, end: int, verify: bool = True
                    ) -> tuple[bytes, list[tuple[int, int]]]:
    """Buffer + payload spans for ONE record-aligned byte range of a plain
    shard (a ``walk_record_bounds`` item): seek, one bounded read, one CRC
    scan.  The node-side half of sub-shard work items — N nodes each read
    their own range of the same multi-GB shard.  ``start``/``end`` MUST be
    record boundaries (the scan raises :class:`RecordError` otherwise, so
    a stale/corrupt range fails loudly rather than mis-framing)."""
    if not 0 <= start < end:
        raise ValueError(f"invalid span range [{start}, {end})")
    with open(path, "rb") as f:
        f.seek(start)
        buf = f.read(end - start)
    if len(buf) < end - start:
        raise RecordError(f"{path}: span range [{start}, {end}) past EOF")
    return buf, scan_record_spans(buf, verify,
                                  name=f"{path}[{start}:{end}]")


def read_record_spans(path: str, verify: bool = True) -> tuple[bytes, list[tuple[int, int]]]:
    """Whole-shard buffer + (offset, length) payload spans.

    The zero-copy companion of ``read_records`` for columnar consumers
    (``dfutil.read_shard_columns`` / the native Example parser): one buffer,
    one scan, no per-record slicing.  Handles gzip, but INFLATES the whole
    shard into memory to do it (the one-buffer contract requires it) — for
    gzip shards of unbounded size prefer ``read_records``, which streams.
    """
    import gzip

    with open(path, "rb") as f:
        buf = f.read()
    if _is_gzip_shard(buf[:12]):
        buf = gzip.decompress(buf)
    return buf, scan_record_spans(buf, verify, name=path)


def _is_gzip_shard(head: bytes) -> bool:
    """GZIP-vs-plain detection on a 12-byte header prefix.

    Must not misread a PLAIN shard whose first record length happens to
    collide with the gzip magic (the header starts with a little-endian
    uint64 length, so 0x1f 0x8b is reachable): beyond the 3-byte gzip
    signature, prefer the plain interpretation whenever the header's own
    masked length-CRC validates — a ~2^-32 discriminator.
    """
    if len(head) < 3 or head[:3] != b"\x1f\x8b\x08":
        return False
    return not (len(head) >= 12
                and masked_crc32c(head[:8]) == _U32.unpack_from(head, 8)[0])


def is_gzipped_shard(path: str) -> bool:
    """Whether the shard file is whole-stream gzipped (by header probe).

    The ingest reader pipeline keys its read strategy on this: plain shards
    go through ``read_record_spans`` (one IO read, one native CRC scan, span
    slices); gzip shards stream-decompress so a multi-GB shard never
    inflates into one buffer inside a reader thread.
    """
    with open(path, "rb") as probe:
        return _is_gzip_shard(probe.read(12))


def _stream_records(f, path: str, verify: bool) -> Iterator[bytes]:
    """Streaming framing parser over an open (possibly gzip) file object:
    constant memory regardless of shard size.  crc32c is the native slice-
    by-8 implementation when built (module-level swap), so streaming does
    not give up the fast checksum — only the whole-buffer C++ scan."""
    offset = 0
    while True:
        hdr = f.read(12)
        if not hdr:
            return
        if len(hdr) < 12:
            raise RecordError(f"{path}: truncated header at offset {offset}")
        (length,) = _U64.unpack_from(hdr, 0)
        (length_crc,) = _U32.unpack_from(hdr, 8)
        if verify and masked_crc32c(hdr[:8]) != length_crc:
            raise RecordError(f"{path}: corrupt length crc at offset {offset}")
        data = f.read(length)
        footer = f.read(4)
        if len(data) < length or len(footer) < 4:
            raise RecordError(f"{path}: truncated record at offset {offset}")
        if verify and masked_crc32c(data) != _U32.unpack(footer)[0]:
            raise RecordError(f"{path}: corrupt data crc at offset {offset}")
        yield data
        offset += 12 + length + 4


def read_records(path: str, verify: bool = True,
                 gzipped: bool | None = None) -> Iterator[bytes]:
    """Yield raw record payloads from a TFRecord file.

    Plain shards with the native codec are scanned whole in C++ (one CRC
    pass, no per-record Python framing work); otherwise a streaming Python
    parser.

    GZIP-compressed shards (TF's ``TFRecordOptions('GZIP')`` format — the
    whole stream gzipped; the reference's Hadoop TFRecord input supported
    the same) are detected by magic bytes and decompressed transparently
    (see ``_is_gzip_shard``) — and ALWAYS via streaming decompression
    (``gzip.open``), never a whole-file ``gzip.decompress``: a multi-GB
    gzip shard must not inflate into one buffer before the first record
    can be yielded (it would OOM an ingest reader thread).

    ``gzipped`` skips the header probe when the caller already knows (the
    ingest readers probe once per shard — on remote filesystems every
    extra open is a metadata round-trip).
    """
    import gzip

    if gzipped if gzipped is not None else is_gzipped_shard(path):
        with gzip.open(path, "rb") as f:
            yield from _stream_records(f, path, verify)
        return
    if _native is not None:
        buf, spans = read_record_spans(path, verify)
        for off, length in spans:
            yield buf[off : off + length]
        return
    with open(path, "rb") as f:
        yield from _stream_records(f, path, verify)


class RecordWriter:
    """Streaming TFRecord writer.

    ``compression='gzip'`` (or a ``.gz`` path suffix) writes the
    TF-compatible whole-stream-gzipped form; ``read_records`` auto-detects
    it on the way back.
    """

    def __init__(self, path: str, compression: str | None = None):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if compression is None and path.endswith(".gz"):
            compression = "gzip"
        normalized = (compression or "none").lower()
        if normalized in ("", "none"):
            self._f = open(path, "wb")
        elif normalized == "gzip":
            import gzip

            self._f = gzip.open(path, "wb")
        else:
            raise ValueError(f"unsupported compression {compression!r}; "
                             "use None or 'gzip'")

    def write(self, data: bytes) -> None:
        self._f.write(frame_record(data))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "RecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_records(path: str, records: Iterable[bytes],
                  compression: str | None = None) -> int:
    """Write all records to one file; returns the record count."""
    n = 0
    with RecordWriter(path, compression=compression) as w:
        for rec in records:
            w.write(rec)
            n += 1
    return n
