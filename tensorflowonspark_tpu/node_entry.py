"""Child-process entry: ``python -m tensorflowonspark_tpu.node_entry``.

Runs one node whose cloudpickled ``NodeConfig`` arrives on stdin (the
SubprocessLauncher / TPUPodLauncher spawn contract — the analogue of the
reference's Spark-executor task entry, ``TFSparkNode.py:~200-260``).

Deliberately a leaf module that the package ``__init__`` does NOT import:
``-m`` on a module already imported as a package attribute executes its body
twice as two distinct module objects (runpy's ``found in sys.modules``
warning), which breaks class-identity checks in the child.
"""

from __future__ import annotations

import sys


def main() -> int:
    payload = sys.stdin.buffer.read()
    if not payload:
        print("tensorflowonspark_tpu.node_entry: no NodeConfig on stdin",
              file=sys.stderr)
        return 2
    import cloudpickle

    config = cloudpickle.loads(payload)
    from tensorflowonspark_tpu.node import node_main

    return node_main(config)


if __name__ == "__main__":
    sys.exit(main())
