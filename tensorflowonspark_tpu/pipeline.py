"""ML pipeline layer — the ``TFEstimator``/``TFModel`` replacement.

Reference (``tensorflowonspark/pipeline.py``, ~780 LoC): pyspark.ml
``Estimator``/``Model`` subclasses with ~20 ``Has*`` Param mixins
(``:~40-300``), ``Namespace``/``TFParams`` argv merging (``:~300-380``),
``TFEstimator._fit`` = write TFRecords → ``TFCluster.run`` → ``train`` →
``shutdown`` → ``TFModel`` (``:~400-500``), and ``TFModel._transform`` =
per-executor cached SavedModel scoring with input/output column mappings
(``:~500-700``).

TPU-native redesign: no pyspark dependency — a small chainable Params system
with the same ``setX``/``getX`` surface; datasets are ``PartitionedDataset``s
of row-dicts; the model artifact is a bundle (params pytree + model-registry
config, ``checkpoint.export_bundle``) instead of a SavedModel; transform
batches rows through one jitted apply per process with the same cached-load
behaviour the reference used for its per-executor SavedModel singleton.
"""

from __future__ import annotations

import copy
import logging
from typing import Any, Callable, Iterable

import numpy as np

from tensorflowonspark_tpu import cluster as _cluster
from tensorflowonspark_tpu import dfutil
from tensorflowonspark_tpu.cluster import InputMode
from tensorflowonspark_tpu.data import PartitionedDataset, as_partitioned

logger = logging.getLogger(__name__)


# -- params system (reference Has* mixins, pipeline.py:~40-300) ---------------

class Param:
    """One declared parameter (name, default, doc)."""

    def __init__(self, name: str, default: Any = None, doc: str = ""):
        self.name = name
        self.default = default
        self.doc = doc

    def __repr__(self) -> str:
        return f"Param({self.name!r}, default={self.default!r})"


class Params:
    """Declared-parameter container with chainable setters.

    Mirrors the pyspark.ml Params surface the reference exposed
    (``getBatchSize``/``setBatchSize`` …) without the pyspark dependency.
    """

    def __init__(self, **kwargs: Any):
        self._values: dict[str, Any] = {}
        for k, v in kwargs.items():
            self.set(k, v)

    @classmethod
    def params(cls) -> dict[str, Param]:
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for v in vars(klass).values():
                if isinstance(v, Param):
                    out[v.name] = v
        return out

    def set(self, name: str, value: Any) -> "Params":
        if name not in self.params():
            raise KeyError(f"unknown param {name!r}; declared: {sorted(self.params())}")
        self._values[name] = value
        return self

    def get(self, name: str) -> Any:
        if name in self._values:
            return self._values[name]
        return self.params()[name].default

    def is_set(self, name: str) -> bool:
        return name in self._values

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            mark = "(set)" if self.is_set(name) else "(default)"
            lines.append(f"{name}: {p.doc} {mark} = {self.get(name)!r}")
        return "\n".join(lines)

    def copy(self) -> "Params":
        c = copy.copy(self)
        c._values = dict(self._values)
        return c

    def __getattr__(self, attr: str) -> Any:
        # setBatchSize(v) / getBatchSize() accessor synthesis (camelCase →
        # the snake_case param names used internally)
        if attr.startswith(("set", "get")) and len(attr) > 3:
            import re

            params = self.params()
            name = re.sub(r"(?<!^)(?=[A-Z])", "_", attr[3:]).lower()
            if name not in params:
                # Acronym accessors: naive camelCase splitting turns
                # setTFRecordDir into "t_f_record_dir" and the accessor the
                # reference API promises raises AttributeError.  Match by
                # underscore-insensitive normalization instead, so ANY
                # camelization of a declared param resolves (TFRecordDir ->
                # "tfrecorddir" == "tfrecord_dir" normalized).
                norm = attr[3:].lower()
                name = next((p for p in params if p.replace("_", "") == norm),
                            name)
            if name in params:
                if attr.startswith("set"):
                    return lambda value: self.set(name, value)
                return lambda: self.get(name)
        raise AttributeError(attr)

    def to_namespace(self) -> "Namespace":
        ns = {name: self.get(name) for name in self.params()}
        return Namespace(ns)


class HasBatchSize(Params):
    batch_size = Param("batch_size", 64, "per-step global batch size")


class HasEpochs(Params):
    epochs = Param("epochs", 1, "number of passes over the training data")


class HasSteps(Params):
    steps = Param("steps", -1, "max training steps (-1 = until data exhausted)")


class HasInputMapping(Params):
    input_mapping = Param("input_mapping", None, "dict: row column -> model input")


class HasOutputMapping(Params):
    output_mapping = Param("output_mapping", None, "dict: model output -> result column")


class HasInputMode(Params):
    input_mode = Param("input_mode", InputMode.STREAMING, "DIRECT (files) or STREAMING (feed)")


class HasMasterNode(Params):
    master_node = Param("master_node", None, "name of the chief role")


class HasNumExecutors(Params):
    num_executors = Param("num_executors", 1, "number of node processes/hosts")


class HasModelDir(Params):
    model_dir = Param("model_dir", None, "checkpoint directory (hdfs:// ok)")


class HasExportDir(Params):
    export_dir = Param("export_dir", None, "bundle export directory (hdfs:// ok)")


class HasTFRecordDir(Params):
    tfrecord_dir = Param("tfrecord_dir", None,
                         "stage the train dataset as TFRecords here before training")


class HasTensorboard(Params):
    tensorboard = Param("tensorboard", False, "spawn TensorBoard on the chief")


class HasLogDir(Params):
    log_dir = Param("log_dir", "", "node log/summary directory")


class HasReaders(Params):
    readers = Param("readers", 1, "per-node reader threads (DIRECT mode)")


class HasFeedTimeout(Params):
    feed_timeout = Param("feed_timeout", None,
                         "seconds before a stalled feed errors "
                         "(default: TOS_FEED_TIMEOUT env or 600)")


class HasShuffleSeed(Params):
    shuffle_seed = Param("shuffle_seed", None,
                         "per-epoch partition shuffle seed (STREAMING mode)")


class HasReservationTimeout(Params):
    reservation_timeout = Param("reservation_timeout", None,
                                "seconds to wait for all nodes to register "
                                "(default: TOS_RESERVATION_TIMEOUT env or 120)")


class HasJaxDistributed(Params):
    jax_distributed = Param("jax_distributed", False,
                            "bootstrap one multi-host jax.distributed job "
                            "over the cluster (global mesh spanning nodes)")


class HasModelConfig(Params):
    model_config = Param("model_config", None,
                         "model registry config dict passed through to the "
                         "train_fn as args.model_config (e.g. {'model': "
                         "'wide_deep', 'vocab_size': 1009}) — the plumbing "
                         "that keeps test/serve table sizes off the "
                         "~530 MB wide_deep defaults")


class HasTrainMode(Params):
    train_mode = Param("train_mode", "async",
                       "cluster.train feeding mode: 'async' (independent "
                       "drains) or 'sync' (lockstep epochs + sync manifest "
                       "block for collective train_fns)")
    embedding_plan = Param("embedding_plan", None,
                           "sharded-embedding plan manifest (ShardPlan or "
                           "its to_manifest() dict) published to the nodes "
                           "via the sync manifest block; requires "
                           "train_mode='sync'")


class HasScoring(Params):
    scoring = Param("scoring", "task",
                    "transform execution mode: 'task' (every node holds the "
                    "whole model, scores its own partitions) or 'sharded' "
                    "(model sharded over one global mesh, SPMD scoring)")
    mesh_axes = Param("mesh_axes", None,
                      "mesh layout for scoring='sharded' "
                      "(default {'fsdp': -1})")


class Namespace:
    """Attribute-style argv bag (reference ``Namespace``, pipeline.py:~300-380).

    Merges dicts / argparse namespaces / other Namespaces; later sources win.
    """

    def __init__(self, *sources: Any):
        self.__dict__["_d"] = {}
        for s in sources:
            self.merge(s)

    def merge(self, source: Any) -> "Namespace":
        if source is None:
            return self
        if isinstance(source, Namespace):
            self._d.update(source._d)
        elif isinstance(source, dict):
            self._d.update(source)
        else:  # argparse.Namespace or any attr bag
            self._d.update(vars(source))
        return self

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["_d"][name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        self._d[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self._d

    def get(self, name: str, default: Any = None) -> Any:
        return self._d.get(name, default)

    def asdict(self) -> dict:
        return dict(self._d)

    def __repr__(self) -> str:
        return f"Namespace({self._d!r})"


class TPUParams(HasBatchSize, HasEpochs, HasSteps, HasInputMapping,
                HasOutputMapping, HasInputMode, HasMasterNode, HasNumExecutors,
                HasModelDir, HasExportDir, HasTFRecordDir, HasTensorboard,
                HasLogDir, HasReaders, HasFeedTimeout, HasReservationTimeout,
                HasShuffleSeed, HasJaxDistributed, HasScoring,
                HasModelConfig, HasTrainMode):
    """All framework params in one mixin stack (reference ``TFParams``)."""

    def merge_args_params(self, tf_args: Any = None) -> Namespace:
        """Params-over-args merge the reference did before ``TFCluster.run``."""
        ns = Namespace(tf_args)
        for name in self.params():
            if self.is_set(name) or name not in ns:
                ns.merge({name: self.get(name)})
        return ns


# -- estimator (reference TFEstimator, pipeline.py:~400-500) ------------------

class TPUEstimator(TPUParams):
    """Train via a user ``train_fn(args, ctx)`` on a cluster; yields TPUModel.

    ``train_fn`` must export a bundle to ``args.export_dir`` (the reference's
    map_fun exported a SavedModel the same way).

    ``epochs`` semantics by input mode (same split as the reference): in
    STREAMING mode the *driver* replays the dataset ``epochs`` times
    through the feed.  In DIRECT mode ``fit`` now ALSO drives the
    ledger-backed ingest feed whenever it has a shard spec — a path /
    glob / list-of-paths dataset, or rows staged via ``tfrecord_dir`` —
    so ``cluster.train(spec, num_epochs=epochs)`` replays the shard set
    through the partition ledger and the train_fn inherits at-least-once
    re-feed, sub-shard parallelism, and elastic recovery by consuming
    ``ctx.get_data_feed()`` (the reference's estimator stayed
    self-service here).  Self-service train_fns that read files
    themselves instead of consuming the feed keep working: the path feed
    is tiny and is drained at shutdown.
    """

    def __init__(self, train_fn: Callable, tf_args: Any = None,
                 launcher: Any = None, env: dict | None = None,
                 per_node_env: list | None = None, **params: Any):
        super().__init__(**params)
        self.train_fn = train_fn
        self.tf_args = tf_args
        # Live placement objects ride on the estimator, not the Params bag
        # (a launcher is not a serializable config value): ``launcher`` e.g.
        # a TPUPodLauncher for multi-host pods, ``env``/``per_node_env`` the
        # same env layering cluster.run takes.  Together with the
        # ``jax_distributed`` Param this opens the full multi-host path to
        # the pipeline surface (reference: Spark placed executors for
        # ``pipeline.py:~400-500``; here placement is explicit).
        self.launcher = launcher
        self.env = env
        self.per_node_env = per_node_env
        # post-run node metadata view (filled by fit, success OR failure)
        self.last_cluster_info: list | None = None

    def fit(self, dataset: Any) -> "TPUModel":
        args = self.merge_args_params(self.tf_args)
        if args.get("export_dir") is None:
            raise ValueError("TPUEstimator requires export_dir (the model artifact path)")
        input_mode = args.input_mode
        # DIRECT + a shard spec (path/glob/dir or list of paths): nothing
        # to partition driver-side — the spec goes straight to the
        # ledger-driven ingest feed below.  A path that does NOT resolve
        # to TFRecord shards (e.g. a raw-image directory a self-service
        # train_fn reads its own way) is left alone: the previous
        # releases' self-service contract must keep working.
        shard_spec = _as_shard_spec(dataset) if input_mode == InputMode.DIRECT \
            else None
        if shard_spec is not None:
            from tensorflowonspark_tpu.ingest import enumerate_shards

            try:
                enumerate_shards(shard_spec)
            except FileNotFoundError as e:
                logger.warning(
                    "DIRECT fit: %s — leaving the train_fn self-service "
                    "(no ledger-driven ingest feed for this dataset)", e)
                shard_spec = None
        data = None if shard_spec is not None else as_partitioned(
            dataset, default_partitions=max(1, args.num_executors))
        if args.get("tfrecord_dir"):
            # Stage to TFRecords so DIRECT-mode train_fns can read files
            # (reference: dfutil.saveAsTFRecords before TFCluster.run).
            rows = data if data is not None and _is_row_data(data) else None
            if rows is None:
                raise ValueError("tfrecord_dir staging requires row-dict datasets")
            dfutil.save_as_tfrecords(rows, args.tfrecord_dir)
            args.merge({"data_dir": args.tfrecord_dir})
            if input_mode == InputMode.DIRECT:
                shard_spec = args.tfrecord_dir  # feed the staged shards
        cluster = _cluster.run(
            self.train_fn,
            args,
            num_executors=args.num_executors,
            input_mode=input_mode,
            master_node=args.master_node,
            tensorboard=args.tensorboard,
            log_dir=args.log_dir,
            feed_timeout=args.feed_timeout,
            reservation_timeout=args.reservation_timeout,
            launcher=self.launcher,
            env=self.env,
            per_node_env=self.per_node_env,
            jax_distributed=bool(args.get("jax_distributed")),
        )
        try:
            if input_mode == InputMode.STREAMING:
                cluster.train(data, num_epochs=args.epochs,
                              shuffle_seed=args.shuffle_seed,
                              mode=args.get("train_mode", "async"),
                              embedding=args.get("embedding_plan"))
            elif shard_spec is not None:
                # DIRECT onto the ledger-driven ingest feed: shard (and
                # sub-shard span) work items flow through the partition
                # ledger, so the pipeline layer inherits at-least-once
                # re-feed and elastic recovery instead of staying
                # self-service
                cluster.train(shard_spec, num_epochs=args.epochs,
                              shuffle_seed=args.shuffle_seed,
                              mode=args.get("train_mode", "async"),
                              embedding=args.get("embedding_plan"))
        finally:
            try:
                cluster.shutdown()
            finally:
                # post-run node metadata (update_meta patches: device facts,
                # step counts, TB url) — the observability view the
                # reference exposed through TFCluster; captured even when
                # shutdown re-raises a node error, so failed runs can be
                # diagnosed from it
                self.last_cluster_info = cluster.coordinator.cluster_info()
        # the fitted model inherits the placement surface: transform() on a
        # pod-trained model must score on the same hosts, not default-local
        model = TPUModel(tf_args=args, launcher=self.launcher, env=self.env,
                         per_node_env=self.per_node_env)
        model.set("export_dir", args.export_dir)
        for name in ("batch_size", "input_mapping", "output_mapping"):
            if self.is_set(name):
                model.set(name, self.get(name))
        return model


# -- model (reference TFModel, pipeline.py:~500-700) --------------------------

class TPUModel(TPUParams):
    """Batch inference over a partitioned dataset from an exported bundle."""

    def __init__(self, tf_args: Any = None, launcher: Any = None,
                 env: dict | None = None, per_node_env: list | None = None,
                 **params: Any):
        super().__init__(**params)
        self.tf_args = tf_args
        # Same placement surface as TPUEstimator (each call to transform
        # launches a fresh scoring cluster through these).
        self.launcher = launcher
        self.env = env
        self.per_node_env = per_node_env

    def transform(self, dataset: Any) -> PartitionedDataset:
        """Score rows on a cluster of executors; preserves partition order/count.

        Reference parity (``pipeline.py:~500-700``): ``TFModel._transform``
        scored partitions on *executors* with a per-executor cached
        SavedModel.  Here each of ``num_executors`` node processes runs
        ``inference.bundle_inference_loop`` over its share of partitions with
        a per-process cached bundle; the driver merges predictions back into
        the rows.  Rows are dicts; ``input_mapping`` {column → model input}
        selects feature columns (multi-column mappings are concatenated on
        the feature axis, see ``inference.rows_to_features``);
        ``output_mapping`` {model output → column} names prediction columns
        (default: {"prediction": "prediction"}).
        """
        from tensorflowonspark_tpu.inference import (
            bundle_inference_loop,
            sharded_bundle_inference_loop,
        )

        args = self.merge_args_params(self.tf_args)
        export_dir = args.get("export_dir")
        if not export_dir:
            raise ValueError("TPUModel requires export_dir")
        num_executors = max(1, int(args.get("num_executors") or 1))
        data = as_partitioned(dataset, default_partitions=num_executors)
        output_mapping = args.get("output_mapping") or {"prediction": "prediction"}
        scoring = args.get("scoring") or "task"
        if scoring not in ("task", "sharded"):
            raise ValueError(f"unknown scoring mode {scoring!r}; "
                             "use 'task' or 'sharded'")
        sharded = scoring == "sharded"
        if sharded and data.num_partitions < num_executors:
            raise ValueError(
                f"scoring='sharded' needs at least one partition per node "
                f"({data.num_partitions} partitions < {num_executors} nodes)")
        # One-pass input read: capture rows WHILE they stream to the scoring
        # nodes, so partitions are consumed exactly once (no double IO on
        # file-backed datasets; consume-once generator partitions work).
        captured: dict[int, list] = {}

        def _tee(p: int):
            def gen():
                rows = captured[p] = []
                for row in data.iter_partition(p):
                    rows.append(row)
                    yield row

            return gen

        tee_data = PartitionedDataset([_tee(p) for p in range(data.num_partitions)])
        cluster = _cluster.run(
            sharded_bundle_inference_loop if sharded else bundle_inference_loop,
            args,
            num_executors=num_executors,
            input_mode=InputMode.STREAMING,
            feed_timeout=args.feed_timeout,
            reservation_timeout=args.reservation_timeout,
            launcher=self.launcher,
            env=self.env,
            per_node_env=self.per_node_env,
            jax_distributed=bool(args.get("jax_distributed")),
        )
        try:
            # sharded scoring REQUIRES eager EOF: a node whose share ran out
            # keeps joining the global SPMD rounds until its peers finish
            pred_parts = cluster.inference(tee_data, flat=False,
                                           eof_when_done=sharded)
        finally:
            cluster.shutdown()
        parts = []
        for p, preds in enumerate(pred_parts):
            rows = captured.get(p)
            if rows is None:
                raise RuntimeError(f"partition {p} produced predictions but was "
                                   "never streamed (tee invariant violated)")
            if len(preds) != len(rows):
                raise RuntimeError(
                    f"partition {p}: {len(preds)} predictions for {len(rows)} rows "
                    "(exactly-count invariant violated)")
            parts.append(merge_prediction_rows(rows, preds, output_mapping))
        return PartitionedDataset.from_partitions(parts)


def merge_prediction_rows(rows: list, preds: list, output_mapping: dict) -> list:
    """Merge per-row predictions into result rows under ``output_mapping``
    ({model output → result column}).

    Single-output models emit one array per row and the mapping's single
    column receives it.  Multi-output models emit a dict per row
    (``bundle_inference_loop`` slices dict apply outputs row-wise); each
    mapped output lands in its column, and BOTH mismatch directions error
    loudly — an output the mapping does not name would otherwise be dropped
    silently, and a mapped name the model never produced used to get the
    whole prediction blob copied under every column (multi-output mappings
    silently mapped wrong before this check existed).
    """
    out = []
    expected = set(output_mapping)
    for row, pred in zip(rows, preds):
        row_out = dict(row) if isinstance(row, dict) else {}
        if isinstance(pred, dict):
            if set(pred) != expected:
                # per ROW, not once: a conditional head that drops an output
                # for some rows must fail with the mapping named, never a
                # bare KeyError (or a silently ignored extra output)
                unmapped = sorted(set(pred) - expected)
                if unmapped:
                    raise ValueError(
                        f"model outputs {unmapped} are not in output_mapping "
                        f"{sorted(output_mapping)}; map every output (or drop "
                        "it explicitly model-side)")
                raise ValueError(
                    f"output_mapping names {sorted(expected - set(pred))} but "
                    f"this row's prediction only has {sorted(pred)}")
            for name, col in output_mapping.items():
                row_out[col] = np.asarray(pred[name])
        else:
            if len(output_mapping) > 1:
                raise ValueError(
                    f"output_mapping has {len(output_mapping)} entries "
                    f"({sorted(output_mapping)}) but the model emits a single "
                    "unnamed output; multi-output mapping needs dict "
                    "predictions (a dict-returning apply fn)")
            for _, col in output_mapping.items():
                row_out[col] = np.asarray(pred)
        out.append(row_out)
    return out


def _as_shard_spec(dataset: Any):
    """A DIRECT-mode fit dataset that is already a shard spec (path, glob,
    directory, or list of paths) — returned as-is for the ledger feed;
    None means row data (needs ``tfrecord_dir`` staging)."""
    import os

    if isinstance(dataset, (str, os.PathLike)):
        return dataset
    if isinstance(dataset, (list, tuple)) and dataset and all(
            isinstance(p, (str, os.PathLike)) for p in dataset):
        return list(dataset)
    return None


def _is_row_data(data: PartitionedDataset) -> bool:
    for p in range(data.num_partitions):
        for row in data.iter_partition(p):
            return isinstance(row, dict)
    return False


