"""Rollout governor: watch a canary cohort, promote or auto-roll-back.

The decision half of staged rollouts (``gateway.rollout``).  The router
feeds every batch outcome — cohort, replica, latency, results, transport
error, and for shadow mirrors the primary's results — into
:meth:`RolloutGovernor.observe`; the governor keeps sliding windows
(``TOS_SERVE_ROLLOUT_WINDOW_SECS``) per cohort and resolves the rollout
one of three ways:

- **promote**: a full window elapsed with enough canary samples and no
  regression verdict — the gateway swaps the whole fleet onto the
  candidate (the existing drained reload path, now signature-verified);
- **roll back**: the canary regressed vs the primary baseline — NaN
  outputs, shadow-mirror divergence past threshold, model-attributable
  errors the primary does not show, or canary p99 inflated well past the
  primary's — so the canaries reload the prior export and the candidate
  is journaled as rolled back;
- **abort**: the gateway closed (or the resolution action itself failed)
  mid-rollout.

Error classification is the load-bearing subtlety: the router's observer
reports *transport* failures (dead replica, severed socket, timed-out
round — ``ConnectionError``/``OSError``/``TimeoutError``/``EOFError`` and
chaos ``FaultInjected``).  Those are INFRA errors: they already have an
owner (retry-once + recovery re-admission) and never count toward the
regression verdict — a SIGKILLed canary replica must trigger recovery and
cohort re-convergence, not a spurious rollback of a healthy model.  Only
errors that cannot be transport (and the model-output signals: NaN rate,
divergence, latency inflation) indict the candidate itself.

Everything here is driver-side bookkeeping; the fleet actions (promote /
rollback control rounds) stay in the gateway, under its reload lock, and
the resulting state transitions are journaled through the coordinator's
rollout registry so a control-plane failover restores what was in flight.
"""

from __future__ import annotations

import collections
import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time
from time import monotonic as _monotonic

import numpy as np

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.faultinject import FaultInjected
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.utils.envtune import env_float

logger = logging.getLogger(__name__)

#: Transport/infra failures (the router's retry + recovery machinery owns
#: these); never evidence against the candidate model.
_INFRA_ERRORS = (ConnectionError, OSError, TimeoutError, EOFError,
                 FaultInjected)


def _is_infra_error(error: BaseException | None) -> bool:
    return isinstance(error, _INFRA_ERRORS)


def _iter_values(row):
    """The numeric leaves of one result row (dict rows yield per output)."""
    if isinstance(row, dict):
        yield from row.values()
    else:
        yield row


def nan_fraction(results, sample: int = 8) -> float:
    """Fraction of NaN elements across (up to ``sample``) result rows —
    the cheapest possible "is the candidate emitting garbage" probe."""
    total = bad = 0
    for row in (results or [])[:sample]:
        for v in _iter_values(row):
            try:
                a = np.asarray(v)
            except Exception:  # noqa: BLE001 - non-numeric output kind
                continue
            if a.dtype.kind != "f":
                continue
            total += a.size
            bad += int(np.isnan(a).sum())
    return bad / total if total else 0.0


def divergence(canary_rows, primary_rows, sample: int = 8) -> float:
    """Worst relative element divergence between a mirror's canary outputs
    and the primary results it shadows.  Shape mismatch, output-key
    mismatch, or NaN on exactly one side is maximal divergence (1.0) —
    those are the regressions shadow testing exists to catch."""
    worst = 0.0
    pairs = list(zip(canary_rows or [], primary_rows or []))[:sample]
    for c_row, p_row in pairs:
        if isinstance(c_row, dict) != isinstance(p_row, dict):
            return 1.0
        if isinstance(c_row, dict):
            if set(c_row) != set(p_row):
                return 1.0
            values = [(c_row[k], p_row[k]) for k in c_row]
        else:
            values = [(c_row, p_row)]
        for cv, pv in values:
            try:
                a = np.asarray(cv, dtype=float)
                b = np.asarray(pv, dtype=float)
            except (TypeError, ValueError):
                # non-numeric outputs (e.g. argmax'd class ids arrive as
                # ints — asarray handles those; strings land here): diverged
                # means not equal
                if cv != pv:
                    return 1.0
                continue
            if a.shape != b.shape:
                return 1.0
            a_nan, b_nan = bool(np.isnan(a).any()), bool(np.isnan(b).any())
            if a_nan or b_nan:
                if a_nan != b_nan:
                    return 1.0
                continue  # both NaN in the same batch: no verdict either way
            if a.size == 0:
                continue
            denom = max(float(np.abs(b).max()), 1.0)
            worst = max(worst, float(np.abs(a - b).max()) / denom)
    return worst


class RolloutState:
    """The journaled facts of one staged rollout — everything a failover
    (or an operator reading statz) needs to know what was in flight."""

    __slots__ = ("candidate", "prior", "canary", "pct", "shadow", "status",
                 "reason", "started_at", "regression_detected_at",
                 "resolved_at", "_mono_detected", "_mono_resolved",
                 "_mono_started")

    def __init__(self, *, candidate: str, prior: str, canary: list[int],
                 pct: int, shadow: bool):
        self.candidate = candidate
        self.prior = prior
        self.canary = sorted(int(e) for e in canary)
        self.pct = int(pct)
        self.shadow = bool(shadow)
        self.status = "canary"  # canary -> promoted | rolled_back | aborted
        self.reason: str | None = None
        self.started_at = time.time()
        self.regression_detected_at: float | None = None
        self.resolved_at: float | None = None
        self._mono_started = _monotonic()
        self._mono_detected: float | None = None
        self._mono_resolved: float | None = None

    def payload(self) -> dict:
        """Journal/statz form (plain JSON-able dict)."""
        return {"candidate": self.candidate, "prior": self.prior,
                "canary": list(self.canary), "pct": self.pct,
                "shadow": self.shadow, "status": self.status,
                "reason": self.reason, "started_at": self.started_at,
                "resolved_at": self.resolved_at}

    def rollback_secs(self) -> float | None:
        """Regression-detected -> canaries-back-on-prior latency (the bench
        headline); None unless this rollout rolled back."""
        if self._mono_detected is None or self._mono_resolved is None:
            return None
        return self._mono_resolved - self._mono_detected


class RolloutGovernor:
    """Watch one rollout's canary cohort and resolve it.

    Lifecycle: built by ``gateway.rollout`` (which wires :meth:`observe`
    into the router and the cohort split into routing), then
    :meth:`start`-ed.  The governor thread evaluates the sliding windows
    every ``poll`` seconds and calls back into the gateway for the fleet
    action; ``wait()`` blocks callers until the rollout resolves.
    """

    def __init__(self, gateway, state: RolloutState, *,
                 window_secs: float | None = None,
                 auto_promote: bool = True,
                 min_canary_samples: int = 3,
                 nan_threshold: float = 1e-3,
                 divergence_threshold: float = 0.05,
                 latency_factor: float = 3.0,
                 latency_floor_secs: float = 0.05,
                 poll_secs: float = 0.25):
        self._gateway = gateway
        self.state = state
        self.window = (float(window_secs) if window_secs is not None
                       else env_float("TOS_SERVE_ROLLOUT_WINDOW_SECS", 5.0))
        self.auto_promote = bool(auto_promote)
        self.min_canary_samples = max(1, int(min_canary_samples))
        self.nan_threshold = float(nan_threshold)
        self.divergence_threshold = float(divergence_threshold)
        self.latency_factor = float(latency_factor)
        self.latency_floor = float(latency_floor_secs)
        self.poll = max(0.05, float(poll_secs))
        self._lock = tos_named_lock("rollout._lock")
        self._stop_evt = threading.Event()
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        # sliding windows, all (monotonic_t, value), pruned to self.window
        self._lat = {"primary": collections.deque(),
                     "canary": collections.deque()}
        self._model_errs = {"primary": collections.deque(),
                            "canary": collections.deque()}
        self._nan: collections.deque = collections.deque()
        self._div: collections.deque = collections.deque()
        self._canary_samples = 0  # lifetime, not windowed (promote gate)
        self._infra_errors = 0    # excluded from the verdict; statz only

    # -- router observer ------------------------------------------------------

    def observe(self, cohort: str, executor_id: int, ok: bool, secs: float,
                results, error, mirror_of) -> None:
        """One batch outcome from the router (worker threads; must stay
        cheap and never raise — the router guards, but don't lean on it)."""
        now = _monotonic()
        is_mirror = mirror_of is not None
        with self._lock:
            if not ok:
                if _is_infra_error(error):
                    # infra failure: recovery's problem, not the model's —
                    # but counted, so statz shows a noisy rollout
                    self._infra_errors += 1
                else:
                    self._model_errs[
                        "canary" if cohort == "canary" else "primary"
                    ].append((now, 1))
                return
            if cohort == "canary":
                self._canary_samples += 1
                self._nan.append((now, nan_fraction(results)))
                if is_mirror:
                    self._div.append((now, divergence(results, mirror_of)))
                else:
                    # mirrors replay a batch the primary already timed —
                    # only LIVE canary batches shape the latency compare
                    self._lat["canary"].append((now, secs))
            elif not is_mirror:
                self._lat["primary"].append((now, secs))

    # -- verdict --------------------------------------------------------------

    def _prune_locked(self, now: float) -> None:
        cut = now - self.window
        for dq in (*self._lat.values(), *self._model_errs.values(),
                   self._nan, self._div):
            while dq and dq[0][0] < cut:
                dq.popleft()

    def _verdict_locked(self, now: float) -> str | None:
        """The regression verdict over the current window, or None while
        the canary looks healthy.  Signals, cheapest/most-damning first."""
        self._prune_locked(now)
        nan_rate = (max(v for _, v in self._nan) if self._nan else 0.0)
        if nan_rate > self.nan_threshold:
            return (f"canary emitted NaN outputs (worst window fraction "
                    f"{nan_rate:.3f})")
        if self._div:
            worst = max(v for _, v in self._div)
            if worst > self.divergence_threshold:
                return (f"canary diverges from primary on mirrored traffic "
                        f"(worst relative divergence {worst:.4f} > "
                        f"{self.divergence_threshold:g})")
        c_errs = len(self._model_errs["canary"])
        if c_errs and not len(self._model_errs["primary"]):
            return (f"{c_errs} model-attributable error(s) on the canary, "
                    "none on the primary")
        c_lat = [v for _, v in self._lat["canary"]]
        p_lat = [v for _, v in self._lat["primary"]]
        if (len(c_lat) >= self.min_canary_samples
                and len(p_lat) >= self.min_canary_samples):
            c99 = float(np.percentile(c_lat, 99))
            p99 = float(np.percentile(p_lat, 99))
            if (c99 > self.latency_factor * max(p99, 1e-3)
                    and c99 - p99 > self.latency_floor):
                return (f"canary p99 inflated: {c99 * 1e3:.0f}ms vs primary "
                        f"{p99 * 1e3:.0f}ms "
                        f"(> {self.latency_factor:g}x)")
        return None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-rollout-governor")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.poll):
            now = _monotonic()
            with self._lock:
                if self.state.status != "canary":
                    return
                verdict = self._verdict_locked(now)
                samples = self._canary_samples
            if verdict is not None:
                self.state.regression_detected_at = time.time()
                self.state._mono_detected = now
                telemetry.counter("serve.rollout_regressions").inc()
                ttrace.event("rollout_regression", reason=verdict,
                             candidate=self.state.candidate)
                logger.warning("rollout regression detected: %s", verdict)
                self._resolve("rolled_back", verdict)
                return
            if (self.auto_promote
                    and now - self.state._mono_started >= self.window
                    and samples >= self.min_canary_samples):
                self._resolve("promoted", None)
                return

    def _resolve(self, status: str, reason: str | None) -> None:
        """Run the fleet action for ``status`` through the gateway and
        finalize + journal the state (``aborted`` when the action fails —
        an operator must never read "promoted" off a swap that half
        happened)."""
        try:
            if status == "promoted":
                self._gateway._promote_rollout(self)
            else:
                self._gateway._rollback_rollout(self, reason)
        except Exception as e:  # noqa: BLE001 - surface via status, never lose it
            logger.exception("rollout %s action failed", status)
            status, reason = "aborted", f"{status} failed: {e}"
        self._finalize(status, reason)

    def _finalize(self, status: str, reason: str | None) -> None:
        now = _monotonic()
        with self._lock:
            if self.state.status != "canary":
                return  # already resolved (stop raced the governor)
            self.state.status = status
            self.state.reason = reason
            self.state.resolved_at = time.time()
            self.state._mono_resolved = now
        ttrace.event("rollout_resolved", status=status, reason=reason,
                     candidate=self.state.candidate)
        self._gateway._note_rollout(self.state.payload())
        self._done.set()

    def promote(self) -> str:
        """Operator-driven promotion (the ``auto_promote=False`` workflow:
        the governor still auto-rolls-back on regression, but promotion
        waits for this call).  Runs the fleet swap now; returns the final
        status — which may be a resolution the governor already reached
        if it beat the operator to it."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if not self._done.is_set():
            self._resolve("promoted", None)
        return self.state.status

    def stop(self) -> None:
        """Abort an unresolved rollout (gateway close): no fleet action —
        the cluster is going away — just finalize + journal the abort."""
        self._stop_evt.set()
        if not self._done.is_set():
            self._finalize("aborted", "gateway closed mid-rollout")
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- introspection --------------------------------------------------------

    def active(self) -> bool:
        return self.state.status == "canary"

    def wait(self, timeout: float | None = None) -> str:
        """Block until the rollout resolves; returns the final status
        (still ``"canary"`` when ``timeout`` fires first)."""
        self._done.wait(timeout)
        return self.state.status

    def status(self) -> dict:
        """Live snapshot: the journaled payload plus the window evidence
        (sample counts, current windowed signals, rollback latency)."""
        now = _monotonic()
        with self._lock:
            self._prune_locked(now)
            out = self.state.payload()
            out.update({
                "canary_samples": self._canary_samples,
                "infra_errors": self._infra_errors,
                "window_secs": self.window,
                "windowed": {
                    "canary_lat": len(self._lat["canary"]),
                    "primary_lat": len(self._lat["primary"]),
                    "mirror_diffs": len(self._div),
                    "worst_divergence": (max(v for _, v in self._div)
                                         if self._div else None),
                    "worst_nan_fraction": (max(v for _, v in self._nan)
                                           if self._nan else None),
                },
            })
        out["rollback_secs"] = self.state.rollback_secs()
        return out
