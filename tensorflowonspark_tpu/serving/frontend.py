"""Reactor TCP frontend for the serving gateway.

One thread, every connection.  The previous frontend spent an OS thread per
client (``_serve_conn``) and a full blocking round-trip per request — at
production fan-in the thread wakeups and the one-request-per-RTT discipline,
not the model, were the ceiling (BENCH_r07: 1,122 req/s in-process vs 316
through TCP).  This module replaces it with the event-driven design of the
TensorFlow-Serving lineage:

- a single ``selectors``-based reactor thread owns the listener and every
  client socket (all non-blocking): non-blocking accept, incremental HMAC
  handshake, incremental v1/v2 frame decode with bounded buffers;
- **request pipelining** — each request may carry a client-assigned id,
  many requests stay outstanding per socket, and responses are written back
  *out of order by id* the moment their micro-batches complete.  A legacy
  peer that sends id-less requests (the pre-reactor ``GatewayClient``)
  keeps working: depth 1, id-less replies, same wire bytes;
- **zero-copy responses** — replies are protocol-5 v2 frames
  (``dataserver.frame_parts``) whose result arrays travel as out-of-band
  buffers; writes go through one non-blocking ``sendmsg`` attempt
  (``utils.net.sendmsg_some``) and partial writes park on a per-connection
  write queue re-armed by ``EVENT_WRITE`` — the reactor never blocks;
- **backpressure end to end** — per-connection outstanding-request cap
  (``TOS_SERVE_CONN_OUTSTANDING``) and the batcher's bounded admission
  queue (``TOS_SERVE_QUEUE``) both answer fast-fail ``unavailable`` (503)
  replies synchronously on the reactor, no thread handoff; a connection
  whose write queue backs up past a high-water mark stops being read until
  it drains.

Threading contract: every ``_on_*`` / ``_run`` / sweep method runs ONLY on
the reactor thread and must never block (enforced statically by the
``reactor-discipline`` toslint rule).  Completions arrive from batcher /
router threads via ``MicroBatcher.add_done_callback`` → ``_request_done``,
which appends the resolved request to a thread-safe queue and wakes the
reactor through a self-pipe; the reactor serializes at drain time, where
one scatter's replies to one connection coalesce into a single
multi-reply frame.  ``stop()`` runs on the caller's thread and is the one
place allowed to join.

Connection lifecycle: accept → server nonce sent → client blob verified
(stalls reaped after ``TOS_SERVE_HANDSHAKE_TIMEOUT``) → open (frames flow)
→ close (peer EOF, ``close`` op, protocol error, or shutdown).  A client
that disconnects with requests in flight has them cancelled so batcher
admission slots free immediately; results already computing are discarded
at scatter time.
"""

from __future__ import annotations

import collections
import contextlib
import heapq
import logging
import os
import pickle
import selectors
import socket
import struct
import sys
import threading
from time import monotonic as _monotonic

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.dataserver import (  # shared framing constants
    _LEN,
    _MAX_SECTIONS,
    _VEC_BIT,
    frame_parts,
)
from tensorflowonspark_tpu.serving.batcher import (
    MicroBatcher,
    ServeClosed,
    ServeQueueFull,
    ServeThrottled,
    ServeTimeout,
)
from tensorflowonspark_tpu.utils.envtune import env_float, env_int
from tensorflowonspark_tpu.utils.net import (
    HANDSHAKE_BLOB_BYTES,
    byte_views,
    hmac_server_challenge,
    hmac_server_verify,
    sendmsg_some,
    set_nodelay,
)

logger = logging.getLogger(__name__)

#: Hard per-frame bound: a request frame declaring more than this is a
#: protocol error and the connection is dropped before any allocation —
#: the read-side buffer bound of the reactor.
MAX_REQUEST_FRAME = 256 << 20
#: Per-read chunk; also the parse granularity of the incremental decoder.
_READ_CHUNK = 1 << 16
# Write-queue flow control: a connection whose un-flushed replies exceed
# the high-water mark stops being read (its requests stop being admitted)
# until the kernel drains it below the low-water mark.
_WRITE_HIGH_WATER = 8 << 20
_WRITE_LOW_WATER = 1 << 20

#: Decoder sentinel: the buffer does not hold a complete frame yet.  (A
#: dedicated object, NOT ``None`` — ``None`` is a pickleable frame value.)
_INCOMPLETE = object()


class ProtocolError(ConnectionError):
    """Malformed/hostile frame: the connection is dropped, the reactor and
    every other connection keep running."""


class FrameDecoder:
    """Incremental parser of the data plane's v1/v2 wire frames.

    Feed raw bytes; ``next_frame()`` returns one decoded object per call or
    ``_INCOMPLETE``.  Both formats are self-describing on the wire (the top
    bit of the length word), so legacy v1 peers and v2 pipelined clients
    share one decoder.  Complete frames are carved out as independent
    bytes objects before unpickling, so out-of-band buffer views never pin
    the (reused) read buffer.
    """

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def feed(self, data: bytes) -> None:
        self.buf += data

    def next_frame(self):
        buf = self.buf
        if len(buf) < 8:
            return _INCOMPLETE
        (word,) = _LEN.unpack_from(buf, 0)
        if word & _VEC_BIT:
            nsec = word & (_VEC_BIT - 1)
            if not 1 <= nsec <= _MAX_SECTIONS:
                raise ProtocolError(f"corrupt vectorized frame ({nsec} sections)")
            hdr = 8 + 8 * nsec
            if len(buf) < hdr:
                return _INCOMPLETE
            lens = struct.unpack_from(f">{nsec}Q", buf, 8)
            total = sum(lens)
            if total > MAX_REQUEST_FRAME:
                raise ProtocolError(f"oversized frame ({total} bytes)")
            if len(buf) < hdr + total:
                return _INCOMPLETE
            view = memoryview(buf)
            body = bytes(view[hdr:hdr + lens[0]])
            blob = bytes(view[hdr + lens[0]:hdr + total])
            view.release()
            del buf[:hdr + total]
            bview = memoryview(blob)
            bufs, off = [], 0
            for ln in lens[1:]:
                bufs.append(bview[off:off + ln])
                off += ln
            return self._loads(body, bufs)
        if word > MAX_REQUEST_FRAME:
            raise ProtocolError(f"oversized frame ({word} bytes)")
        if len(buf) < 8 + word:
            return _INCOMPLETE
        body = bytes(memoryview(buf)[8:8 + word])
        del buf[:8 + word]
        return self._loads(body, None)

    @staticmethod
    def _loads(body: bytes, bufs):
        # hostile pickle bytes can raise nearly anything (UnpicklingError,
        # EOFError, AttributeError, ...): every decode failure is a protocol
        # error on THIS connection, never a reactor death
        try:
            return (pickle.loads(body, buffers=bufs) if bufs is not None
                    else pickle.loads(body))
        except Exception as e:  # noqa: BLE001 - see comment above
            raise ProtocolError(
                f"undecodable frame: {type(e).__name__}: {e}") from e


class _Conn:
    """Reactor-thread-owned per-connection state."""

    __slots__ = ("sock", "fd", "peer", "decoder", "authed", "hs_nonce",
                 "hs_deadline", "wviews", "wbytes", "outstanding", "closing",
                 "events", "paused_read")

    def __init__(self, sock: socket.socket, peer, hs_deadline: float):
        self.sock = sock
        self.fd = sock.fileno()
        self.peer = peer
        self.decoder = FrameDecoder()
        self.authed = False
        self.hs_nonce = hmac_server_challenge()
        self.hs_deadline = hs_deadline
        self.wviews: list = []       # pending write views (flat, in order)
        self.wbytes = 0              # pending write bytes (flow control)
        self.outstanding: dict = {}  # _Request -> client id (None = legacy)
        self.closing = False         # close after the write queue flushes
        self.events = 0              # currently registered selector mask
        self.paused_read = False     # write-queue high-water reached


class ReactorFrontend:
    """The gateway's TCP endpoint: one reactor thread, pipelined clients.

    ``listener`` must already be bound+listening; the frontend owns it from
    here (including close at ``stop()``).  ``batcher`` is the gateway's
    :class:`MicroBatcher`; admission errors it raises become fast-fail
    replies without leaving the reactor thread.
    """

    def __init__(self, listener: socket.socket, authkey: bytes,
                 batcher: MicroBatcher, *, default_timeout: float,
                 handshake_timeout: float | None = None,
                 max_conn_outstanding: int | None = None):
        self._listener = listener
        listener.setblocking(False)
        self._authkey = authkey
        self._batcher = batcher
        self._default_timeout = float(default_timeout)
        self._handshake_timeout = (
            float(handshake_timeout) if handshake_timeout is not None
            else env_float("TOS_SERVE_HANDSHAKE_TIMEOUT", 5.0))
        self._max_outstanding = (
            int(max_conn_outstanding) if max_conn_outstanding is not None
            else env_int("TOS_SERVE_CONN_OUTSTANDING", 128))
        if self._handshake_timeout <= 0 or self._max_outstanding < 1:
            raise ValueError("handshake_timeout must be > 0 and "
                             "max_conn_outstanding >= 1")
        self._sel = selectors.DefaultSelector()
        # self-pipe: completion threads wake the reactor out of select()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        #: (conn, resolved request, client id) from completion threads;
        #: deque append/popleft are atomic — no lock needed.
        self._completions: collections.deque = collections.deque()
        self._wake_pending = False
        self._conns: dict[int, _Conn] = {}   # reactor-thread only
        # mid-handshake connections only (reactor-thread only): the
        # per-pass deadline scans walk THIS set, not every established
        # connection — at production fan-in the steady-state conns must
        # cost the hot loop nothing
        self._handshaking: set[_Conn] = set()
        # deadline tracking (reactor-thread only): heap of mutable
        # [deadline, seq, req, conn] entries + req -> entry index.  When a
        # request resolves its entry is BLANKED (req/conn set to None), not
        # searched out of the heap — otherwise every resolved request (its
        # rows, results, and connection) would stay pinned until its
        # deadline passed, which at qps x timeout scale is real memory.
        self._deadline_heap: list = []
        self._deadline_entries: dict = {}
        self._deadline_seq = 0
        self._n_outstanding = 0
        self._stopping = False
        self._stopped = False
        self._conn_gauge = telemetry.gauge("serve.frontend.connections")
        self._outstanding_gauge = telemetry.gauge(
            "serve.frontend.outstanding")
        self._frames_in = telemetry.counter("serve.frontend.frames_in")
        self._frames_out = telemetry.counter("serve.frontend.frames_out")
        self._loop_lag = telemetry.histogram("serve.frontend.loop_lag_secs")
        self._conn_gauge.set(0)
        self._outstanding_gauge.set(0)
        # A serving driver is a latency process: the interpreter's default
        # 5ms GIL switch interval convoys every reactor<->batcher<->router
        # handoff into a multi-millisecond stall (measured: ~40% of the
        # instant-model wire ceiling on the 2-core bench box).  1ms trades
        # a little switch overhead for bounded handoff latency; restored
        # at stop().  TOS_SERVE_SWITCH_INTERVAL tunes it (5 = CPython's
        # default, effectively opting out).
        self._prev_switch_interval = sys.getswitchinterval()
        sys.setswitchinterval(
            env_float("TOS_SERVE_SWITCH_INTERVAL", 1.0) / 1e3)
        self._sel.register(listener, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wakeup")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-reactor")
        self._thread.start()

    # -- reactor loop (reactor thread only) ----------------------------------

    def _run(self) -> None:
        while not self._stopping:
            events = self._sel.select(self._next_timeout())
            t0 = _monotonic()
            if self._stopping:
                break
            for key, mask in events:
                try:
                    if key.data == "accept":
                        self._on_accept()
                    elif key.data == "wakeup":
                        self._on_wakeup()
                    else:
                        conn = key.data
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(conn)
                        if (mask & selectors.EVENT_READ
                                and self._conns.get(conn.fd) is conn):
                            self._on_readable(conn)
                except Exception:  # noqa: BLE001 - one bad connection must never kill the reactor
                    logger.exception("reactor event handler failed")
                    if isinstance(key.data, _Conn):
                        self._close_conn(key.data, "handler error")
            self._drain_completions()
            self._sweep_deadlines()
            self._reap_handshakes()
            if events:
                # reactor-loop lag: how long this pass kept new events
                # waiting (the single-thread design's latency tax — watch
                # its p99 before blaming the model)
                self._loop_lag.observe(_monotonic() - t0)
        self._teardown()

    def _next_timeout(self) -> float:
        now = _monotonic()
        nxt = now + 0.5
        for conn in self._handshaking:
            if conn.hs_deadline < nxt:
                nxt = conn.hs_deadline
        if self._deadline_heap and self._deadline_heap[0][0] < nxt:
            nxt = self._deadline_heap[0][0]
        return max(0.0, min(nxt - now, 0.5))

    def _on_accept(self) -> None:
        while True:
            try:
                sock, peer = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener closed under us (shutdown)
            sock.setblocking(False)
            set_nodelay(sock)
            conn = _Conn(sock, peer,
                         _monotonic() + self._handshake_timeout)
            self._conns[conn.fd] = conn
            self._handshaking.add(conn)
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.events = selectors.EVENT_READ
            self._conn_gauge.set(len(self._conns))
            telemetry.counter("serve.frontend.accepts").inc()
            # server speaks first: its handshake nonce
            self._queue_write(conn, [conn.hs_nonce])

    def _on_wakeup(self) -> None:
        with contextlib.suppress(BlockingIOError, InterruptedError):
            while os.read(self._wake_r, 4096):
                pass

    def _on_readable(self, conn: _Conn) -> None:
        try:
            while not conn.paused_read:
                try:
                    data = conn.sock.recv(_READ_CHUNK)
                except (BlockingIOError, InterruptedError):
                    break
                if not data:
                    self._close_conn(conn, "peer closed")
                    return
                conn.decoder.feed(data)
                if not self._process_buffer(conn):
                    return  # connection closed while processing
                if len(data) < _READ_CHUNK:
                    break
        except ProtocolError as e:
            telemetry.counter("serve.frontend.protocol_errors").inc()
            logger.warning("gateway connection %s: %s; disconnecting",
                           conn.peer, e)
            self._close_conn(conn, "protocol error")
        except OSError as e:
            self._close_conn(conn, f"read failed: {e}")

    def _process_buffer(self, conn: _Conn) -> bool:
        """Drain every complete frame (and the handshake blob) from the
        connection's decode buffer; False when the connection was closed."""
        if not conn.authed:
            if len(conn.decoder.buf) < HANDSHAKE_BLOB_BYTES:
                return True
            blob = bytes(conn.decoder.buf[:HANDSHAKE_BLOB_BYTES])
            del conn.decoder.buf[:HANDSHAKE_BLOB_BYTES]
            ok, proof = hmac_server_verify(self._authkey, conn.hs_nonce, blob)
            if not ok:
                telemetry.counter("serve.frontend.auth_failures").inc()
                logger.warning("rejected gateway connection from %s: bad "
                               "authkey", conn.peer)
                # closing BEFORE the queue: the flush that drains the proof
                # frame closes the connection (possibly inline right here)
                conn.closing = True
                self._set_events(conn, conn.events & ~selectors.EVENT_READ)
                self._queue_write(conn, [proof])
                return self._conns.get(conn.fd) is conn
            self._queue_write(conn, [proof])
            conn.authed = True
            conn.hs_deadline = 0.0
            self._handshaking.discard(conn)
        admissions: list = []  # (rows, deadline, done_cb) per predict frame
        rids: list = []
        while self._conns.get(conn.fd) is conn and not conn.closing:
            obj = conn.decoder.next_frame()
            if obj is _INCOMPLETE:
                break
            self._frames_in.inc()
            self._handle_frame(conn, obj, admissions, rids)
        if admissions and self._conns.get(conn.fd) is conn:
            self._admit(conn, admissions, rids)
        return self._conns.get(conn.fd) is conn

    def _admit(self, conn: _Conn, admissions: list, rids: list) -> None:
        """Bulk-admit one read pass's predict frames: ONE batcher critical
        section for the whole pipelined burst."""
        out = self._batcher.submit_many(admissions)
        for (_rows, deadline, _cb, _tenant), rid, res in zip(
                admissions, rids, out):
            if isinstance(res, ServeThrottled):
                # per-tenant rejection (429): THIS tenant is over budget;
                # the queue may be nowhere near full for everyone else
                self._queue_reply(conn, self._err_reply(
                    "throttled", str(res), rid))
            elif isinstance(res, ServeQueueFull):
                self._queue_reply(conn, self._err_reply(
                    "unavailable", str(res), rid))
            elif isinstance(res, ServeClosed):
                self._queue_reply(conn, self._err_reply("closed", str(res), rid))
            else:
                conn.outstanding[res] = rid
                self._n_outstanding += 1
                self._deadline_seq += 1
                entry = [deadline, self._deadline_seq, res, conn]
                heapq.heappush(self._deadline_heap, entry)
                self._deadline_entries[res] = entry
        self._outstanding_gauge.set(self._n_outstanding)

    def _handle_frame(self, conn: _Conn, msg, admissions: list,
                      rids: list) -> None:
        if not isinstance(msg, tuple) or not msg:
            raise ProtocolError(f"malformed request frame: {type(msg).__name__}")
        op = msg[0]
        if op == "predict":
            if len(msg) < 2:
                raise ProtocolError("predict frame without rows")
            rid = msg[3] if len(msg) > 3 else None  # None = legacy depth-1
            try:
                timeout = (float(msg[2])
                           if len(msg) > 2 and msg[2] is not None
                           else self._default_timeout)
                rows = list(msg[1])
                # optional tenant key (v3 field; legacy 3/4-tuple frames —
                # and v2 peers that omit it — land on the anonymous tenant)
                tenant = (str(msg[4]) if len(msg) > 4 and msg[4] is not None
                          else "")
            except (TypeError, ValueError) as e:
                raise ProtocolError(f"bad predict frame: {e}") from e
            if timeout != timeout or timeout == float("inf"):
                # a NaN deadline would poison the shared deadline heap
                # (NaN comparisons are always False — heap order breaks
                # frontend-wide); inf would opt out of expiry entirely
                raise ProtocolError(f"non-finite predict timeout: {timeout!r}")
            if not rows:
                self._queue_reply(conn, self._err_reply(
                    "internal", "predict needs at least one row", rid))
                return
            if (len(conn.outstanding) + len(admissions)
                    >= self._max_outstanding):
                # per-connection pipelining cap: fast-fail 503, no handoff
                telemetry.counter("serve.frontend.throttled_total").inc()
                self._queue_reply(conn, self._err_reply(
                    "unavailable", f"connection pipelining cap "
                    f"({self._max_outstanding} outstanding); widen "
                    f"TOS_SERVE_CONN_OUTSTANDING or add connections", rid))
                return
            deadline = _monotonic() + timeout
            admissions.append((rows, deadline,
                               lambda r, c=conn, i=rid:
                               self._request_done(c, r, i),
                               tenant))
            rids.append(rid)
        elif op == "ping":
            rid = msg[1] if len(msg) > 1 else None
            self._queue_reply(conn, ("ok", "pong") if rid is None
                              else ("ok", "pong", rid))
        elif op == "close":
            # closing BEFORE the queue: the flush that drains the ack frame
            # closes the connection (possibly inline)
            conn.closing = True
            self._set_events(conn, conn.events & ~selectors.EVENT_READ)
            self._queue_reply(conn, ("ok",))
        else:
            self._queue_reply(conn, self._err_reply(
                "internal", f"unknown op {op!r}",
                msg[-1] if len(msg) > 1 and isinstance(msg[-1], int) else None))

    @staticmethod
    def _err_reply(kind: str, text: str, rid) -> tuple:
        return (("err", kind, text) if rid is None
                else ("err", kind, text, rid))

    # -- completion path (batcher/router threads) ----------------------------

    def _request_done(self, conn: _Conn, req, rid) -> None:
        """Done callback (router/batcher threads): hand the resolved
        request to the reactor via the completion queue + self-pipe.
        Serialization happens at drain time, where same-connection replies
        from one scatter coalesce into a single multi-reply frame — one
        pickle and one sendmsg for a whole batch instead of one each."""
        self._completions.append((conn, req, rid))
        self._wakeup()

    @staticmethod
    def _reply_entry(req, rid) -> tuple:
        """(rid, "ok", results) / (rid, "err", kind, text) — the per-request
        payload of a multi-reply ``okm`` frame; ``entry[1:]`` is exactly the
        legacy single-reply tuple shape."""
        err = req.error
        if err is None:
            return (rid, "ok", req.results)
        kind = ("throttled" if isinstance(err, ServeThrottled)
                else "unavailable" if isinstance(err, ServeQueueFull)
                else "deadline" if isinstance(err, ServeTimeout)
                else "closed" if isinstance(err, ServeClosed)
                else "internal")
        return (rid, "err", kind, str(err) or type(err).__name__)

    def _wakeup(self) -> None:
        # dedup: one pending byte is enough, and the reactor resets the
        # flag BEFORE draining, so a completion enqueued after the reset
        # always writes its own wakeup — no lost signal
        if self._wake_pending:
            return
        self._wake_pending = True
        try:
            os.write(self._wake_w, b"\x00")
        except (BlockingIOError, OSError):  # toslint: allow-silent(pipe full means a wakeup is already pending; closed pipe means the reactor is gone)
            pass

    def _drain_completions(self) -> None:
        self._wake_pending = False
        # conn -> multi-reply entries; order within a conn is preserved
        grouped: dict[_Conn, list] = {}
        drained = False
        while True:
            try:
                conn, req, rid = self._completions.popleft()
            except IndexError:
                break
            drained = True
            if req in conn.outstanding:
                del conn.outstanding[req]
                self._n_outstanding -= 1
            entry = self._deadline_entries.pop(req, None)
            if entry is not None:
                entry[2] = entry[3] = None  # unpin; heap drops it on expiry
            if self._conns.get(conn.fd) is not conn:
                continue  # client gone; reply dropped
            if req.trace is not None and req.resolved_at is not None:
                # stage span: reply (request resolved -> its frame queued on
                # the reactor); the kernel write that follows is the one
                # part of the path no span can cover from this side
                ttrace.record_child("serve.reply", req.trace,
                                    req.resolved_at,
                                    _monotonic() - req.resolved_at)
            grouped.setdefault(conn, []).append(self._reply_entry(req, rid))
        if drained:
            self._outstanding_gauge.set(self._n_outstanding)
        # ONE frame and ONE flush per connection per pass: a whole
        # scatter's replies to one pipelined peer cost one pickle and one
        # sendmsg instead of one each.  Legacy (id-less) peers get their
        # classic per-request frames — they predate the okm op.
        for conn, entries in grouped.items():
            if self._conns.get(conn.fd) is not conn:
                continue
            views: list = []
            pipelined = [e for e in entries if e[0] is not None]
            for e in entries:
                if e[0] is None:
                    self._frames_out.inc()
                    views.extend(byte_views(frame_parts(e[1:], wire=2)))
            if pipelined:
                self._frames_out.inc()
                views.extend(byte_views(
                    frame_parts(("okm", pipelined), wire=2)))
            conn.wbytes += sum(len(v) for v in views)
            conn.wviews.extend(views)
            self._flush_writes(conn)

    # -- write path (reactor thread only) ------------------------------------

    def _queue_reply(self, conn: _Conn, reply: tuple) -> None:
        self._frames_out.inc()
        self._queue_write(conn, frame_parts(reply, wire=2))

    def _queue_write(self, conn: _Conn, buffers) -> None:
        if self._conns.get(conn.fd) is not conn:
            return  # closed earlier in this pass; drop the reply
        views = byte_views(buffers)
        conn.wbytes += sum(len(v) for v in views)
        conn.wviews.extend(views)
        self._flush_writes(conn)

    def _on_writable(self, conn: _Conn) -> None:
        self._flush_writes(conn)

    def _flush_writes(self, conn: _Conn) -> None:
        try:
            while conn.wviews:
                sent = sendmsg_some(conn.sock, conn.wviews)
                if sent == 0:
                    break
                conn.wbytes -= sent
        except OSError as e:
            self._close_conn(conn, f"send failed: {e}")
            return
        if conn.wviews:
            self._set_events(conn, conn.events | selectors.EVENT_WRITE)
            if conn.wbytes > _WRITE_HIGH_WATER and not conn.paused_read:
                # reply backlog: stop reading (and admitting) this client
                # until the kernel drains it — per-connection backpressure
                conn.paused_read = True
                self._set_events(conn, conn.events & ~selectors.EVENT_READ)
            elif (conn.paused_read and conn.wbytes <= _WRITE_LOW_WATER
                    and not conn.closing):
                # hysteresis: resume reads at the LOW water mark, not only
                # once the backlog fully drains
                conn.paused_read = False
                self._set_events(conn, conn.events | selectors.EVENT_READ)
        else:
            if conn.closing:
                self._close_conn(conn, "closed")
                return
            self._set_events(conn, conn.events & ~selectors.EVENT_WRITE)
            if conn.paused_read:
                conn.paused_read = False
                self._set_events(conn, conn.events | selectors.EVENT_READ)

    def _set_events(self, conn: _Conn, mask: int) -> None:
        if mask == conn.events or self._conns.get(conn.fd) is not conn:
            return
        if not mask:
            self._sel.unregister(conn.sock)
        elif conn.events:
            self._sel.modify(conn.sock, mask, conn)
        else:
            # a mask-0 connection (e.g. a closing one whose final reply hit
            # a full send buffer) is fully unregistered: re-register, don't
            # modify — modify() on an unregistered fd raises
            self._sel.register(conn.sock, mask, conn)
        conn.events = mask

    # -- sweeps (reactor thread only) ----------------------------------------

    def _sweep_deadlines(self) -> None:
        now = _monotonic()
        while self._deadline_heap and self._deadline_heap[0][0] <= now:
            _, _, req, _conn = heapq.heappop(self._deadline_heap)
            if req is None:
                continue  # resolved earlier; entry was blanked
            self._deadline_entries.pop(req, None)
            if not req.event.is_set():
                # resolves with ServeTimeout; the done callback routes the
                # "deadline" reply back through the completion queue
                self._batcher.expire(req)

    def _reap_handshakes(self) -> None:
        if not self._handshaking:
            return
        now = _monotonic()
        stalled = [c for c in self._handshaking if c.hs_deadline <= now]
        for conn in stalled:
            telemetry.counter("serve.frontend.handshake_timeouts").inc()
            logger.warning("reaping gateway connection from %s: handshake "
                           "stalled past %.1fs", conn.peer,
                           self._handshake_timeout)
            self._close_conn(conn, "handshake timeout")

    def _close_conn(self, conn: _Conn, reason: str) -> None:
        if self._conns.get(conn.fd) is not conn:
            return  # already closed this pass
        del self._conns[conn.fd]
        self._handshaking.discard(conn)
        if conn.events:
            with contextlib.suppress(KeyError, OSError, ValueError):
                self._sel.unregister(conn.sock)
        with contextlib.suppress(OSError):
            conn.sock.close()
        self._conn_gauge.set(len(self._conns))
        telemetry.counter("serve.frontend.disconnects").inc()
        if conn.outstanding:
            # free the batcher admission slots NOW; in-flight slices finish
            # on their replica and are discarded at scatter time.  cancel()
            # fires the done callbacks inline (this thread) — their replies
            # enqueue and are dropped above because the conn is deregistered.
            reqs = list(conn.outstanding)
            self._n_outstanding -= len(conn.outstanding)
            conn.outstanding.clear()
            self._outstanding_gauge.set(self._n_outstanding)
            for req in reqs:
                self._batcher.cancel(req, ServeClosed(
                    f"client disconnected ({reason}) with the request "
                    "outstanding"))
        logger.debug("gateway connection %s closed: %s", conn.peer, reason)

    def _teardown(self) -> None:
        # one last drain + non-blocking flush: the gateway closes router
        # and batcher BEFORE stop(), so the final error replies they
        # resolved are sitting in the completion queue right now — deliver
        # them (best-effort: a full send buffer still drops) instead of
        # slamming every pipelined client with a raw dead socket
        self._drain_completions()
        for conn in list(self._conns.values()):
            self._close_conn(conn, "frontend stopped")
        with contextlib.suppress(Exception):
            self._sel.close()
        with contextlib.suppress(OSError):
            self._listener.close()

    # -- lifecycle (caller threads) ------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        """Stop accepting, cancel outstanding wire requests, close every
        connection, join the reactor.  Idempotent.

        Call with no completion producers left (the gateway closes router
        and batcher FIRST, which resolves every request): the wake-pipe
        fds are closed only here, after the join — closing them anywhere a
        racing ``_wakeup`` could still write would hand the stray byte to
        whatever unrelated file just reused the fd number."""
        if self._stopped:
            return
        self._stopped = True
        self._stopping = True
        self._wakeup()  # pop the reactor out of select(); it sees _stopping
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():  # pragma: no cover - defensive
            logger.warning("serving reactor did not stop within %.1fs",
                           timeout)
        else:
            for fd in (self._wake_r, self._wake_w):
                with contextlib.suppress(OSError):
                    os.close(fd)
        sys.setswitchinterval(self._prev_switch_interval)
