"""Node-side resident serving map_fun.

Each serving replica runs this loop: load the bundle once (through the
process-wide single-flight cache), then answer micro-batches streamed in by
the gateway's router over the ordinary data plane — one ``infer_partition``
round per batch, one result per input row, in order.

Latency properties:

- the gateway pads every batch to the static ``max_batch`` shape, so the
  jitted apply compiles exactly once and never recompiles on partial
  batches (the same pad-and-slice trick ``bundle_inference_loop`` uses);
- control items (``{CTL_KEY: "reload"}``) ride the same stream as one-item
  rounds and are acked with a one-item result, so the exactly-count
  transport invariant holds for them too.  A ``reload`` invalidates the
  bundle cache entry and reloads — the node half of the gateway's hot
  swap.  The control item may carry its own ``export_dir`` (the staged-
  rollout primitive: a canary replica switches to the CANDIDATE bundle's
  directory while the rest of the fleet stays on the boot export) and a
  ``candidate`` bit marking the loaded bundle as a rollout candidate (the
  ``bad_model`` chaos hook fires only then).  The ack echoes the active
  export_dir plus its on-disk bundle signature, so the gateway can verify
  every cohort member actually converged on the bundle it asked for — a
  replica acking a different signature is a promotion laggard.

Termination is the standard feed contract: EOF (cluster shutdown) or the
driver's stop signal ends the loop; a supervised restart simply re-enters
it, loading whatever bundle is newest on disk.
"""

from __future__ import annotations

import time

import numpy as np


def serving_loop(args, ctx) -> None:
    """map_fun: serve gateway micro-batches with the bundle at
    ``args.export_dir``.

    Args: ``export_dir`` (required), ``max_batch`` (static batch shape;
    default ``TOS_SERVE_MAX_BATCH`` — keep it equal to the gateway's),
    ``postprocess`` ("argmax" for int class ids), ``input_mapping``
    (row-dict column selection, see ``inference.rows_to_features``).
    """
    from tensorflowonspark_tpu import faultinject
    from tensorflowonspark_tpu.checkpoint import (
        bundle_signature,
        invalidate_bundle,
        load_bundle_cached,
    )
    from tensorflowonspark_tpu.inference import _arg, rows_to_features
    from tensorflowonspark_tpu.models.registry import build_apply
    from tensorflowonspark_tpu.serving.batcher import CTL_KEY
    from tensorflowonspark_tpu.telemetry import trace as ttrace
    from tensorflowonspark_tpu.utils.envtune import env_int

    export_dir = _arg(args, "export_dir")
    if not export_dir:
        raise ValueError("serving_loop requires args.export_dir")
    max_batch = (int(_arg(args, "max_batch", 0) or 0)
                 or env_int("TOS_SERVE_MAX_BATCH", 64))
    postprocess = _arg(args, "postprocess")
    input_mapping = _arg(args, "input_mapping")

    variables, _config, apply_fn = load_bundle_cached(export_dir, build_apply)
    # sharded-embedding bundles (config block written by the sharded
    # export): load THIS replica's range of the table — re-sharded over the
    # serve world — plus the dense-half apply, and answer the router's
    # lookup fan-out on the dedicated embed queue pair from a responder
    # thread.  Scoring batches then arrive as one-item `sharded_batch`
    # control rounds carrying the rows the router already gathered.
    embed_shard = None
    sharded_apply = None
    if _config.get("sharded_embedding"):
        import threading

        from tensorflowonspark_tpu.embedding.serve import (
            build_sharded_apply,
            embed_responder_loop,
            load_serving_shard,
        )

        _, embed_shard = load_serving_shard(
            export_dir, _config["sharded_embedding"], ctx.executor_id,
            ctx.num_executors)
        sharded_apply = build_sharded_apply(_config)
        threading.Thread(
            target=embed_responder_loop, args=(ctx, embed_shard),
            daemon=True, name=f"embed-responder-{ctx.executor_id}").start()
    # staged-rollout state: True while this replica serves a rollout
    # CANDIDATE bundle (set by the reload ctl's `candidate` bit) — the
    # bad_model chaos hook only ever corrupts candidate output
    serving_candidate = False
    batches = ctx.metrics.counter("serve.node_batches")
    rows_served = ctx.metrics.counter("serve.node_rows")
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        items = feed.next_batch(max_batch)
        if not items:
            continue
        if len(items) == 1 and isinstance(items[0], dict) and CTL_KEY in items[0]:
            op = items[0][CTL_KEY]
            if op == "sharded_batch":
                # one wrapped scoring batch: raw rows + the fused-table rows
                # the router's fan-out gathered; one result item back keeps
                # the exactly-count invariant (the router unwraps it)
                rows = items[0]["rows"]
                emb = np.asarray(items[0]["emb"], np.float32)
                with ctx.metrics.timed("serve.node_batch_secs"), \
                        ttrace.span("serve.node_compute",
                                    parent=getattr(feed, "last_trace", None)):
                    x = rows_to_features(list(rows), input_mapping)
                    out = np.asarray(sharded_apply(variables, x, emb))
                results = ([int(p) for p in out.argmax(axis=-1)]
                           if postprocess == "argmax" else list(out))
                batches.inc()
                rows_served.inc(len(rows))
                feed.batch_results([{CTL_KEY: "sharded_results",
                                     "results": results}], chunk=True)
                continue
            if op == "reload":
                # the ctl may redirect this replica to a DIFFERENT export
                # (canary load / rollback); a plain reload re-reads the
                # active one
                export_dir = str(items[0].get("export_dir") or export_dir)
                serving_candidate = bool(items[0].get("candidate"))
                invalidate_bundle(export_dir)
                variables, _config, apply_fn = load_bundle_cached(
                    export_dir, build_apply)
                if embed_shard is not None and _config.get("sharded_embedding"):
                    # newer export: swap the resident range in place (the
                    # responder thread reads shard.rows, so the swap is
                    # visible to in-flight lookups atomically per request)
                    from tensorflowonspark_tpu.embedding.serve import (
                        build_sharded_apply,
                        load_serving_shard,
                    )

                    _, fresh = load_serving_shard(
                        export_dir, _config["sharded_embedding"],
                        ctx.executor_id, ctx.num_executors)
                    embed_shard.rows = fresh.rows
                    sharded_apply = build_sharded_apply(_config)
                ctx.metrics.counter("serve.node_reloads").inc()
                # echo dir + on-disk signature: the gateway verifies every
                # cohort member converged on the bundle it asked for
                feed.batch_results([{CTL_KEY: "reloaded",
                                     "export_dir": export_dir,
                                     "signature": bundle_signature(export_dir)}])
            elif op == "ping":
                # echo the nonce: the router's re-admission resync matches
                # ITS pong (inputs are processed in order, so everything
                # popped before it is provably stale) — see router._resync
                feed.batch_results([{CTL_KEY: "pong",
                                     "nonce": items[0].get("nonce")}])
            else:
                feed.batch_results([{CTL_KEY: f"unknown:{op}"}])
            continue
        n = len(items)
        # gateway batches arrive pre-padded (len == max_batch); pad here too
        # so direct infer_partition callers get the same single-compile apply
        padded = list(items) + [items[-1]] * (max_batch - n)
        # a sampled round's ctx rode the EndPartition that closed this batch
        # (feed.last_trace): the pure-compute span separates model time from
        # the node_round span's queue wait in the merged trace
        with ctx.metrics.timed("serve.node_batch_secs"), \
                ttrace.span("serve.node_compute",
                            parent=getattr(feed, "last_trace", None)):
            x = rows_to_features(padded, input_mapping)
            out = apply_fn(variables, x)
            corrupt, delay = faultinject.bad_model(serving_candidate)
            if delay:
                time.sleep(delay)
            if corrupt:
                # injected model regression: candidate outputs go NaN —
                # the rollout governor must catch this, never the clients
                # of primary replicas
                out = ({k: np.full_like(np.asarray(v, dtype=float),
                                        np.nan) for k, v in out.items()}
                       if isinstance(out, dict)
                       else np.full_like(np.asarray(out, dtype=float),
                                         np.nan))
        if isinstance(out, dict):
            if postprocess == "argmax":
                raise ValueError("postprocess='argmax' needs a single-output "
                                 "model; this bundle emits named outputs "
                                 f"{sorted(out)}")
            cols = {k: np.asarray(v)[:n] for k, v in out.items()}
            results: list = [{k: v[i] for k, v in cols.items()}
                             for i in range(n)]
        else:
            preds = np.asarray(out)[:n]
            if postprocess == "argmax":
                results = [int(p) for p in preds.argmax(axis=-1)]
            else:
                results = list(preds)
        batches.inc()
        rows_served.inc(n)
        # one ResultChunk = one queue put + one collect round-trip per batch
        feed.batch_results(results, chunk=True)
