"""Driver-side online serving gateway: request/response over a live cluster.

The reference stack only ever scored data as Spark partitions — a batch
path (PAPER.md §3.3).  This gateway adds the missing request/response
path: ``cluster.serve(export_dir)`` returns a handle whose ``predict`` /
``predict_async`` answer individual requests with micro-batched, replica-
routed inference over the SAME resident nodes, data plane, telemetry, and
elastic machinery the batch path uses.

Three layers, composed here:

- admission + coalescing: :class:`~.batcher.MicroBatcher` (bounded queue
  ``TOS_SERVE_QUEUE``, fast-fail rejection, per-request deadlines
  ``TOS_SERVE_TIMEOUT``, flush at ``TOS_SERVE_MAX_BATCH`` rows or
  ``TOS_SERVE_MAX_DELAY_MS``);
- routing + failover: :class:`~.router.ReplicaRouter` (least-outstanding
  replica choice, one retry on a live replica after a death, incarnation-
  fenced recovery);
- the wire endpoint: :class:`~.frontend.ReactorFrontend` — a single-thread
  ``selectors`` reactor speaking the data plane's framing (HMAC handshake
  on the cluster authkey, then protocol-5 zero-copy v2 frames) with
  request *pipelining*: many id-tagged requests outstanding per socket,
  responses written back out of order as their micro-batches complete.
  :class:`GatewayClient` is the matching pipelined remote caller;
  :class:`GatewayClientPool` fans closed-loop callers over several
  sockets.

Hot reload: a version watcher polls ``export_dir``; when a newer export
lands, in-flight batches drain, every replica swaps its bundle via a
control round (``serving_loop`` + ``checkpoint.invalidate_bundle``), and
dispatch resumes — requests keep queuing during the swap.

Staged rollouts (ISSUE 16): ``rollout(export_dir, ...)`` replaces the
stop-the-world swap with a supervised one — load the candidate bundle on
a canary cohort only (signature-verified targeted control round), split
``canary_pct`` of live traffic onto it, optionally shadow-mirror primary
batches for output diffing, and let a :class:`~.rollout.RolloutGovernor`
watch the canary's error rate / NaN rate / divergence / p99 against the
primary baseline over a sliding window.  A healthy window promotes
(fleet-wide verified swap, laggards quarantined until converged); a
regression auto-rolls the canaries back to the prior export.  Every state
transition is journaled through the coordinator's rollout registry, so a
control-plane failover restores what was in flight.

Per-tenant fairness: requests may carry a tenant key (``predict(...,
tenant=...)``; v2/v3 frames carry it on the wire, legacy id-less clients
land in the anonymous tenant).  Admission runs per-tenant token buckets
and weighted DRR queues with a brownout ladder instead of one cliff —
see ``serving/tenancy.py``.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
from time import monotonic as _monotonic
from typing import Any, Sequence

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.checkpoint import bundle_signature
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.dataserver import _recv, _send
from tensorflowonspark_tpu.serving.batcher import (  # noqa: F401 - CTL_KEY re-exported
    CTL_KEY,
    MicroBatcher,
    PendingPrediction,
    ServeClosed,
    ServeQueueFull,
    ServeThrottled,
    ServeTimeout,
)
from tensorflowonspark_tpu.serving.frontend import ReactorFrontend
from tensorflowonspark_tpu.serving.rollout import RolloutGovernor, RolloutState
from tensorflowonspark_tpu.serving.router import ReplicaRouter
from tensorflowonspark_tpu.utils.envtune import env_float, env_int
from tensorflowonspark_tpu.utils.net import (
    bound_socket,
    connect_with_backoff,
    hmac_handshake_client,
    local_ip,
)

logger = logging.getLogger(__name__)

_ERR_TYPES = {"unavailable": ServeQueueFull, "deadline": ServeTimeout,
              "closed": ServeClosed, "throttled": ServeThrottled}


class ServingGateway:
    """Handle returned by ``cluster.serve(export_dir, ...)``.

    ``predict(rows, timeout)`` blocks for one request; ``predict_async``
    returns a :class:`~.batcher.PendingPrediction`.  ``endpoint`` is the
    TCP frontend's ``(host, port)`` for :class:`GatewayClient` callers.
    """

    def __init__(self, cluster, export_dir: str, *,
                 qname_in: str = "input", qname_out: str = "output",
                 max_batch: int | None = None,
                 max_delay_ms: float | None = None,
                 queue_limit: int | None = None,
                 default_timeout: float | None = None,
                 listen: bool = True, listen_host: str = "",
                 handshake_timeout: float | None = None,
                 max_conn_outstanding: int | None = None,
                 reload_poll_secs: float = 2.0,
                 tenant_weights: dict[str, float] | None = None):
        self.export_dir = export_dir
        self._cluster = cluster
        self.max_batch = (int(max_batch) if max_batch is not None
                          else env_int("TOS_SERVE_MAX_BATCH", 64))
        delay_ms = (float(max_delay_ms) if max_delay_ms is not None
                    else env_float("TOS_SERVE_MAX_DELAY_MS", 5.0))
        self.queue_limit = (int(queue_limit) if queue_limit is not None
                            else env_int("TOS_SERVE_QUEUE", 256))
        self.default_timeout = (float(default_timeout)
                                if default_timeout is not None
                                else env_float("TOS_SERVE_TIMEOUT", 30.0))
        if self.max_batch < 1 or self.queue_limit < 1:
            raise ValueError("max_batch and queue_limit must be >= 1")
        if delay_ms < 0 or self.default_timeout <= 0:
            raise ValueError("max_delay_ms must be >= 0 and default_timeout "
                             "> 0")
        self._authkey = cluster.authkey
        self._closed = False
        self._reloading = False
        self._reload_lock = tos_named_lock("gateway._reload_lock")
        self._rollout: RolloutGovernor | None = None
        self._router = ReplicaRouter(cluster, None,  # batcher set just below
                                     qname_in=qname_in, qname_out=qname_out,
                                     request_timeout=self.default_timeout)
        self._batcher = MicroBatcher(
            self._router.submit, max_batch=self.max_batch,
            max_delay_secs=delay_ms / 1e3, queue_limit=self.queue_limit,
            pause_fn=lambda: self._reloading,
            capacity_fn=self._router.has_capacity,
            tenant_weights=tenant_weights)
        self._router._batcher = self._batcher
        # sharded-embedding bundles: the config's "sharded_embedding" block
        # puts the router into fan-out mode (gather each batch's fused-
        # table rows from the replica shards before scoring)
        self._refresh_embed_plan()
        # version watch: swap in a newer export, draining in-flight first
        self._export_sig = self._export_signature()
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        if reload_poll_secs and reload_poll_secs > 0:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, args=(float(reload_poll_secs),),
                daemon=True, name="serve-version-watch")
            self._watch_thread.start()
        # TCP frontend (the wire endpoint): a single-thread reactor serving
        # every connection — see serving/frontend.py.  Default
        # listen_host="" binds ALL interfaces — remote callers are the
        # point, and every connection must pass the HMAC handshake on the
        # cluster authkey; pass listen_host="127.0.0.1" to confine it.
        self._frontend: ReactorFrontend | None = None
        self._endpoint: tuple[str, int] | None = None
        if listen:
            listener = bound_socket(listen_host)
            port = listener.getsockname()[1]
            self._endpoint = (listen_host or local_ip() or "127.0.0.1", port)
            self._frontend = ReactorFrontend(
                listener, self._authkey, self._batcher,
                default_timeout=self.default_timeout,
                handshake_timeout=handshake_timeout,
                max_conn_outstanding=max_conn_outstanding)
        logger.info("serving gateway up: %d replica(s), max_batch=%d, "
                    "max_delay=%.1fms, queue=%d%s",
                    len(cluster._feed_ids), self.max_batch, delay_ms,
                    self.queue_limit,
                    f", endpoint={self._endpoint}" if self._endpoint else "")

    # -- request API ---------------------------------------------------------

    @property
    def endpoint(self) -> tuple[str, int] | None:
        """(host, port) of the TCP frontend (None when ``listen=False``)."""
        return self._endpoint

    def predict(self, rows: Sequence[Any], timeout: float | None = None,
                tenant: str | None = None) -> list:
        """Score ``rows``; returns one result per row, in order.

        Raises :class:`ServeQueueFull` when admission control rejects the
        request (queue full — the 503), :class:`ServeThrottled` when the
        request's *tenant* is over its rate limit or brownout share (the
        429 — other tenants are still being served), :class:`ServeTimeout`
        when the deadline (``timeout``, default ``TOS_SERVE_TIMEOUT``)
        expires first, and :class:`ServeClosed` after shutdown.
        """
        return self.predict_async(rows, timeout, tenant).result()

    def predict_async(self, rows: Sequence[Any],
                      timeout: float | None = None,
                      tenant: str | None = None) -> PendingPrediction:
        """Admit one request and return immediately; ``result()`` blocks.
        ``tenant`` scopes fairness (queues, rate limits, brownout shares);
        omitted means the anonymous tenant."""
        deadline = _monotonic() + (timeout if timeout is not None
                                   else self.default_timeout)
        return PendingPrediction(
            self._batcher,
            self._batcher.submit(rows, deadline, tenant or ""))

    def healthy_replicas(self) -> list[int]:
        return self._router.healthy_replicas()

    def replica_loads(self) -> dict[int, int]:
        """Per-replica outstanding batches (the router's routing signal) —
        what autoscaling victim selection reads."""
        return self._router.replica_loads()

    def shed_level(self) -> int:
        """Current brownout rung (0 = normal; see ``TOS_SERVE_SHED_LADDER``)."""
        return self._batcher.shed_level()

    def tenant_depths(self) -> dict[str, int]:
        """Queued requests per tenant (nonzero only)."""
        return self._batcher.tenant_depths()

    # -- elastic membership (driven by cluster.resize) -----------------------

    def add_replica(self, executor_id: int) -> bool:
        """Admit a freshly-registered serving node into this gateway's
        routing (scale-out)."""
        return self._router.add_replica(executor_id)

    def retire_replica(self, executor_id: int, timeout: float = 60.0) -> bool:
        """Drain one replica out of this gateway's routing (scale-in): stop
        routing to it, let its in-flight batches finish (re-routing them to
        survivors on timeout or death), then drop it."""
        return self._router.retire_replica(executor_id, timeout)

    # -- hot reload ----------------------------------------------------------

    def reload(self) -> dict[int, Any]:
        """Swap every replica onto the bundle currently in ``export_dir``:
        pause dispatch, drain in-flight batches, round-trip the reload
        control item through each replica, resume.  Returns per-replica
        acks.  Called automatically by the version watcher; safe to call
        by hand after an in-place re-export.  Refused while a staged
        rollout is in flight — a fleet-wide swap would clobber the canary
        cohort's candidate bundle under the governor."""
        with self._reload_lock:
            if self._rollout is not None and self._rollout.active():
                raise RuntimeError(
                    "a staged rollout is in flight; wait for it to resolve "
                    "(or roll it back) before a fleet-wide reload")
            self._reloading = True
            try:
                self._router.drain()
                ctl = {CTL_KEY: "reload", "export_dir": self.export_dir}
                acks = self._router.broadcast_ctl(ctl)
                self._quarantine_laggards(
                    acks, bundle_signature(self.export_dir), ctl)
                self._refresh_embed_plan()
                telemetry.counter("serve.reloads_total").inc()
                ttrace.event("reload", export_dir=self.export_dir,
                             replicas=sorted(acks))
                logger.info("serving bundle reloaded on replicas %s",
                            sorted(acks))
                return acks
            finally:
                self._reloading = False

    def _quarantine_laggards(self, acks: dict[int, Any], want: tuple,
                             ctl: dict) -> list[int]:
        """The mixed-fleet guard: every replica whose reload ack does not
        carry the expected bundle signature is fenced out of routing with
        the ctl pinned for recovery replay (``quarantine_for_reload``), so
        a half-applied swap can never keep silently serving the stale
        bundle next to the converged fleet.  (Replicas that failed the
        round outright were already fenced + pinned by the broadcast.)"""
        laggards = [eid for eid, ack in acks.items()
                    if not (isinstance(ack, dict)
                            and tuple(ack.get("signature") or ()) == want)]
        for eid in laggards:
            logger.warning("serving replica %d acked the reload with the "
                           "wrong bundle signature; quarantined until "
                           "recovery converges it", eid)
            self._router.quarantine_for_reload(eid, ctl)
        return laggards

    def _refresh_embed_plan(self) -> None:
        """Read the active export's bundle config and (re)arm the router's
        sharded-embedding fan-out when it carries a ``sharded_embedding``
        block — called at construction and after every fleet-wide reload
        (a newer export may have moved the table's final step or
        geometry).  Never raises: a malformed block degrades to plain
        dense routing with a warning."""
        import json
        import os

        from tensorflowonspark_tpu.utils.paths import resolve_uri

        try:
            with open(os.path.join(resolve_uri(self.export_dir),
                                   "bundle.json")) as f:
                config = json.load(f)
            block = config.get("sharded_embedding")
            if block:
                from tensorflowonspark_tpu.embedding.serve import make_id_fn

                self._router.set_embed_plan(block, make_id_fn(config))
        except Exception:  # noqa: BLE001 - degrade to dense routing
            logger.warning("could not arm sharded-embedding routing from "
                           "%s", self.export_dir, exc_info=True)

    def _export_signature(self) -> tuple:
        """Change signature of the active export (see
        ``checkpoint.bundle_signature``): a changed tuple is a complete
        newer export, thanks to the atomic-rename commit."""
        return bundle_signature(self.export_dir)

    def _watch_loop(self, poll: float) -> None:
        while not self._watch_stop.wait(poll):
            try:
                cur = self._export_signature()
            except Exception:  # noqa: BLE001 - transient fs hiccup
                logger.debug("export version check failed", exc_info=True)
                continue
            if cur and cur != self._export_sig:
                logger.info("newer export detected in %s; hot-reloading",
                            self.export_dir)
                try:
                    self.reload()
                except Exception:  # noqa: BLE001 - keep serving the old bundle
                    # signature NOT advanced: the next poll retries the swap
                    # instead of pinning the stale bundle forever
                    logger.warning("hot reload failed; still serving the "
                                   "previous bundle (will retry)",
                                   exc_info=True)
                else:
                    # under the reload lock: a promotion updates the active
                    # signature too, and the two must not interleave
                    with self._reload_lock:
                        self._export_sig = cur

    # -- staged rollouts (shadow/canary + governed promote/rollback) ---------

    def rollout(self, export_dir: str, *, canary_pct: int | None = None,
                shadow: bool | int = True,
                window_secs: float | None = None,
                auto_promote: bool = True,
                **governor_kwargs) -> RolloutGovernor:
        """Stage the bundle at ``export_dir`` as a rollout CANDIDATE
        instead of swapping the fleet onto it.

        Mechanics: pause + drain, load the candidate on a canary cohort
        (``canary_pct`` percent of the healthy replicas, at least one,
        never all) via a targeted signature-verified control round, then
        resume with split routing — every ``100/canary_pct``-th batch
        rides the canary, and with ``shadow`` enabled primary batches are
        mirrored onto it (every Nth when ``shadow`` is an int) so the
        governor can diff candidate outputs against primary answers that
        were already served.  The returned :class:`RolloutGovernor` then
        watches the canary for ``window_secs`` (default
        ``TOS_SERVE_ROLLOUT_WINDOW_SECS``) and promotes fleet-wide or
        auto-rolls the canaries back; ``.wait()`` blocks for the outcome,
        ``.status()`` is the live picture.  The in-flight state is
        journaled in the coordinator's rollout registry.
        """
        if self._closed:
            raise ServeClosed("serving gateway is closed")
        pct = (int(canary_pct) if canary_pct is not None
               else env_int("TOS_SERVE_CANARY_PCT", 25))
        if not 0 < pct <= 100:
            raise ValueError("canary_pct must be in (0, 100]")
        want = bundle_signature(export_dir)
        if not want:
            raise ValueError(f"no exported bundle found at {export_dir!r}")
        ctl = {CTL_KEY: "reload", "export_dir": export_dir,
               "candidate": True}
        with self._reload_lock:
            if self._rollout is not None and self._rollout.active():
                raise RuntimeError("a staged rollout is already in flight")
            healthy = self._router.healthy_replicas()
            if len(healthy) < 2:
                raise RuntimeError(
                    "staged rollout needs >= 2 healthy replicas (one must "
                    "keep serving primary traffic); use reload() on a "
                    "single-replica fleet")
            # deterministic cohort: lowest executor ids — stable across
            # retries and reconstructable from the journaled state
            n = max(1, min(len(healthy) - 1,
                           math.ceil(len(healthy) * pct / 100)))
            canary = healthy[:n]
            self._reloading = True
            try:
                self._router.drain()
                acks = self._router.ctl_to(canary, ctl)
                laggards = set(self._quarantine_laggards(acks, want, ctl))
                cohort = [eid for eid in canary
                          if eid in acks and eid not in laggards]
                if not cohort:
                    raise RuntimeError(
                        f"no canary replica loaded the candidate bundle "
                        f"from {export_dir!r}; rollout aborted "
                        f"(fleet unchanged)")
                mirror_every = (0 if not shadow
                                else 1 if shadow is True else max(1, int(shadow)))
                state = RolloutState(candidate=export_dir,
                                     prior=self.export_dir, canary=cohort,
                                     pct=pct, shadow=bool(shadow))
                governor = RolloutGovernor(
                    self, state, window_secs=window_secs,
                    auto_promote=auto_promote, **governor_kwargs)
                self._router.set_rollout(
                    cohort,
                    traffic_every=max(1, round(100 / pct)),
                    mirror_every=mirror_every,
                    observer=governor.observe,
                    canary_ctl=ctl,
                    shed_fn=self._batcher.shed_level)
                self._rollout = governor
            finally:
                self._reloading = False
        telemetry.counter("serve.rollouts_total").inc()
        ttrace.event("rollout_started", candidate=export_dir,
                     canary=cohort, pct=pct, shadow=bool(shadow))
        logger.info("staged rollout of %s: canary cohort %s (%d%% traffic"
                    "%s)", export_dir, cohort, pct,
                    ", shadow mirroring" if mirror_every else "")
        self._note_rollout(state.payload())
        governor.start()
        return governor

    def rollout_status(self) -> dict | None:
        """The current (or last) rollout's live status dict, or None if
        this gateway never staged one."""
        gov = self._rollout
        return None if gov is None else gov.status()

    def _promote_rollout(self, governor: RolloutGovernor) -> None:
        """Governor callback: the canary window stayed clean — swap the
        WHOLE fleet onto the candidate via the verified reload path and
        end the split.  The candidate becomes the gateway's active
        ``export_dir`` (the version watcher now tracks it)."""
        candidate = governor.state.candidate
        want = bundle_signature(candidate)
        with self._reload_lock:
            self._reloading = True
            try:
                self._router.drain()
                # no `candidate` bit: post-promotion this is the active
                # bundle everywhere (bad_model chaos stops firing too)
                ctl = {CTL_KEY: "reload", "export_dir": candidate}
                acks = self._router.broadcast_ctl(ctl)
                self._quarantine_laggards(acks, want, ctl)
                self._router.clear_rollout()
                self.export_dir = candidate
                self._export_sig = want
            finally:
                self._reloading = False
        telemetry.counter("serve.promotions_total").inc()
        ttrace.event("rollout_promoted", candidate=candidate,
                     replicas=sorted(acks))
        logger.info("rollout promoted: fleet now serving %s", candidate)

    def _rollback_rollout(self, governor: RolloutGovernor,
                          reason: str | None) -> None:
        """Governor callback: the canary regressed — reload the canary
        cohort back onto the prior export and end the split.  Primary
        replicas never touched the candidate, so they need nothing."""
        state = governor.state
        ctl = {CTL_KEY: "reload", "export_dir": state.prior}
        with self._reload_lock:
            self._reloading = True
            try:
                self._router.drain()
                acks = self._router.ctl_to(state.canary, ctl)
                self._quarantine_laggards(acks, bundle_signature(state.prior),
                                          ctl)
                self._router.clear_rollout()
            finally:
                self._reloading = False
        telemetry.counter("serve.rollbacks_total").inc()
        ttrace.event("rollout_rolled_back", candidate=state.candidate,
                     reason=reason, replicas=sorted(acks))
        logger.warning("rollout of %s rolled back: %s", state.candidate,
                       reason)

    def _note_rollout(self, payload: dict) -> None:
        """Best-effort journal of the rollout state through the
        coordinator's rollout registry (keyed by this gateway's router
        name) — failover/statz evidence, never allowed to break serving."""
        coord = getattr(self._cluster, "coordinator", None)
        if coord is None or not hasattr(coord, "note_rollout"):
            return
        try:
            coord.note_rollout(self._router._registry_name, payload)
        except Exception:  # noqa: BLE001 - journal publish must not break serving
            logger.debug("rollout journal publish failed", exc_info=True)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop accepting, fail queued requests, tear the layers down.
        Called automatically by ``cluster.shutdown()``."""
        if self._closed:
            return
        self._closed = True
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=10.0)
        if self._rollout is not None:
            # journal the abort before the layers come down: the registry
            # must not read "canary" forever off a gateway that is gone
            with contextlib.suppress(Exception):
                self._rollout.stop()
        # router + batcher first: closing them resolves every request (the
        # last completion producers), so the frontend's reactor — still
        # draining — delivers the final error replies, and stop() can then
        # safely retire the wake pipe with no racing writers left.
        self._router.close()
        self._batcher.close()
        if self._frontend is not None:
            self._frontend.stop()


class _GatewayFuture:
    """Async handle for one pipelined :class:`GatewayClient` request:
    ``result()`` blocks until the id-matched reply arrives and returns the
    results or raises the mapped gateway error."""

    __slots__ = ("_event", "_reply", "_error", "_timeout", "_slack",
                 "_deadline")

    def __init__(self, timeout: float, slack: float = 30.0):
        self._event = threading.Event()
        self._reply: tuple | None = None
        self._error: Exception | None = None
        self._timeout = timeout
        self._slack = slack
        # client-side hang detector: the gateway answers every accepted
        # request by its server-side deadline, so a reply this overdue
        # (TOS_SERVE_CLIENT_SLACK past it) means the connection is dead,
        # not slow
        self._deadline = _monotonic() + timeout + slack

    def done(self) -> bool:
        return self._event.is_set()

    def _resolve(self, reply: tuple) -> None:
        self._reply = reply
        self._event.set()

    def _resolve_error(self, error: Exception) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: float | None = None):
        """The request's results (or raises its error).  ``timeout`` is a
        client-side backstop on top of the server-enforced deadline — the
        gateway answers every accepted request, so this should only fire
        when the server is unreachable (then the receiver poisons the
        client and resolves every future with the connection error)."""
        budget = timeout if timeout is not None else self._timeout + self._slack
        if not self._event.wait(budget):
            raise ServeTimeout(
                f"no gateway reply within the client-side budget ({budget:.1f}s)")
        if self._error is not None:
            raise self._error
        reply = self._reply
        if isinstance(reply, tuple) and reply and reply[0] == "ok":
            return reply[1]
        if isinstance(reply, tuple) and len(reply) >= 3 and reply[0] == "err":
            raise _ERR_TYPES.get(reply[1], RuntimeError)(reply[2])
        raise RuntimeError(f"malformed gateway reply: {reply!r}")


class GatewayClient:
    """Pipelined remote caller for a gateway's TCP endpoint.

    Same wire stack as the data plane — HMAC challenge-response on the
    cluster authkey, then v2 (protocol-5, zero-copy) frames — but
    *multiplexed*: every request carries a client-assigned id, many
    requests stay outstanding on the one socket (``predict_async``), and a
    receiver thread resolves futures as id-tagged replies arrive, in
    whatever order the gateway finishes them.  ``predict`` is the
    closed-loop convenience (``predict_async(...).result()``).

    ``max_outstanding`` (0 = unbounded) caps the client-side pipeline
    depth with a semaphore — the gateway additionally enforces its own
    per-connection cap (``TOS_SERVE_CONN_OUTSTANDING``) with fast-fail
    ``ServeQueueFull`` replies.
    """

    def __init__(self, host: str, port: int, authkey: bytes, *,
                 connect_timeout: float = 30.0, call_timeout: float = 120.0,
                 max_outstanding: int = 0, tenant: str | None = None):
        self._sock = connect_with_backoff((host, port),
                                          timeout=connect_timeout)
        self._sock.settimeout(call_timeout)
        if not hmac_handshake_client(self._sock, authkey):
            self._sock.close()
            raise RuntimeError("gateway auth handshake failed")
        self._call_timeout = call_timeout
        # fairness identity: rides every predict frame as a trailing field
        # (absent for the default "" — byte-identical to the pre-tenant
        # wire, which is what keeps id-less/legacy clients compatible;
        # they all land in the anonymous tenant)
        self._tenant = str(tenant) if tenant else ""
        # reply-reaper backstop past the server-enforced deadline: how much
        # grace an overdue reply gets before the connection is presumed dead
        self._slack = env_float("TOS_SERVE_CLIENT_SLACK", 30.0)
        # frame-write serializer: interleaved sendmsg from two threads would
        # interleave frame bytes (same deliberate hold-lock-across-I/O
        # pattern as DataClient._call; baselined in analysis/baseline.json)
        self._send_lock = tos_named_lock("gateway.client._send_lock")
        self._lock = tos_named_lock("gateway.client._lock")  # id counter + pending map + closed
        self._pending: dict[int, _GatewayFuture] = {}
        self._next_id = 1
        self._closed = False
        self._sem = (threading.Semaphore(max_outstanding)
                     if max_outstanding > 0 else None)
        self._rx = threading.Thread(target=self._recv_loop, daemon=True,
                                    name="gateway-client-rx")
        self._rx.start()

    # -- wire ----------------------------------------------------------------

    def _start(self, msg: tuple, timeout: float,
               tail: tuple = ()) -> _GatewayFuture:
        """Register a future under a fresh id and send ``msg + (id,) +
        tail`` (``tail`` carries optional post-id fields like the tenant
        key — old gateways ignore trailing fields they don't know)."""
        if self._sem is not None:
            self._sem.acquire()
        with self._lock:
            if self._closed:
                if self._sem is not None:
                    self._sem.release()
                raise ServeClosed("gateway client is closed")
            rid = self._next_id
            self._next_id += 1
            fut = _GatewayFuture(timeout, self._slack)
            self._pending[rid] = fut
        try:
            with self._send_lock:
                _send(self._sock, (*msg, rid, *tail), wire=2)
        except (TimeoutError, OSError) as e:
            self._poison(e)
            raise
        return fut

    def _recv_loop(self) -> None:
        import select as _select

        try:
            while True:
                # Wait for readability OUTSIDE the frame reader: a timeout
                # here consumes no stream bytes, so an idle (or
                # about-to-reply) connection is never poisoned by quiet
                # time — only a genuinely overdue pending request is.
                # Once bytes are ready, _recv runs with call_timeout armed
                # on the socket: a stall MID-frame at that scale really is
                # a dead peer.
                while True:
                    ready, _, _ = _select.select([self._sock], [], [], 1.0)
                    if ready:
                        break
                    self._check_overdue()
                reply = _recv(self._sock)
                if not isinstance(reply, tuple) or not reply:
                    continue
                if reply[0] == "okm":
                    # multi-reply frame: one batch scatter's worth of
                    # (rid, "ok"/"err", ...) entries coalesced by the
                    # reactor; entry[1:] is the single-reply tuple shape
                    for entry in reply[1]:
                        self._resolve_one(entry[0], tuple(entry[1:]))
                    continue
                rid = (reply[-1] if len(reply) >= 2
                       and isinstance(reply[-1], int) else None)
                if rid is None:
                    continue  # close ack / unsolicited frame
                self._resolve_one(rid, reply[:-1])
        except (ConnectionError, OSError, EOFError, ValueError) as e:
            # ValueError: select() on a socket another thread just closed
            self._poison(e)

    def _check_overdue(self) -> None:
        now = _monotonic()
        with self._lock:
            if self._closed:
                raise ConnectionError("gateway client closed")
            overdue = any(f._deadline <= now for f in self._pending.values())
        if overdue:
            raise ConnectionError(
                "no gateway reply well past the request deadline; "
                "connection presumed dead")

    def _resolve_one(self, rid, payload: tuple) -> None:
        with self._lock:
            fut = self._pending.pop(rid, None)
        if fut is not None:
            if self._sem is not None:
                self._sem.release()
            fut._resolve(payload)

    def _poison(self, error: Exception) -> None:
        """Terminal: fail every pending future and close the socket.  A
        stream that errored may hold partial frames — there is no way to
        resync, so the client is done (mirror of DataClient._call)."""
        with self._lock:
            was_closed, self._closed = self._closed, True
            pending, self._pending = self._pending, {}
        with contextlib.suppress(OSError):
            self._sock.close()
        err = (ServeClosed("gateway client closed") if was_closed
               else ConnectionError(f"gateway connection lost: {error}"))
        for fut in pending.values():
            if self._sem is not None:
                self._sem.release()
            fut._resolve_error(err)

    # -- API -----------------------------------------------------------------

    def predict_async(self, rows: Sequence[Any],
                      timeout: float | None = None,
                      tenant: str | None = None) -> _GatewayFuture:
        """Send one predict request; returns a future resolved by reply id.
        Many may be outstanding — that is the point.  ``tenant`` overrides
        the client's default fairness identity for this request."""
        t = float(timeout) if timeout is not None else self._call_timeout
        ten = self._tenant if tenant is None else str(tenant)
        return self._start(("predict", list(rows), timeout), t,
                           (ten,) if ten else ())

    def predict(self, rows: Sequence[Any], timeout: float | None = None,
                tenant: str | None = None) -> list:
        """Round-trip one predict request; mirrors ``ServingGateway.predict``
        including its error types (``ServeThrottled`` = this tenant is over
        its rate limit / brownout share)."""
        return self.predict_async(rows, timeout, tenant).result()

    def outstanding(self) -> int:
        """Requests currently awaiting replies (the pool's load signal)."""
        with self._lock:
            return len(self._pending)

    def ping(self, timeout: float = 10.0) -> bool:
        try:
            return self._start(("ping",), timeout).result(timeout) == "pong"
        except (ConnectionError, OSError, ServeTimeout):
            return False

    def close(self) -> None:
        """Best-effort close op, then poison: outstanding futures resolve
        with ``ServeClosed``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            with self._send_lock:
                _send(self._sock, ("close",), wire=2)
        except OSError:  # toslint: allow-silent(best-effort teardown; the poison below closes the socket regardless)
            pass
        self._poison(ServeClosed("client closed"))
        self._rx.join(timeout=5.0)


class GatewayClientPool:
    """A fixed pool of pipelined :class:`GatewayClient` connections.

    Closed-loop callers (one request in flight per caller thread) cannot
    exploit pipelining on their own; the pool gives a fleet of them
    connection reuse + multiplexing for free: each call goes to the pooled
    connection with the fewest outstanding requests, so T caller threads
    share ``size`` sockets instead of opening T.  All methods are
    thread-safe; every client maps its own futures by id, so interleaving
    is free of head-of-line blocking at the protocol level.
    """

    def __init__(self, host: str, port: int, authkey: bytes, *,
                 size: int = 4, **client_kwargs):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        self._clients = [GatewayClient(host, port, authkey, **client_kwargs)
                         for _ in range(size)]

    def _pick(self) -> GatewayClient:
        return min(self._clients, key=lambda c: c.outstanding())

    def predict_async(self, rows: Sequence[Any],
                      timeout: float | None = None,
                      tenant: str | None = None) -> _GatewayFuture:
        return self._pick().predict_async(rows, timeout, tenant)

    def predict(self, rows: Sequence[Any], timeout: float | None = None,
                tenant: str | None = None) -> list:
        return self.predict_async(rows, timeout, tenant).result()

    def ping(self) -> bool:
        return all(c.ping() for c in self._clients)

    def close(self) -> None:
        for c in self._clients:
            with contextlib.suppress(Exception):
                c.close()


class LegacyGatewayClient:
    """The pre-reactor one-request-per-round-trip caller: id-less predict
    frames, blocking request/reply on one socket.  Kept as the wire-
    compatibility reference — the reactor must answer these clients
    forever (depth 1, id-less replies) — and for minimal embedded callers
    that want no background thread."""

    def __init__(self, host: str, port: int, authkey: bytes, *,
                 connect_timeout: float = 30.0, call_timeout: float = 120.0):
        self._sock = connect_with_backoff((host, port),
                                          timeout=connect_timeout)
        self._sock.settimeout(call_timeout)
        if not hmac_handshake_client(self._sock, authkey):
            self._sock.close()
            raise RuntimeError("gateway auth handshake failed")
        # request/reply serializer (same deliberate hold-lock-across-I/O
        # pattern as DataClient._call; baselined in analysis/baseline.json)
        self._lock = tos_named_lock("gateway.legacy._lock")

    def _call(self, msg: tuple):
        with self._lock:
            try:
                _send(self._sock, msg, wire=2)
                return _recv(self._sock)
            except (TimeoutError, OSError):
                # the stream may hold a partial frame or a late reply; a
                # retry on it would read the PREVIOUS request's answer as
                # its own — poison the socket (mirror of DataClient._call)
                with contextlib.suppress(OSError):
                    self._sock.close()
                raise

    def predict(self, rows: Sequence[Any], timeout: float | None = None) -> list:
        reply = self._call(("predict", list(rows), timeout))
        if isinstance(reply, tuple) and reply and reply[0] == "ok":
            return reply[1]
        if isinstance(reply, tuple) and len(reply) >= 3 and reply[0] == "err":
            raise _ERR_TYPES.get(reply[1], RuntimeError)(reply[2])
        raise RuntimeError(f"malformed gateway reply: {reply!r}")

    def ping(self) -> bool:
        reply = self._call(("ping",))
        return bool(isinstance(reply, tuple) and reply and reply[0] == "ok")

    def close(self) -> None:
        try:
            with self._lock:
                _send(self._sock, ("close",), wire=2)
                try:
                    _recv(self._sock)
                except (ConnectionError, OSError, EOFError):  # toslint: allow-silent(best-effort close ack; the gateway may already be gone)
                    pass
        except OSError:  # toslint: allow-silent(best-effort teardown; socket close below is what matters)
            pass
        finally:
            self._sock.close()
