"""Driver-side online serving gateway: request/response over a live cluster.

The reference stack only ever scored data as Spark partitions — a batch
path (PAPER.md §3.3).  This gateway adds the missing request/response
path: ``cluster.serve(export_dir)`` returns a handle whose ``predict`` /
``predict_async`` answer individual requests with micro-batched, replica-
routed inference over the SAME resident nodes, data plane, telemetry, and
elastic machinery the batch path uses.

Three layers, composed here:

- admission + coalescing: :class:`~.batcher.MicroBatcher` (bounded queue
  ``TOS_SERVE_QUEUE``, fast-fail rejection, per-request deadlines
  ``TOS_SERVE_TIMEOUT``, flush at ``TOS_SERVE_MAX_BATCH`` rows or
  ``TOS_SERVE_MAX_DELAY_MS``);
- routing + failover: :class:`~.router.ReplicaRouter` (least-outstanding
  replica choice, one retry on a live replica after a death, incarnation-
  fenced recovery);
- the wire endpoint: a threaded TCP frontend speaking the data plane's
  own framing — HMAC handshake on the cluster authkey, then protocol-5
  zero-copy v2 frames (numpy rows/results travel as out-of-band buffers).
  :class:`GatewayClient` is the matching remote caller.

Hot reload: a version watcher polls ``export_dir``; when a newer export
lands, in-flight batches drain, every replica swaps its bundle via a
control round (``serving_loop`` + ``checkpoint.invalidate_bundle``), and
dispatch resumes — requests keep queuing during the swap.
"""

from __future__ import annotations

import contextlib
import logging
import os
import socket
import threading
from time import monotonic as _monotonic
from typing import Any, Sequence

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.dataserver import _recv, _send
from tensorflowonspark_tpu.serving.batcher import (  # noqa: F401 - CTL_KEY re-exported
    CTL_KEY,
    MicroBatcher,
    PendingPrediction,
    ServeClosed,
    ServeQueueFull,
    ServeTimeout,
)
from tensorflowonspark_tpu.serving.router import ReplicaRouter
from tensorflowonspark_tpu.utils.envtune import env_float, env_int
from tensorflowonspark_tpu.utils.net import (
    bound_socket,
    connect_with_backoff,
    hmac_handshake_client,
    hmac_handshake_server,
    local_ip,
    set_nodelay,
)
from tensorflowonspark_tpu.utils.paths import resolve_uri

logger = logging.getLogger(__name__)

_ERR_TYPES = {"unavailable": ServeQueueFull, "deadline": ServeTimeout,
              "closed": ServeClosed}


class ServingGateway:
    """Handle returned by ``cluster.serve(export_dir, ...)``.

    ``predict(rows, timeout)`` blocks for one request; ``predict_async``
    returns a :class:`~.batcher.PendingPrediction`.  ``endpoint`` is the
    TCP frontend's ``(host, port)`` for :class:`GatewayClient` callers.
    """

    def __init__(self, cluster, export_dir: str, *,
                 qname_in: str = "input", qname_out: str = "output",
                 max_batch: int | None = None,
                 max_delay_ms: float | None = None,
                 queue_limit: int | None = None,
                 default_timeout: float | None = None,
                 listen: bool = True, listen_host: str = "",
                 reload_poll_secs: float = 2.0):
        self.export_dir = export_dir
        self.max_batch = (int(max_batch) if max_batch is not None
                          else env_int("TOS_SERVE_MAX_BATCH", 64))
        delay_ms = (float(max_delay_ms) if max_delay_ms is not None
                    else env_float("TOS_SERVE_MAX_DELAY_MS", 5.0))
        self.queue_limit = (int(queue_limit) if queue_limit is not None
                            else env_int("TOS_SERVE_QUEUE", 256))
        self.default_timeout = (float(default_timeout)
                                if default_timeout is not None
                                else env_float("TOS_SERVE_TIMEOUT", 30.0))
        if self.max_batch < 1 or self.queue_limit < 1:
            raise ValueError("max_batch and queue_limit must be >= 1")
        if delay_ms < 0 or self.default_timeout <= 0:
            raise ValueError("max_delay_ms must be >= 0 and default_timeout "
                             "> 0")
        self._authkey = cluster.authkey
        self._closed = False
        self._reloading = False
        self._reload_lock = threading.Lock()
        self._router = ReplicaRouter(cluster, None,  # batcher set just below
                                     qname_in=qname_in, qname_out=qname_out,
                                     request_timeout=self.default_timeout)
        self._batcher = MicroBatcher(
            self._router.submit, max_batch=self.max_batch,
            max_delay_secs=delay_ms / 1e3, queue_limit=self.queue_limit,
            pause_fn=lambda: self._reloading,
            capacity_fn=self._router.has_capacity)
        self._router._batcher = self._batcher
        # version watch: swap in a newer export, draining in-flight first
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        if reload_poll_secs and reload_poll_secs > 0:
            self._export_sig = self._export_signature()
            self._watch_thread = threading.Thread(
                target=self._watch_loop, args=(float(reload_poll_secs),),
                daemon=True, name="serve-version-watch")
            self._watch_thread.start()
        # TCP frontend (the wire endpoint).  Default listen_host="" binds
        # ALL interfaces — remote callers are the point, and every
        # connection must pass the HMAC handshake on the cluster authkey;
        # pass listen_host="127.0.0.1" to confine it to loopback.
        self._listener: socket.socket | None = None
        self._endpoint: tuple[str, int] | None = None
        if listen:
            self._listener = bound_socket(listen_host)
            port = self._listener.getsockname()[1]
            self._endpoint = (listen_host or local_ip() or "127.0.0.1", port)
            threading.Thread(target=self._accept_loop, daemon=True,
                             name="serve-frontend").start()
        logger.info("serving gateway up: %d replica(s), max_batch=%d, "
                    "max_delay=%.1fms, queue=%d%s",
                    len(cluster._feed_ids), self.max_batch, delay_ms,
                    self.queue_limit,
                    f", endpoint={self._endpoint}" if self._endpoint else "")

    # -- request API ---------------------------------------------------------

    @property
    def endpoint(self) -> tuple[str, int] | None:
        """(host, port) of the TCP frontend (None when ``listen=False``)."""
        return self._endpoint

    def predict(self, rows: Sequence[Any], timeout: float | None = None) -> list:
        """Score ``rows``; returns one result per row, in order.

        Raises :class:`ServeQueueFull` when admission control rejects the
        request (queue full — the 503), :class:`ServeTimeout` when the
        deadline (``timeout``, default ``TOS_SERVE_TIMEOUT``) expires first,
        and :class:`ServeClosed` after shutdown.
        """
        return self.predict_async(rows, timeout).result()

    def predict_async(self, rows: Sequence[Any],
                      timeout: float | None = None) -> PendingPrediction:
        """Admit one request and return immediately; ``result()`` blocks."""
        deadline = _monotonic() + (timeout if timeout is not None
                                   else self.default_timeout)
        return PendingPrediction(self._batcher,
                                 self._batcher.submit(rows, deadline))

    def healthy_replicas(self) -> list[int]:
        return self._router.healthy_replicas()

    # -- hot reload ----------------------------------------------------------

    def reload(self) -> dict[int, Any]:
        """Swap every replica onto the bundle currently in ``export_dir``:
        pause dispatch, drain in-flight batches, round-trip the reload
        control item through each replica, resume.  Returns per-replica
        acks.  Called automatically by the version watcher; safe to call
        by hand after an in-place re-export."""
        with self._reload_lock:
            self._reloading = True
            try:
                self._router.drain()
                acks = self._router.broadcast_ctl(
                    {CTL_KEY: "reload", "export_dir": self.export_dir})
                telemetry.counter("serve.reloads_total").inc()
                logger.info("serving bundle reloaded on replicas %s",
                            sorted(acks))
                return acks
            finally:
                self._reloading = False

    def _export_signature(self) -> tuple:
        """Cheap change signature of the export: (name, mtime_ns, size) of
        the bundle files.  ``export_bundle`` commits params.npz by atomic
        rename, so a changed signature is a complete newer export."""
        local = resolve_uri(self.export_dir)
        sig = []
        for name in ("bundle.json", "params.npz", "params"):
            try:
                st = os.stat(os.path.join(local, name))
            except OSError:
                continue
            sig.append((name, st.st_mtime_ns, st.st_size))
        return tuple(sig)

    def _watch_loop(self, poll: float) -> None:
        while not self._watch_stop.wait(poll):
            try:
                cur = self._export_signature()
            except Exception:  # noqa: BLE001 - transient fs hiccup
                logger.debug("export version check failed", exc_info=True)
                continue
            if cur and cur != self._export_sig:
                logger.info("newer export detected in %s; hot-reloading",
                            self.export_dir)
                try:
                    self.reload()
                except Exception:  # noqa: BLE001 - keep serving the old bundle
                    # signature NOT advanced: the next poll retries the swap
                    # instead of pinning the stale bundle forever
                    logger.warning("hot reload failed; still serving the "
                                   "previous bundle (will retry)",
                                   exc_info=True)
                else:
                    self._export_sig = cur

    # -- TCP frontend --------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            set_nodelay(conn)  # small request/reply frames: Nagle adds ~40ms
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="serve-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if not hmac_handshake_server(conn, self._authkey):
                logger.warning("rejected gateway connection: bad authkey")
                return
            while True:
                msg = _recv(conn)
                reply = self._handle(msg)
                _send(conn, reply, wire=2)
                if msg[0] == "close":
                    return
        except (ConnectionError, OSError, EOFError):
            return
        finally:
            conn.close()

    def _handle(self, msg: tuple) -> tuple:
        op = msg[0]
        if op == "predict":
            rows, timeout = msg[1], (msg[2] if len(msg) > 2 else None)
            try:
                return ("ok", self.predict(list(rows), timeout))
            except ServeQueueFull as e:
                return ("err", "unavailable", str(e))
            except ServeTimeout as e:
                return ("err", "deadline", str(e))
            except ServeClosed as e:
                return ("err", "closed", str(e))
            except Exception as e:  # noqa: BLE001 - surface to the caller
                logger.exception("gateway predict failed")
                return ("err", "internal", f"{type(e).__name__}: {e}")
        if op == "ping":
            return ("ok", "pong")
        if op == "close":
            return ("ok",)
        return ("err", "internal", f"unknown op {op!r}")

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop accepting, fail queued requests, tear the layers down.
        Called automatically by ``cluster.shutdown()``."""
        if self._closed:
            return
        self._closed = True
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=10.0)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:  # toslint: allow-silent(closing the listener is what stops the accept loop; a racing second close is fine)
                pass
        self._router.close()
        self._batcher.close()


class GatewayClient:
    """Remote caller for a gateway's TCP endpoint.

    Same wire stack as the data plane: HMAC challenge-response on the
    cluster authkey, then v2 (protocol-5, zero-copy) frames.  One
    request/reply in flight per connection — open one client per
    closed-loop caller (the bench does), or several for pipelining.
    """

    def __init__(self, host: str, port: int, authkey: bytes, *,
                 connect_timeout: float = 30.0, call_timeout: float = 120.0):
        self._sock = connect_with_backoff((host, port),
                                          timeout=connect_timeout)
        self._sock.settimeout(call_timeout)
        if not hmac_handshake_client(self._sock, authkey):
            self._sock.close()
            raise RuntimeError("gateway auth handshake failed")
        # request/reply serializer (same deliberate hold-lock-across-I/O
        # pattern as DataClient._call; baselined in analysis/baseline.json)
        self._lock = threading.Lock()

    def _call(self, msg: tuple):
        with self._lock:
            try:
                _send(self._sock, msg, wire=2)
                return _recv(self._sock)
            except (TimeoutError, OSError):
                # the stream may hold a partial frame or a late reply; a
                # retry on it would read the PREVIOUS request's answer as
                # its own — poison the socket (mirror of DataClient._call)
                with contextlib.suppress(OSError):
                    self._sock.close()
                raise

    def predict(self, rows: Sequence[Any], timeout: float | None = None) -> list:
        """Round-trip one predict request; mirrors ``ServingGateway.predict``
        including its error types."""
        reply = self._call(("predict", list(rows), timeout))
        if isinstance(reply, tuple) and reply and reply[0] == "ok":
            return reply[1]
        if isinstance(reply, tuple) and len(reply) >= 3 and reply[0] == "err":
            raise _ERR_TYPES.get(reply[1], RuntimeError)(reply[2])
        raise RuntimeError(f"malformed gateway reply: {reply!r}")

    def ping(self) -> bool:
        reply = self._call(("ping",))
        return bool(isinstance(reply, tuple) and reply and reply[0] == "ok")

    def close(self) -> None:
        try:
            with self._lock:
                _send(self._sock, ("close",), wire=2)
                try:
                    _recv(self._sock)
                except (ConnectionError, OSError, EOFError):  # toslint: allow-silent(best-effort close ack; the gateway may already be gone)
                    pass
        except OSError:  # toslint: allow-silent(best-effort teardown; socket close below is what matters)
            pass
        finally:
            self._sock.close()
