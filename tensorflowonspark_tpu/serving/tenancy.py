"""Per-tenant fairness for the serving gateway's admission queue.

Before this module, admission was one global FIFO with one failure mode:
``ServeQueueFull`` when the queue hit its bound.  One hot client could fill
the whole queue and every other caller's p99 rode its backlog — the
isolation gap the TensorFlow-Serving lineage calls out for multi-tenant
model servers.  This module replaces the single deque inside
:class:`~.batcher.MicroBatcher` with three mechanisms, all scoped by an
optional per-request *tenant key* (anonymous ``""`` for legacy callers):

- **weighted per-tenant queues with deficit-round-robin drain**: each
  tenant gets its own FIFO; batch building pulls rows tenant-by-tenant
  with a row-granularity DRR (each turn grants ``quantum × weight`` rows
  of deficit, an emptied queue forfeits its deficit), so a tenant with a
  deep backlog cannot monopolize batch fill — everyone else's head-of-line
  requests keep landing in the next batch;
- **per-tenant token-bucket rate limits** (``TOS_SERVE_TENANT_RATE`` rows/
  second per unit weight, one second of burst): a tenant over its budget
  gets its own 429-equivalent (:class:`ServeThrottled`, wire kind
  ``throttled``) at the door, before it can occupy an admission slot;
- **a brownout ladder** (``TOS_SERVE_SHED_LADDER``, occupancy fractions of
  the queue bound): overload sheds in stages instead of one cliff — level
  1 pauses shadow-mirror traffic (the rollout layer polls
  ``shed_level()``), level 2 sheds any tenant past its weight-proportional
  share of the queue (the lowest-weight tenants' overage first, since
  their absolute share is smallest), and only then does the queue-full
  cliff (``ServeQueueFull``) remain for the last rung.

Threading contract: :class:`TenantQueues` is NOT internally locked — it is
owned by the :class:`~.batcher.MicroBatcher` and every method is called
under the batcher's condition lock (the same discipline as the deque it
replaces).  The ``hot_tenant`` chaos hook (``faultinject.tenant_charge_mult``)
rides the admission path so overload tests are deterministic.
"""

from __future__ import annotations

import collections
from time import monotonic as _monotonic

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.utils.envtune import env_float, env_str

#: Rows of DRR deficit granted per unit of tenant weight per rotation turn.
#: Small enough that a max_batch=64 batch interleaves several backlogged
#: tenants; large enough that a single-tenant steady state never pays
#: rotation overhead per row.
_DRR_QUANTUM = 8


class ServeThrottled(RuntimeError):
    """Per-tenant admission rejection (the 429 of this wire protocol):
    THIS tenant is over its token-bucket rate limit or — under brownout —
    past its weight-proportional queue share.  Other tenants' requests are
    still being admitted; retry with backoff or raise the tenant's
    weight/rate."""


class _Tenant:
    __slots__ = ("key", "weight", "queue", "deficit", "tokens", "refilled")

    def __init__(self, key: str, weight: float, burst: float):
        self.key = key
        self.weight = weight
        self.queue: collections.deque = collections.deque()
        self.deficit = 0.0
        # token bucket starts full: a fresh tenant gets its burst
        self.tokens = burst
        self.refilled = _monotonic()


def _parse_ladder(spec: str) -> tuple[float, ...]:
    """Occupancy fractions (ascending) at which shedding escalates; a bad
    spec falls back to the documented default rather than disabling the
    ladder."""
    try:
        rungs = tuple(sorted(float(p) for p in spec.split(",") if p.strip()))
        if rungs and all(0.0 < r <= 1.0 for r in rungs):
            return rungs
    except ValueError:  # toslint: allow-silent(operator typo in the ladder spec; the default ladder below still protects the queue)
        pass
    return (0.5, 0.8)


class TenantQueues:
    """The MicroBatcher's admission queue: per-tenant FIFOs + DRR drain +
    token buckets + the brownout ladder.  Every method runs under the
    owning batcher's lock (see module docstring)."""

    def __init__(self, *, queue_limit: int,
                 weights: dict[str, float] | None = None,
                 rate: float | None = None,
                 ladder: str | None = None):
        self.queue_limit = max(1, int(queue_limit))
        self._weights = {str(k): max(1e-3, float(v))
                         for k, v in (weights or {}).items()}
        self._rate = (float(rate) if rate is not None
                      else env_float("TOS_SERVE_TENANT_RATE", 0.0))
        self._ladder = _parse_ladder(
            ladder if ladder is not None
            else env_str("TOS_SERVE_SHED_LADDER", "0.5,0.8"))
        self._tenants: dict[str, _Tenant] = {}
        # DRR rotation ring over ALL known tenants (rotation skips the
        # empty ones, resetting their deficit — classic DRR forfeiture)
        self._ring: collections.deque[_Tenant] = collections.deque()
        self._current: _Tenant | None = None
        self._n = 0
        self._shed_gauge = telemetry.gauge("serve.shed_level")
        self._shed_gauge.set(0)

    # -- tenant bookkeeping --------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    def _tenant(self, key: str) -> _Tenant:
        t = self._tenants.get(key)
        if t is None:
            w = self.weight(key)
            burst = max(self._rate * w, 1.0) if self._rate > 0 else 0.0
            t = self._tenants[key] = _Tenant(key, w, burst)
            self._ring.append(t)
        return t

    # -- admission (token buckets + brownout) --------------------------------

    def shed_level(self) -> int:
        """Current brownout rung: 0 = normal; 1+ = the highest ladder
        fraction the queue occupancy has crossed.  Level 1 pauses shadow
        mirroring (polled by the rollout layer), level >= 2 sheds tenants
        past their weight-proportional queue share at admission."""
        occ = self._n / self.queue_limit
        level = 0
        for i, frac in enumerate(self._ladder, start=1):
            if occ >= frac:
                level = i
        return level

    def admission_error(self, tenant: str, nrows: int) -> Exception | None:
        """Token-bucket + brownout check for one arriving request; returns
        the rejection to answer with (:class:`ServeThrottled`) or None to
        admit.  Runs BEFORE the request occupies a queue slot, so a
        flooding tenant is refused at the door and never inflates anyone
        else's backlog."""
        from tensorflowonspark_tpu import faultinject

        t = self._tenant(tenant)
        level = self.shed_level()
        self._shed_gauge.set(level)
        if self._rate > 0:
            rate = self._rate * t.weight
            burst = max(rate, 1.0)
            now = _monotonic()
            t.tokens = min(burst, t.tokens + (now - t.refilled) * rate)
            t.refilled = now
            charge = nrows * faultinject.tenant_charge_mult(tenant)
            if charge > t.tokens:
                telemetry.counter("serve.throttled_total").inc()
                return ServeThrottled(
                    f"tenant {tenant or '(anonymous)'!r} over its rate "
                    f"limit ({rate:g} rows/s); retry with backoff")
            t.tokens -= charge
        if level >= 2:
            # brownout level 2: no tenant may hold more than its weight-
            # proportional share of the remaining queue — the lowest-weight
            # tenants' overage sheds first because their share is smallest
            active_w = sum(x.weight for x in self._tenants.values()
                           if x.queue or x is t)
            share = max(1, int(self.queue_limit * t.weight / max(active_w,
                                                                 t.weight)))
            if len(t.queue) >= share:
                telemetry.counter("serve.throttled_total").inc()
                telemetry.counter("serve.shed_total").inc()
                return ServeThrottled(
                    f"gateway under brownout (level {level}): tenant "
                    f"{tenant or '(anonymous)'!r} past its queue share "
                    f"({share} of {self.queue_limit}); retry with backoff")
        return None

    # -- queue surface (what the batcher's deque used to provide) ------------

    def append(self, req) -> None:
        t = self._tenant(req.tenant)
        t.queue.append(req)
        self._n += 1

    def remove(self, req) -> None:
        """Remove a queued request (expiry/cancel); raises ValueError when
        absent — the batcher's existing races catch it, same as deque."""
        t = self._tenants.get(req.tenant)
        if t is None:
            raise ValueError("tenant unknown")
        t.queue.remove(req)  # raises ValueError when already pulled
        self._n -= 1
        if not t.queue:
            t.deficit = 0.0

    def discard(self, req) -> None:
        """Drop an already-resolved request found at batch-build time (its
        slot frees without a batch entry)."""
        t = self._tenants.get(req.tenant)
        if t is not None and t.queue and t.queue[0] is req:
            t.queue.popleft()
            self._n -= 1
            if not t.queue:
                t.deficit = 0.0

    def clear(self) -> None:
        for t in self._tenants.values():
            t.queue.clear()
            t.deficit = 0.0
        self._n = 0
        self._current = None

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        for t in self._tenants.values():
            yield from t.queue

    def oldest_submit(self) -> float | None:
        """Earliest ``t_submit`` across every tenant's head-of-line request
        (per-tenant queues are FIFO, so heads are each tenant's oldest) —
        the batcher's ripeness clock."""
        heads = [t.queue[0].t_submit for t in self._tenants.values()
                 if t.queue]
        return min(heads) if heads else None

    # -- DRR drain (batch building) ------------------------------------------

    def next_for_batch(self):
        """The request batch-building should pull rows from next, DRR
        order, or None when nothing is queued.  The current tenant keeps
        the turn while it has queue AND deficit; otherwise the ring
        rotates, granting each visited nonempty tenant ``quantum × weight``
        more deficit."""
        if not self._n:
            return None
        cur = self._current
        if cur is not None and cur.queue and cur.deficit > 0:
            return cur.queue[0]
        self._current = None
        for _ in range(len(self._ring)):
            t = self._ring[0]
            self._ring.rotate(-1)
            if not t.queue:
                t.deficit = 0.0  # an empty queue forfeits its deficit
                continue
            t.deficit += _DRR_QUANTUM * t.weight
            if t.deficit > 0:
                self._current = t
                return t.queue[0]
        return None

    def charge(self, req, nrows: int) -> None:
        """Account ``nrows`` just pulled from ``req`` against its tenant's
        deficit; pops the request once fully pulled into batches."""
        t = self._tenants.get(req.tenant)
        if t is None:  # pragma: no cover - charge always follows next_for_batch
            return
        t.deficit -= nrows
        if req.offset >= len(req.rows):
            if t.queue and t.queue[0] is req:
                t.queue.popleft()
                self._n -= 1
            if not t.queue:
                t.deficit = 0.0
                if self._current is t:
                    self._current = None

    # -- introspection (stats / tests) ---------------------------------------

    def depths(self) -> dict[str, int]:
        """Queued requests per tenant (nonzero only) — the per-tenant
        stats block's queue picture."""
        return {t.key: len(t.queue) for t in self._tenants.values()
                if t.queue}
