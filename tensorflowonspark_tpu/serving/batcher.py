"""Dynamic micro-batching for the online serving gateway.

The latency half of the serving subsystem (PAPERS.md: tf.data's lesson that
deadline-driven batching turns a throughput engine into a latency one):
individual predict requests are coalesced into device-sized batches —
flushed the moment ``TOS_SERVE_MAX_BATCH`` rows are queued OR the oldest
request has waited ``TOS_SERVE_MAX_DELAY_MS``, whichever comes first — and
each batch is padded to exactly ``max_batch`` rows so the node's jitted
apply sees ONE static batch shape and never recompiles.

Admission control happens here too: the request queue is bounded
(``TOS_SERVE_QUEUE``) and an arriving request that finds it full is
rejected immediately with :class:`ServeQueueFull` (the 503 of this wire
protocol) — a loaded gateway sheds load at the door instead of growing an
unbounded latency tail.  The queue itself is tenant-aware
(:class:`~.tenancy.TenantQueues`): requests carry an optional tenant key,
admission applies per-tenant token-bucket rate limits and the brownout
ladder (:class:`~.tenancy.ServeThrottled` — the 429), and batch building
drains the per-tenant FIFOs deficit-round-robin so one hot tenant cannot
monopolize batch fill.  Every request carries a deadline
(``TOS_SERVE_TIMEOUT`` default); requests that expire while still queued
are dropped before dispatch, and a late result for an expired waiter is
discarded — each accepted request is answered exactly once, with either
its results or one error.

A request may carry several rows; rows scatter back to their waiter by
position, and a request larger than ``max_batch`` simply spans batches.
"""

from __future__ import annotations

import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_condition
from time import monotonic as _monotonic
from typing import Any, Callable, Sequence

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.serving.tenancy import (  # noqa: F401 - ServeThrottled re-exported
    ServeThrottled,
    TenantQueues,
)
from tensorflowonspark_tpu.telemetry import trace as ttrace

logger = logging.getLogger(__name__)

#: Marker key for in-band control items on the serving stream (reload /
#: ping); ``serving_loop`` answers each with a one-item ack, preserving the
#: exactly-count invariant of the transport.  Lives here (the leaf serving
#: module) so gateway, router, and loop can all import it cycle-free.
CTL_KEY = "_tos_serve_ctl"


class ServeClosed(RuntimeError):
    """The gateway is shut down; no further requests are accepted."""


class ServeQueueFull(RuntimeError):
    """Admission control rejection: the bounded request queue is full
    (the wire protocol's 503 — retry later or add replicas)."""


class ServeTimeout(TimeoutError):
    """The request's deadline expired before its results arrived."""


class _Request:
    """One predict call: rows in, results (or one error) out, exactly once."""

    __slots__ = ("rows", "results", "remaining", "offset", "error",
                 "event", "deadline", "t_submit", "dispatched_at",
                 "callbacks", "trace", "resolved_at", "tenant")

    def __init__(self, rows: list, deadline: float, tenant: str = ""):
        self.rows = rows
        self.tenant = tenant
        self.results: list = [None] * len(rows)
        self.remaining = len(rows)
        self.offset = 0              # rows already pulled into batches
        self.error: Exception | None = None
        self.event = threading.Event()
        self.deadline = deadline
        self.t_submit = _monotonic()
        self.dispatched_at: float | None = None
        # done callbacks (the reactor frontend's completion path); invoked
        # exactly once, never with the batcher lock held
        self.callbacks: list = []
        # sampled request's trace context (None = unsampled/tracing off);
        # the root serve.request span records at resolution
        self.trace = None
        self.resolved_at: float | None = None


class MicroBatch:
    """One dispatchable unit: ``rows`` padded to the static batch shape,
    ``n`` real rows, and the (request, request_offset, count, batch_offset)
    entries that scatter results back to their waiters.  ``retries`` counts
    re-dispatches after a replica failure (the router allows one)."""

    __slots__ = ("rows", "n", "entries", "retries", "created_at",
                 "trace", "trace_parent", "cohort", "mirror_of")

    def __init__(self, rows: list, n: int,
                 entries: list[tuple[_Request, int, int, int]]):
        self.rows = rows
        self.n = n
        self.entries = entries
        self.retries = 0
        self.created_at = _monotonic()
        # rollout support (router-owned): which replica cohort this batch
        # must run on (None = router decides at submit); a shadow MIRROR
        # batch carries the primary's results here for output diffing and
        # has no entries — nothing waits on it
        self.cohort: str | None = None
        self.mirror_of: list | None = None
        # batch span context: derived from the FIRST sampled request in the
        # batch (the batcher "links N request spans to their batch span" —
        # the other sampled requests are listed in the span's link tags);
        # the router/wire/node spans all parent onto this ctx
        self.trace = None
        self.trace_parent: int | None = None


class PendingPrediction:
    """Async handle returned by ``predict_async``: ``result()`` blocks until
    the request's deadline and returns its results or raises its error."""

    def __init__(self, batcher: "MicroBatcher", request: _Request):
        self._batcher = batcher
        self._request = request

    def done(self) -> bool:
        return self._request.event.is_set()

    def result(self) -> list:
        return self._batcher.await_request(self._request)


class MicroBatcher:
    """Bounded request queue + the coalescing flush loop.

    ``dispatch`` (the router's ``submit``) receives each built
    :class:`MicroBatch`; ``pause_fn`` returning True holds flushes (the
    gateway raises it while a hot reload drains in-flight batches —
    requests keep queuing under the same admission bound meanwhile).

    ``capacity_fn`` makes the flush *capacity-aware*: a ripe partial batch
    is only dispatched while a replica can start it soon (the router's
    ``has_capacity``).  When every replica is already busy, flushing would
    just park a tiny batch in a replica queue — so the batcher keeps
    coalescing instead, and the arrivals that land during the in-flight
    round ride the next batch for free.  Measured on the 2-core bench box
    this is the difference between ~1-row fills convoying behind each
    other (95 qps, p50 296ms at 32 clients) and full-fill batches
    (~3400 qps, p50 9ms).
    """

    def __init__(self, dispatch: Callable[[MicroBatch], None], *,
                 max_batch: int, max_delay_secs: float, queue_limit: int,
                 pause_fn: Callable[[], bool] | None = None,
                 capacity_fn: Callable[[], bool] | None = None,
                 tenant_weights: dict[str, float] | None = None):
        self.max_batch = max(1, int(max_batch))
        self.max_delay = max(0.0, float(max_delay_secs))
        self.queue_limit = max(1, int(queue_limit))
        self._dispatch = dispatch
        self._pause_fn = pause_fn or (lambda: False)
        self._capacity_fn = capacity_fn or (lambda: True)
        self._cond = tos_named_condition("batcher._cond")
        # tenant-aware admission queue (per-tenant FIFOs, DRR drain, token
        # buckets, brownout ladder) — owned here, every access under _cond
        self._queue = TenantQueues(queue_limit=self.queue_limit,
                                   weights=tenant_weights)
        self._rows_queued = 0
        self._closed = False
        # requests finished while the lock was held, their callbacks not yet
        # run — drained by _fire_done() after every lock release
        self._done_pending: list[_Request] = []
        self._depth = telemetry.gauge("serve.queue_depth")
        self._thread = threading.Thread(target=self._flush_loop, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # -- admission -----------------------------------------------------------

    def submit(self, rows: Sequence[Any], deadline: float,
               tenant: str = "") -> _Request:
        """Admit one request or fast-fail; never blocks on a full queue."""
        rows = list(rows)
        if not rows:
            raise ValueError("predict needs at least one row")
        res = self.submit_many([(rows, deadline, None, tenant)])[0]
        if isinstance(res, Exception):
            raise res
        return res

    def submit_many(self, entries: list) -> list:
        """Bulk admission for the reactor: admit ``[(rows, deadline,
        done_cb[, tenant]), ...]`` under ONE lock acquisition with ONE
        flush-loop notify — a pipelined burst decoded in one read pass
        costs one critical section, not one per request.  Returns one
        entry per input: the admitted request, or the admission error
        instance (:class:`ServeClosed` / :class:`ServeQueueFull` /
        :class:`~.tenancy.ServeThrottled`) for refusals.  Callbacks are
        attached inside the lock, so a request can never resolve before
        its callback is registered."""
        out: list = []
        accepted = rows_total = 0
        with self._cond:
            for entry in entries:
                rows, deadline, done_cb = entry[0], entry[1], entry[2]
                tenant = entry[3] if len(entry) > 3 else ""
                if self._closed:
                    out.append(ServeClosed("serving gateway is closed"))
                    continue
                if len(self._queue) >= self.queue_limit:
                    telemetry.counter("serve.rejected_total").inc()
                    out.append(ServeQueueFull(
                        f"request queue full ({self.queue_limit} queued); "
                        "retry later or add replicas"))
                    continue
                shed = self._queue.admission_error(tenant, len(rows))
                if shed is not None:
                    out.append(shed)
                    continue
                req = _Request(rows, deadline, tenant)
                # gateway-side trace stamping: the deterministic sampler
                # (TOS_TRACE_SAMPLE) decides here, once, for the request's
                # whole cross-process life; None costs one check downstream
                req.trace = ttrace.sample()
                if done_cb is not None:
                    req.callbacks.append(done_cb)
                self._queue.append(req)
                self._rows_queued += len(rows)
                accepted += 1
                rows_total += len(rows)
                out.append(req)
            if accepted:
                self._depth.set(len(self._queue))
                self._cond.notify_all()
        if accepted:
            telemetry.counter("serve.requests_total").inc(accepted)
            telemetry.counter("serve.rows_total").inc(rows_total)
        return out

    def shed_level(self) -> int:
        """Current brownout rung (0 = normal) — the rollout layer pauses
        shadow mirroring at level >= 1; see ``tenancy.TenantQueues``."""
        with self._cond:
            return self._queue.shed_level()

    def tenant_depths(self) -> dict[str, int]:
        """Queued requests per tenant — the per-tenant stats surface."""
        with self._cond:
            return self._queue.depths()

    def await_request(self, req: _Request) -> list:
        """Block until the request resolves or its deadline passes; returns
        results or raises the request's single error."""
        if not req.event.wait(max(0.0, req.deadline - _monotonic())):
            self.expire(req)
            req.event.wait()  # expire (or a racing completion) resolved it
        if req.error is not None:
            raise req.error
        return req.results

    def add_done_callback(self, req: _Request, fn) -> None:
        """Register ``fn(req)`` to run once the request resolves (results or
        error) — the reactor frontend's completion hook, so no thread ever
        blocks in ``await_request`` for a wire request.  Runs on whichever
        thread resolves the request (router worker, flush loop, expiry,
        close), never with the batcher lock held; when the request already
        resolved, runs immediately on the calling thread."""
        with self._cond:
            if not req.event.is_set():
                req.callbacks.append(fn)
                return
        fn(req)

    def expire(self, req: _Request) -> None:
        """Resolve ``req`` with :class:`ServeTimeout` unless completion won
        the race — idempotent; callable from the waiter thread
        (``await_request``) or the reactor's deadline sweep."""
        with self._cond:
            if req.event.is_set():
                return  # completion won the race
            try:
                self._queue.remove(req)
                self._rows_queued -= len(req.rows) - req.offset
                self._depth.set(len(self._queue))
            except ValueError:  # toslint: allow-silent(already pulled into an in-flight batch; the late results are discarded below)
                pass
            telemetry.counter("serve.expired_total").inc()
            self._finish_locked(req, ServeTimeout(
                f"request deadline expired after "
                f"{_monotonic() - req.t_submit:.3f}s"))
        self._fire_done()

    def cancel(self, req: _Request, error: Exception | None = None) -> None:
        """Resolve ``req`` with ``error`` (default :class:`ServeClosed`)
        without waiting for results: queued rows — including a spanning
        request's tail — are pulled out so they never reach a replica or
        hold an admission slot; a slice already in flight completes on its
        replica and is discarded at scatter time (the set event).  The
        frontend calls this when a client disconnects with requests
        outstanding."""
        with self._cond:
            if req.event.is_set():
                return
            try:
                self._queue.remove(req)
                self._rows_queued -= len(req.rows) - req.offset
                self._depth.set(len(self._queue))
            except ValueError:  # toslint: allow-silent(already pulled into an in-flight batch; the late results are discarded at scatter time)
                pass
            telemetry.counter("serve.cancelled_total").inc()
            self._finish_locked(req, error or ServeClosed(
                "request cancelled (client gone)"))
        self._fire_done()

    # -- flush loop ----------------------------------------------------------

    def _flush_loop(self) -> None:
        while True:
            batch: MicroBatch | None = None
            with self._cond:
                while not self._closed and batch is None:
                    self._drop_expired_locked()
                    if self._done_pending:
                        break  # run expiry callbacks before waiting again
                    if self._queue and not self._pause_fn():
                        oldest = self._queue.oldest_submit()
                        age = (_monotonic() - oldest if oldest is not None
                               else 0.0)
                        ripe = (self._rows_queued >= self.max_batch
                                or age >= self.max_delay)
                        if ripe and self._capacity_fn():
                            batch = self._build_batch_locked()
                            if batch is not None:
                                break
                            continue  # only already-resolved requests queued
                        # ripe but no downstream capacity: hold — completion
                        # notifies this cond, and every arrival meanwhile
                        # raises the eventual batch's fill
                        self._cond.wait(0.05 if ripe
                                        else min(self.max_delay - age, 0.05))
                    else:
                        self._cond.wait(0.05)
                closed = self._closed
            self._fire_done()
            if batch is not None:
                self._dispatch(batch)
            elif closed:
                return  # close() already resolved the queue

    def _drop_expired_locked(self) -> None:
        now = _monotonic()
        expired = [r for r in self._queue if r.deadline <= now]
        for req in expired:
            self._queue.remove(req)
            self._rows_queued -= len(req.rows) - req.offset
            telemetry.counter("serve.expired_total").inc()
            self._finish_locked(req, ServeTimeout(
                "request deadline expired while queued"))
        if expired:
            self._depth.set(len(self._queue))

    def _build_batch_locked(self) -> MicroBatch | None:
        """Pull up to ``max_batch`` rows in deficit-round-robin tenant
        order (``TenantQueues.next_for_batch``); None when everything
        queued turned out to be already resolved."""
        rows: list = []
        entries: list[tuple[_Request, int, int, int]] = []
        now = _monotonic()
        while len(rows) < self.max_batch:
            req = self._queue.next_for_batch()
            if req is None:
                break
            if req.event.is_set():
                # already resolved (expired, or an earlier slice's batch
                # failed): its queued tail must not reach a replica or keep
                # occupying an admission slot
                self._queue.discard(req)
                self._rows_queued -= len(req.rows) - req.offset
                continue
            take = min(len(req.rows) - req.offset, self.max_batch - len(rows))
            entries.append((req, req.offset, take, len(rows)))
            rows.extend(req.rows[req.offset:req.offset + take])
            if req.dispatched_at is None:
                req.dispatched_at = now
                telemetry.histogram("serve.queue_wait_secs").observe(
                    now - req.t_submit)
                # stage span: admission wait (submit -> pulled into a batch)
                ttrace.record_child("serve.admission", req.trace,
                                    req.t_submit, now - req.t_submit)
            req.offset += take
            self._queue.charge(req, take)
        if not rows:
            self._depth.set(len(self._queue))
            return None
        n = len(rows)
        self._rows_queued -= n
        self._depth.set(len(self._queue))
        telemetry.counter("serve.batches_total").inc()
        telemetry.histogram("serve.batch_fill").observe(n / self.max_batch)
        # pad to the static batch shape: the jitted apply compiles once
        rows.extend(rows[-1] for _ in range(self.max_batch - n))
        batch = MicroBatch(rows, n, entries)
        sampled = [r for r, _roff, _cnt, _boff in entries
                   if r.trace is not None]
        if sampled:
            # batch span under the first sampled request; the rest are
            # linked by id so their traces reach this batch in the export
            batch.trace = ttrace.derive(sampled[0].trace)
            batch.trace_parent = sampled[0].trace[1]
        return batch

    # -- completion (router threads) -----------------------------------------

    def complete_batch(self, batch: MicroBatch, results: list) -> None:
        """Scatter one batch's results back to each waiter (positional)."""
        self._record_batch_span(batch)
        with self._cond:
            for req, roff, cnt, boff in batch.entries:
                if req.event.is_set():
                    continue  # expired/errored while the batch was in flight
                req.results[roff:roff + cnt] = results[boff:boff + cnt]
                req.remaining -= cnt
                if req.remaining <= 0:
                    self._finish_locked(req, None)
            self._cond.notify_all()  # capacity freed: the flush loop may act
        self._fire_done()

    def fail_batch(self, batch: MicroBatch, error: Exception) -> None:
        """Resolve every waiter of a failed batch with one error.  A
        spanning request whose later rows are still queued is pulled out —
        one error answers the whole request, and scoring its tail would be
        wasted replica work charged against the admission bound."""
        self._record_batch_span(batch, error=error)
        with self._cond:
            for req, _roff, _cnt, _boff in batch.entries:
                if not req.event.is_set():
                    self._finish_locked(req, error)
                    if req.offset < len(req.rows):
                        try:
                            self._queue.remove(req)
                            self._rows_queued -= len(req.rows) - req.offset
                        except ValueError:  # toslint: allow-silent(tail already pulled into another in-flight batch; complete/fail will skip the set event)
                            pass
            self._depth.set(len(self._queue))
            self._cond.notify_all()
        self._fire_done()

    def _record_batch_span(self, batch: MicroBatch,
                           error: Exception | None = None) -> None:
        """Record the serve.batch span (build -> scatter) with its request
        links; called OUTSIDE the lock, once per batch resolution."""
        if batch.trace is None:
            return
        tags: dict = {"rows": batch.n, "retries": batch.retries}
        links = [[r.trace[0], r.trace[1]]
                 for r, _roff, _cnt, _boff in batch.entries
                 if r.trace is not None]
        if len(links) > 1:
            tags["links"] = links[1:]
        if error is not None:
            tags["error"] = type(error).__name__
        ttrace.record_span("serve.batch", batch.trace, batch.trace_parent,
                           batch.created_at, _monotonic() - batch.created_at,
                           tags)

    def _finish_locked(self, req: _Request, error: Exception | None) -> None:
        req.error = error
        req.resolved_at = _monotonic()
        if error is None:
            telemetry.histogram("serve.request_secs").observe(
                req.resolved_at - req.t_submit)
        if req.trace is not None:
            # root span: the whole request, submit -> resolution (stage
            # spans — admission/batch_fill/wire/node_round/reply — nest
            # under it in the merged trace)
            tags = {"rows": len(req.rows)}
            if error is not None:
                tags["error"] = type(error).__name__
            ttrace.record_span("serve.request", req.trace, None,
                               req.t_submit, req.resolved_at - req.t_submit,
                               tags)
        req.event.set()
        if req.callbacks:
            self._done_pending.append(req)

    def _fire_done(self) -> None:
        """Run done callbacks of requests resolved under the lock — outside
        it, so a callback may safely re-enter the batcher (submit / cancel)
        without deadlocking."""
        while True:
            with self._cond:
                if not self._done_pending:
                    return
                pending, self._done_pending = self._done_pending, []
            for req in pending:
                callbacks, req.callbacks = req.callbacks, []
                for fn in callbacks:
                    try:
                        fn(req)
                    except Exception:  # noqa: BLE001 - one bad callback must not orphan the rest
                        logger.exception("serve done-callback failed")

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            err = ServeClosed("serving gateway closed with the request queued")
            for req in self._queue:
                self._finish_locked(req, err)
            self._queue.clear()
            self._rows_queued = 0
            self._depth.set(0)
            self._cond.notify_all()
        self._fire_done()
        self._thread.join(timeout=10.0)
