"""Least-outstanding batch routing across node replicas.

The availability half of the serving subsystem: each feedable node runs the
resident ``serving_loop`` map_fun and this router spreads micro-batches
across them — every batch goes to the healthy replica with the fewest
outstanding (queued + in-flight) batches, the closed-loop analogue of the
reference's Spark partition placement, but latency-aware.

Transport is the existing data plane: one ``DataClient`` per replica, each
batch one ``infer_partition`` round-trip (protocol-5 zero-copy frames,
exactly-count ordered results).  One worker thread per replica serializes
its rounds — interleaving two batches on one connection would interleave
their rows in the node's input queue.

Failure semantics (wired into the ISSUE-1 elastic machinery):

- a batch in flight on a replica that dies is retried ONCE on a live
  replica before its waiters see an error;
- the dead replica is marked unhealthy and its queued (not yet attempted)
  batches re-route to survivors without spending their retry;
- a recovery thread re-admits the replica once it is reachable again —
  restarted (bumped incarnation, fresh queues) or still the same live
  process (a severed socket, a timed-out round) — but only after an
  order-fenced *resync*: a nonce'd ping control round whose pong, by the
  map_fun's FIFO processing, proves every result of an abandoned round
  has been drained and discarded, so stale results can never corrupt a
  later batch's exactly-count collection (``_resync``).  A hot reload the
  replica missed while out is replayed before it rejoins routing.

Hot reload support: ``drain()`` blocks until no batch is queued or in
flight, and ``broadcast_ctl()`` round-trips a control item (e.g. the
``serving_loop`` reload command) through every healthy replica while the
workers are idle.

Staged rollouts (ISSUE 16): ``set_rollout`` splits the fleet into a
primary and a canary cohort.  Routing becomes cohort-aware — every
``traffic_every``-th batch rides the canary, every ``mirror_every``-th
successful primary batch is cloned onto the canary as a no-waiter shadow
mirror carrying the primary's results for output diffing, and every batch
outcome (cohort, latency, results, transport error) feeds the rollout
governor's observer.  Live traffic never depends on the canary: a canary
batch with no healthy canary falls back to primary, a failed canary
attempt retries on primary, and mirrors are dropped (shed first under
brownout).  ``ctl_to`` targets control rounds at one cohort, and the
cohort's reload ctl is remembered so a canary that dies mid-rollout is
converged back onto the candidate bundle before re-admission.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_condition
from time import monotonic as _monotonic
from typing import Any

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.serving.batcher import (
    CTL_KEY,
    MicroBatch,
    MicroBatcher,
)
from tensorflowonspark_tpu.telemetry import trace as ttrace

logger = logging.getLogger(__name__)


class EmbedLookupError(RuntimeError):
    """A sharded-embedding fan-out round failed (a shard OWNER was
    unreachable or timed out).  Deliberately distinct from a scoring-
    replica transport error: the scoring replica is healthy and must not
    be fenced for another node's shard being dark — the batch retries
    (the owner may have recovered) and then fails its waiters."""


class _Replica:
    __slots__ = ("executor_id", "queue", "inflight", "healthy", "client",
                 "client_inc", "pending_ctl", "thread", "last_pick",
                 "draining", "retired", "cohort")

    def __init__(self, executor_id: int):
        self.executor_id = executor_id
        self.queue: list[MicroBatch] = []
        self.inflight = 0
        self.healthy = True
        self.client = None
        self.client_inc = -1
        # a control item (hot reload) this replica missed while unhealthy;
        # replayed by recovery before the replica rejoins routing
        self.pending_ctl: dict | None = None
        self.thread: threading.Thread | None = None
        self.last_pick = 0
        # rollout cohort: "primary" outside a rollout; the canary members
        # of an active rollout carry "canary" (the object outlives the
        # replica PROCESS, so a SIGKILLed canary's restart rejoins the
        # same cohort — recovery replays the cohort's reload ctl first)
        self.cohort = "primary"
        # scale-in lifecycle (retire_replica): a DRAINING replica finishes
        # its queued/in-flight batches but is never picked for new ones;
        # RETIRED tells its worker thread to exit once the queue is empty
        self.draining = False
        self.retired = False


def _load(rep: _Replica) -> int:
    """A replica's outstanding work (queued + in-flight batches) — the ONE
    load definition shared by routing picks, the inflight gauge, the public
    ``replica_loads()`` surface, and autoscaling victim selection."""
    return len(rep.queue) + rep.inflight


# monotone per-process router id: the key each router publishes its serving
# replica set under in the coordinator's journal-backed registry
# (itertools.count: gateways can be opened from concurrent threads)
_ROUTER_SEQ = itertools.count(1)


class ReplicaRouter:
    """Dispatch micro-batches to the cluster's serving replicas."""

    def __init__(self, cluster, batcher: MicroBatcher, *,
                 qname_in: str = "input", qname_out: str = "output",
                 request_timeout: float = 30.0):
        self._cluster = cluster
        self._batcher = batcher
        self.qname_in = qname_in
        self.qname_out = qname_out
        # Data-plane budgets: serving round-trips are sub-second, so a
        # replica that stalls past a couple of request deadlines is treated
        # as failed (the retry path owns recovery) instead of pinning a
        # worker for the feed-path's ~10-minute budget.
        self._stall_timeout = max(10.0, 2.0 * request_timeout)
        self._call_timeout = self._stall_timeout + 30.0
        self._cond = tos_named_condition("router._cond")
        self._stop = False
        self._pick_seq = 0
        self._resync_seq = 0  # recovery-thread only; nonces for _resync
        # rollout state (set_rollout/clear_rollout): deterministic traffic
        # split + shadow mirroring + the per-batch outcome observer the
        # rollout governor feeds on.  All mutated under _cond.
        self._batch_seq = 0
        self._mirror_seq = 0
        self._canary_every = 0   # every Nth batch routes to canary (0=off)
        self._mirror_every = 0   # every Nth primary batch mirrored (0=off)
        self._observer = None    # fn(cohort, eid, ok, secs, results, error, mirror_of)
        self._cohort_ctl: dict[str, dict] = {}  # cohort -> reload ctl for recovery
        self._shed_fn = lambda: 0  # batcher brownout level (sheds mirrors)
        self._replicas: dict[int, _Replica] = {
            eid: _Replica(eid) for eid in cluster._feed_ids}
        # sharded-embedding fan-out state (set_embed_plan): owner plan over
        # the serve fleet, the id-extraction fn from the bundle config, and
        # one DEDICATED DataClient per shard owner on the embed queue pair
        # — reusing rep.client would interleave lookup results into batch
        # rounds and break their exactly-count collection.  Per-owner locks
        # serialize rounds per connection for the same reason.
        self._embed_plan = None
        self._embed_id_fn = None
        self._embed_owners: list[int] = []
        self._embed_clients: dict[int, Any] = {}
        self._embed_locks: dict[int, Any] = {}
        # journal-backed serving registry (ISSUE 13): this router's healthy
        # replica set, published to the coordinator whenever it changes so
        # a control-plane failover restores who was serving
        self._registry_name = f"router{next(_ROUTER_SEQ)}"
        self._published: list[int] | None = None
        self._healthy_gauge = telemetry.gauge("serve.replicas_healthy")
        self._draining_gauge = telemetry.gauge("serve.replicas_draining")
        self._outstanding_gauge = telemetry.gauge("serve.inflight_batches")
        self._healthy_gauge.set(len(self._replicas))
        self._draining_gauge.set(0)
        for rep in self._replicas.values():
            rep.thread = threading.Thread(
                target=self._worker, args=(rep,), daemon=True,
                name=f"serve-replica-{rep.executor_id}")
            rep.thread.start()
        self._recovery = threading.Thread(target=self._recovery_loop,
                                          daemon=True, name="serve-recovery")
        self._recovery.start()
        self._publish_registry()

    def _publish_registry(self) -> None:
        """Best-effort publish of this router's healthy replica set to the
        coordinator's journal-backed serving registry (no-op changes are
        deduped).  Never on a hot path; never raises — the registry is
        failover evidence, not routing state."""
        coord = getattr(self._cluster, "coordinator", None)
        if coord is None or not hasattr(coord, "note_serving_replicas"):
            return
        try:
            healthy = self.healthy_replicas()
            if healthy != self._published:
                self._published = healthy
                coord.note_serving_replicas(self._registry_name, healthy)
        except Exception:  # noqa: BLE001 - registry publish must not break serving
            logger.debug("serving-registry publish failed", exc_info=True)

    # -- dispatch ------------------------------------------------------------

    def submit(self, batch: MicroBatch, exclude: int | None = None) -> None:
        """Queue the batch on the least-outstanding healthy replica of its
        cohort; a batch that finds no healthy replica fails its waiters
        immediately.  Cohort selection: a fresh batch is assigned here
        (every ``canary_every``-th batch rides the canary during a live
        split); a canary batch with no healthy canary replica falls back
        to primary — live traffic must never fail because the canary
        cohort is down — while a shadow MIRROR (nothing waits on it) is
        simply dropped."""
        with self._cond:
            if batch.cohort is None:
                batch.cohort = self._choose_cohort_locked()
            target = None if self._stop else self._pick_locked(
                exclude, batch.cohort)
            if target is None and batch.cohort == "canary" and not self._stop:
                if batch.mirror_of is not None:
                    telemetry.counter("serve.shadow_dropped").inc()
                    return
                batch.cohort = "primary"
                target = self._pick_locked(exclude, "primary")
            if target is not None:
                target.queue.append(batch)
                self._update_outstanding_locked()
                self._cond.notify_all()
                return
        if batch.mirror_of is not None:
            return  # mirrors carry no waiters; nothing to fail
        self._batcher.fail_batch(batch, RuntimeError(
            "no healthy serving replica available"))

    def _choose_cohort_locked(self) -> str:
        if not self._canary_every:
            return "primary"
        self._batch_seq += 1
        return ("canary" if self._batch_seq % self._canary_every == 0
                else "primary")

    def _pick_locked(self, exclude: int | None,
                     cohort: str = "primary") -> _Replica | None:
        live = [r for r in self._replicas.values()
                if r.healthy and not r.draining and r.executor_id != exclude
                and r.cohort == cohort]
        if not live:
            return None
        # least-outstanding, ties broken least-recently-picked: a fixed
        # tiebreak (executor id) would route EVERY batch to replica 0 at
        # low load, leaving the rest cold — LRU rotation spreads them
        target = min(live, key=lambda r: (_load(r), r.last_pick))
        self._pick_seq += 1
        target.last_pick = self._pick_seq
        return target

    def _update_outstanding_locked(self) -> None:
        self._outstanding_gauge.set(sum(
            _load(r) for r in self._replicas.values()))

    def has_capacity(self) -> bool:
        """True while some healthy replica is strictly IDLE (0 outstanding).
        The batcher gates partial-batch flushes on this — see
        ``MicroBatcher``.  Strictly-idle beats allowing one queued batch
        behind the in-flight one on the bench box: the queued slot just
        re-creates a small-batch convoy (fill p50 6 rows / 280 qps at
        ``<= 1`` vs 9+ rows / 430 qps at ``== 0``).  Full batches are
        gated too — they wait in the BATCHER queue rather than a replica
        queue, which costs one completion-notify wakeup but keeps the
        least-outstanding choice as late (= as informed) as possible.
        With NO healthy replica it returns True so batches flush and fail
        fast instead of silently aging out on their deadlines.  Only the
        PRIMARY cohort counts: during a shadow rollout the canary replicas
        sit idle between mirrors, and letting their idleness trigger
        partial flushes would re-create the small-batch convoy on the
        primaries that actually serve the traffic."""
        with self._cond:
            live = [r for r in self._replicas.values()
                    if r.healthy and not r.draining
                    and r.cohort == "primary"]
            if not live:
                return True
            return any(_load(r) == 0 for r in live)

    # -- per-replica worker --------------------------------------------------

    def _worker(self, rep: _Replica) -> None:
        while True:
            exit_client = None
            with self._cond:
                while not self._stop and not rep.queue and not rep.retired:
                    self._cond.wait(0.2)
                if self._stop or (rep.retired and not rep.queue):
                    if rep.retired:
                        # retire_replica leaves the client to us when we
                        # outlived its join (batch completing past the
                        # drain deadline); on stop, close() owns clients
                        exit_client, rep.client = rep.client, None
                    batch = None
                else:
                    batch = rep.queue.pop(0)
                    rep.inflight += 1
                    self._update_outstanding_locked()
            if batch is None:
                if exit_client is not None:
                    with contextlib.suppress(Exception):
                        exit_client.close()
                return
            error: Exception | None = None
            results: list | None = None
            if batch.trace is not None and batch.retries == 0:
                # stage span: batch fill/hold (built -> wire call starts;
                # capacity holds and router queueing both land here).  Only
                # the first dispatch records it — a retried batch would emit
                # a second fill span spanning the failed wire attempt too
                ttrace.record_child(
                    "serve.batch_fill", batch.trace, batch.created_at,
                    _monotonic() - batch.created_at)
            t0 = _monotonic()
            try:
                client = self._client_for(rep)
                wire_rows = batch.rows
                wrapped = False
                if self._embed_plan is not None:
                    # sharded-embedding mode: gather the batch's fused-table
                    # rows from the owner shards first, then ship ONE
                    # wrapped item — the scoring replica answers with one
                    # result item the unwrap below opens (exactly-count: 1)
                    with ttrace.span("serve.embed_fanout",
                                     parent=batch.trace):
                        emb = self._gather_embeddings(batch.rows)
                    wire_rows = [{CTL_KEY: "sharded_batch",
                                  "rows": list(batch.rows), "emb": emb}]
                    wrapped = True
                with telemetry.timed("serve.batch_secs"), \
                        ttrace.span("serve.wire", parent=batch.trace,
                                    tags={"executor": rep.executor_id}) as ws:
                    results = client.infer_round(
                        wire_rows, self.qname_in, self.qname_out,
                        trace=ws.ctx)
                if wrapped:
                    ack = results[0] if results else None
                    if not (isinstance(ack, dict)
                            and ack.get(CTL_KEY) == "sharded_results"):
                        raise RuntimeError(
                            f"sharded batch round answered {type(ack)}")
                    results = list(ack["results"])
            except Exception as e:  # noqa: BLE001 - retried/surfaced below
                error = e
            rerouted: list[MicroBatch] = []
            with self._cond:
                rep.inflight -= 1
                if (error is not None and not self._stop
                        and not isinstance(error, EmbedLookupError)):
                    # a failed LOOKUP owner is not this replica's failure —
                    # fence nothing; the retry redoes the fan-out
                    rerouted = self._mark_unhealthy_locked(rep)
                self._update_outstanding_locked()
                self._cond.notify_all()
            self._observe(batch, rep, error, _monotonic() - t0, results)
            if error is None:
                if batch.mirror_of is None:
                    self._batcher.complete_batch(batch, results)
                    self._maybe_mirror(batch, results)
                # a mirror's results went to the observer (output diff);
                # nothing waits on the batch itself
                continue
            logger.warning("serving replica %d failed a batch: %s",
                           rep.executor_id, error)
            for queued in rerouted:
                # never attempted on this replica: re-route without
                # spending the queued batch's one retry
                self.submit(queued, exclude=rep.executor_id)
            if batch.mirror_of is not None:
                continue  # a failed mirror is dropped, never retried
            self._retry(batch, rep.executor_id, error)

    def _observe(self, batch: MicroBatch, rep: _Replica,
                 error: Exception | None, secs: float,
                 results: list | None) -> None:
        """Feed one batch outcome to the rollout observer (never on the
        lock, never allowed to break serving).  The observer owns the
        canary-vs-primary bookkeeping — error classification (an exception
        HERE is transport/infra, e.g. a dead replica, and must not count
        as model regression), latency windows, NaN/divergence scans."""
        obs = self._observer
        if obs is None:
            return
        try:
            obs(batch.cohort or "primary", rep.executor_id, error is None,
                secs, results, error, batch.mirror_of)
        except Exception:  # noqa: BLE001 - rollout bookkeeping must not break serving
            logger.debug("rollout observer failed", exc_info=True)

    def _maybe_mirror(self, batch: MicroBatch, results: list) -> None:
        """Shadow sampling: clone every ``mirror_every``-th successful
        PRIMARY batch onto the canary cohort, carrying the primary's
        results for the observer's output diff.  Mirrors have no entries —
        no client ever waits on one — and are the FIRST traffic shed under
        brownout (ladder level 1)."""
        if (self._mirror_every <= 0 or batch.cohort != "primary"
                or batch.mirror_of is not None):
            return
        if self._shed_fn() >= 1:
            telemetry.counter("serve.shadow_shed").inc()
            return
        with self._cond:
            self._mirror_seq += 1
            if self._mirror_seq % self._mirror_every:
                return
        mirror = MicroBatch(batch.rows, batch.n, [])
        mirror.cohort = "canary"
        mirror.mirror_of = results
        telemetry.counter("serve.shadow_mirrors").inc()
        self.submit(mirror)

    def _retry(self, batch: MicroBatch, failed_eid: int,
               error: Exception) -> None:
        if batch.retries < 1:
            batch.retries += 1
            telemetry.counter("serve.retries_total").inc()
            ttrace.event("retry", executor=failed_eid, rows=batch.n,
                         error=str(error)[:200])
            logger.warning("retrying in-flight batch from dead replica %d "
                           "on a live replica", failed_eid)
            # a failed canary attempt retries on the PRIMARY cohort: the
            # request's answer must never depend on the canary staying up
            if batch.cohort == "canary":
                batch.cohort = "primary"
            self.submit(batch, exclude=failed_eid)
            return
        wrapped = RuntimeError(
            f"serving batch failed on replica {failed_eid} after retry: "
            f"{error}")
        wrapped.__cause__ = error
        self._batcher.fail_batch(batch, wrapped)

    def _mark_unhealthy_locked(self, rep: _Replica) -> list[MicroBatch]:
        """Fence the replica out of routing; returns its queued batches for
        the caller to re-route OUTSIDE the lock.  Re-admission goes through
        ``_try_recover`` (dial + order-fenced resync), which handles both a
        restarted process and a live one whose round was abandoned."""
        if rep.healthy:
            rep.healthy = False
            telemetry.counter("serve.replica_failures").inc()
            ttrace.event("replica_unhealthy", executor=rep.executor_id)
        stale, rep.client = rep.client, None
        if stale is not None:
            with contextlib.suppress(Exception):
                stale.abort()
        queued, rep.queue = rep.queue, []
        self._healthy_gauge.set(
            sum(1 for r in self._replicas.values() if r.healthy))
        return queued

    def _client_for(self, rep: _Replica):
        """The replica's data client, dialing if needed.  Only its own worker
        (or the drained/paused reload path) calls this, so the mutation needs
        no lock — routing never hands one replica's rounds to another
        thread."""
        if rep.client is None:
            from tensorflowonspark_tpu.dataserver import DataClient

            meta = self._cluster._fresh_meta(rep.executor_id)
            inc, _ = self._cluster.coordinator.registered_incarnation(
                rep.executor_id)
            rep.client = DataClient(
                meta["host"], meta["data_port"], self._cluster.authkey,
                call_timeout=self._call_timeout,
                stall_timeout=self._stall_timeout,
                connect_timeout=10.0)
            rep.client_inc = inc
        return rep.client

    # -- sharded-embedding fan-out (gateway.set via set_embed_plan) ----------

    def set_embed_plan(self, block: dict, id_fn) -> None:
        """Enter sharded-embedding mode: the bundle's table (``block`` is
        its ``"sharded_embedding"`` config) is resident range-sharded over
        the serve fleet, and every scoring batch is preceded by a fan-out
        that gathers its unique fused-table rows from the owner replicas.
        ``id_fn(features) -> [B, C] int64`` extracts the table ids from a
        stacked feature batch (model-specific; the gateway builds it from
        the bundle config)."""
        from tensorflowonspark_tpu.embedding.sharding import ShardPlan
        from tensorflowonspark_tpu.utils.locks import tos_named_lock

        with self._cond:
            owners = sorted(self._replicas)
            if owners != list(range(len(owners))):
                # replica ranks must mirror the node-side shard loading
                # (rank = executor_id, world = num_executors)
                logger.warning(
                    "sharded embeddings over a non-contiguous replica set "
                    "%s; shard ownership assumes rank == executor id",
                    owners)
            self._embed_plan = ShardPlan.even(
                str(block["name"]), int(block["total_rows"]),
                int(block["dim"]), len(owners))
            self._embed_id_fn = id_fn
            self._embed_owners = owners
            self._embed_locks = {
                eid: tos_named_lock(f"router._embed[{eid}]")
                for eid in owners}

    def clear_embed_plan(self) -> None:
        with self._cond:
            self._embed_plan = None
            self._embed_id_fn = None
            clients, self._embed_clients = self._embed_clients, {}
        for client in clients.values():
            with contextlib.suppress(Exception):
                client.close()

    def _gather_embeddings(self, rows: list):
        """One fan-out round: rows -> stacked features -> unique table ids
        -> per-owner lookup sub-requests -> assembled ``[B, C, dim]`` fused
        rows.  Any owner failure raises :class:`EmbedLookupError`."""
        import numpy as np

        from tensorflowonspark_tpu.inference import rows_to_features

        plan, id_fn = self._embed_plan, self._embed_id_fn
        ids = id_fn(rows_to_features(list(rows), None))
        flat = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(flat, return_inverse=True)
        idx = plan.partition(uniq)
        out = np.empty((uniq.size, plan.dim), np.float32)
        for r, eid in enumerate(self._embed_owners):
            if not idx[r].size:
                continue
            got = self._embed_lookup_round(eid, uniq[idx[r]])
            out[idx[r]] = got
        telemetry.counter("serve.embed_fanouts").inc()
        telemetry.counter("serve.embed_rows_fetched").inc(int(uniq.size))
        return out[inv].reshape(ids.shape + (plan.dim,))

    def _embed_lookup_round(self, eid: int, ids):
        """One id-lookup sub-request to the shard owner ``eid`` over its
        dedicated embed-queue client (dialed lazily, serialized by the
        per-owner lock, torn down on failure so the next round redials)."""
        from tensorflowonspark_tpu.embedding.serve import (
            EMBED_QNAME_IN,
            EMBED_QNAME_OUT,
        )
        from tensorflowonspark_tpu.utils.envtune import env_float

        timeout = env_float("TOS_EMBED_LOOKUP_TIMEOUT", 30.0)
        lock = self._embed_locks.get(eid)
        if lock is None:
            raise EmbedLookupError(f"no embed lock for owner {eid}")
        with lock:
            client = self._embed_clients.get(eid)
            try:
                if client is None:
                    from tensorflowonspark_tpu.dataserver import DataClient

                    meta = self._cluster._fresh_meta(eid)
                    client = DataClient(
                        meta["host"], meta["data_port"],
                        self._cluster.authkey,
                        call_timeout=timeout + 30.0, stall_timeout=timeout,
                        connect_timeout=5.0)
                    self._embed_clients[eid] = client
                got = client.infer_round(
                    [{"ids": ids}], EMBED_QNAME_IN, EMBED_QNAME_OUT,
                    wait=timeout)
                return got[0]["rows"]
            except Exception as e:  # noqa: BLE001 - wrapped for the worker
                stale = self._embed_clients.pop(eid, None)
                if stale is not None:
                    with contextlib.suppress(Exception):
                        stale.abort()
                raise EmbedLookupError(
                    f"embedding lookup to shard owner {eid} failed: "
                    f"{e}") from e

    # -- recovery ------------------------------------------------------------

    def _recovery_loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                # draining replicas are on their way OUT (retire_replica owns
                # their teardown) — re-admitting one would route new batches
                # onto a node about to receive its EOF
                down = [r for r in self._replicas.values()
                        if not r.healthy and not r.draining]
            for rep in down:
                self._try_recover(rep)
            # keep the coordinator's journal-backed registry current with
            # whatever membership changes this pass (or a death elsewhere)
            # produced — the tick is the change-coalescing boundary
            self._publish_registry()
            with self._cond:
                if self._stop:
                    return
                self._cond.wait(0.5)

    def _try_recover(self, rep: _Replica) -> bool:
        """Re-admit one unhealthy replica: dial, order-fenced resync, replay
        any hot reload it missed, THEN rejoin routing.  Works for a
        supervised restart (fresh queues — the resync pong comes straight
        back) and for a live process whose round was abandoned (sever,
        timeout — the resync drains and discards the stale results first)."""
        inc, tracked = self._cluster.coordinator.registered_incarnation(
            rep.executor_id)
        if not tracked:
            return False  # dead / mid-restart: nothing to dial yet
        try:
            from tensorflowonspark_tpu.dataserver import DataClient

            meta = self._cluster._fresh_meta(rep.executor_id)
            client = DataClient(
                meta["host"], meta["data_port"], self._cluster.authkey,
                call_timeout=self._call_timeout,
                stall_timeout=self._stall_timeout,
                connect_timeout=3.0, connect_attempts=1)
        except Exception:  # noqa: BLE001 - port dark mid-restart
            return False
        with self._cond:
            pinned = rep.pending_ctl  # snapshot; re-checked at admission
            # during a rollout the replica's COHORT pins a reload ctl too
            # (set_rollout): a SIGKILLed canary's restart boots from the
            # ORIGINAL export_dir, so recovery must replay the cohort's
            # candidate reload or the "canary" would silently serve the
            # primary bundle and poison the governor's comparison
            pending = pinned or self._cohort_ctl.get(rep.cohort)
        try:
            if not self._resync(client):
                raise RuntimeError("resync did not complete in time")
            if pending is not None:
                # a hot reload landed while this replica was out: a restarted
                # process MAY have loaded the new export already, but the
                # replay is idempotent — never guess, always converge
                client.infer_round([dict(pending)], self.qname_in,
                                   self.qname_out)
        except Exception as e:  # noqa: BLE001 - stay out, retry next pass
            logger.debug("serving replica %d not re-admitted yet: %s",
                         rep.executor_id, e)
            with contextlib.suppress(Exception):
                client.close()
            return False
        with self._cond:
            if rep.pending_ctl is not None and rep.pending_ctl != pinned:
                # a reload broadcast pinned a NEWER ctl while this recovery
                # was in flight: admitting now would serve the old bundle —
                # bail and let the next pass replay it
                admitted = False
            else:
                rep.client = client
                rep.client_inc = inc
                rep.pending_ctl = None
                rep.healthy = True
                self._healthy_gauge.set(sum(
                    1 for r in self._replicas.values() if r.healthy))
                self._cond.notify_all()
                admitted = True
        if not admitted:
            with contextlib.suppress(Exception):
                client.close()
            return False
        ttrace.event("resync", executor=rep.executor_id, incarnation=inc,
                     readmitted=True)
        logger.info("serving replica %d recovered (incarnation %d)",
                    rep.executor_id, inc)
        return True

    def _resync(self, client, timeout: float = 15.0) -> bool:
        """Order-fence a connection before re-admission: round-trip a
        nonce'd ping and drain the output queue until OUR pong surfaces.

        The map_fun consumes its input queue in order, so by the time this
        ping's pong is emitted every result of every abandoned earlier
        round (including earlier failed resync attempts' pongs — hence the
        nonce) has already been popped here and discarded.  Without this, a
        round abandoned mid-compute could leave its late results in the
        output queue and a later batch's exactly-count collection would
        hand them to the WRONG waiters."""
        self._resync_seq += 1
        nonce = f"{id(self)}:{self._resync_seq}"

        def _mine(x) -> bool:
            return (isinstance(x, dict) and x.get(CTL_KEY) == "pong"
                    and x.get("nonce") == nonce)

        deadline = _monotonic() + timeout
        got = client.infer_round([{CTL_KEY: "ping", "nonce": nonce}],
                                 self.qname_in, self.qname_out,
                                 wait=min(10.0, timeout))
        discarded = 0
        while not any(_mine(x) for x in got):
            discarded += len(got)
            if _monotonic() >= deadline:
                return False
            got = client.collect_results(self.qname_out, 64, wait=1.0)
        discarded += sum(1 for x in got if not _mine(x))
        if discarded:
            telemetry.counter("serve.resync_discarded_results").inc(discarded)
            logger.warning("discarded %d stale result(s) of abandoned rounds "
                           "while re-admitting a serving replica", discarded)
        return True

    # -- hot reload support --------------------------------------------------

    def drain(self, timeout: float = 60.0) -> None:
        """Block until no batch is queued or in flight (the gateway pauses
        the batcher first, so nothing new arrives meanwhile)."""
        deadline = _monotonic() + timeout
        with self._cond:
            while any(r.queue or r.inflight for r in self._replicas.values()):
                if self._stop:
                    return
                if _monotonic() >= deadline:
                    raise TimeoutError(
                        f"serving router did not drain within {timeout}s")
                self._cond.wait(0.2)

    def broadcast_ctl(self, item: dict, timeout: float = 60.0) -> dict[int, Any]:
        """Round-trip one control item through every healthy replica (call
        only paused + drained: the workers are idle, so their clients are
        free).  Returns {executor_id: ack}.  A replica that fails the round
        is marked unhealthy, and for a ``reload`` every replica that did
        NOT ack (failed here, or already out) gets the item pinned as its
        ``pending_ctl`` — recovery replays it before re-admission, so a
        replica that was out during a hot swap can never quietly rejoin
        serving the previous bundle."""
        acks: dict[int, Any] = {}
        with self._cond:
            targets = [r for r in self._replicas.values() if r.healthy]
        for rep in targets:
            try:
                client = self._client_for(rep)
                acks[rep.executor_id] = client.infer_round(
                    [item], self.qname_in, self.qname_out)[0]
            except Exception as e:  # noqa: BLE001 - replica fenced below
                logger.warning("control round to serving replica %d failed: "
                               "%s", rep.executor_id, e)
                with self._cond:
                    self._mark_unhealthy_locked(rep)
        if item.get(CTL_KEY) == "reload":
            with self._cond:
                late = [rep for rep in self._replicas.values()
                        if rep.executor_id not in acks and rep.healthy]
                for rep in self._replicas.values():
                    if rep.executor_id not in acks and not rep.healthy:
                        rep.pending_ctl = dict(item)
            # a replica re-admitted BETWEEN the healthy snapshot above and
            # now would otherwise serve the old bundle with nobody left to
            # replay the reload (recovery only scans unhealthy replicas) —
            # send it the round directly; its worker is idle (the batcher
            # is paused + drained for the whole broadcast)
            for rep in late:
                try:
                    client = self._client_for(rep)
                    acks[rep.executor_id] = client.infer_round(
                        [item], self.qname_in, self.qname_out)[0]
                except Exception as e:  # noqa: BLE001 - replica fenced below
                    logger.warning("late control round to serving replica "
                                   "%d failed: %s", rep.executor_id, e)
                    with self._cond:
                        self._mark_unhealthy_locked(rep)
                        rep.pending_ctl = dict(item)
        return acks

    def ctl_to(self, executor_ids, item: dict,
               timeout: float = 60.0) -> dict[int, Any]:
        """``broadcast_ctl`` restricted to a replica subset — the staged-
        rollout primitive (load the candidate on the canary cohort only;
        roll just the canaries back).  Same contract: call only paused +
        drained; a target that fails the round is fenced unhealthy with a
        ``reload`` item pinned as its ``pending_ctl`` so recovery replays
        it before re-admission."""
        acks: dict[int, Any] = {}
        with self._cond:
            targets = [r for eid in executor_ids
                       if (r := self._replicas.get(eid)) is not None
                       and r.healthy]
        for rep in targets:
            try:
                client = self._client_for(rep)
                acks[rep.executor_id] = client.infer_round(
                    [item], self.qname_in, self.qname_out)[0]
            except Exception as e:  # noqa: BLE001 - replica fenced below
                logger.warning("control round to serving replica %d failed: "
                               "%s", rep.executor_id, e)
                with self._cond:
                    self._mark_unhealthy_locked(rep)
        if item.get(CTL_KEY) == "reload":
            with self._cond:
                for eid in executor_ids:
                    rep = self._replicas.get(eid)
                    if rep is not None and eid not in acks:
                        rep.pending_ctl = dict(item)
        return acks

    def quarantine_for_reload(self, executor_id: int, item: dict) -> None:
        """Fence one replica out of routing until recovery has replayed
        ``item`` (a reload ctl) through it — the mixed-fleet guard: a
        replica whose promotion reload acked the WRONG bundle signature
        must not keep serving the stale bundle alongside the promoted
        fleet.  Its queued batches re-route to the survivors."""
        with self._cond:
            rep = self._replicas.get(executor_id)
            if rep is None:
                return
            rerouted = self._mark_unhealthy_locked(rep)
            rep.pending_ctl = dict(item)
            self._update_outstanding_locked()
            self._cond.notify_all()
        telemetry.counter("serve.promotion_laggards").inc()
        ttrace.event("promotion_laggard", executor=executor_id)
        for batch in rerouted:
            self.submit(batch, exclude=executor_id)

    # -- staged rollouts (gateway.rollout) -----------------------------------

    def set_rollout(self, canary_eids, *, traffic_every: int = 0,
                    mirror_every: int = 0, observer=None,
                    canary_ctl: dict | None = None,
                    shed_fn=None) -> None:
        """Enter a rollout split: replicas in ``canary_eids`` form the
        canary cohort, every ``traffic_every``-th batch routes to them,
        every ``mirror_every``-th primary batch is shadow-mirrored, and
        every batch outcome feeds ``observer`` (the rollout governor).
        ``canary_ctl`` is the candidate's reload item, remembered per
        cohort so a canary that dies and restarts mid-rollout is converged
        back onto the CANDIDATE bundle before it rejoins (see
        ``_try_recover``)."""
        eids = set(canary_eids)
        with self._cond:
            for rep in self._replicas.values():
                rep.cohort = "canary" if rep.executor_id in eids \
                    else "primary"
            self._batch_seq = 0
            self._mirror_seq = 0
            self._canary_every = max(0, int(traffic_every))
            self._mirror_every = max(0, int(mirror_every))
            self._observer = observer
            self._cohort_ctl = ({"canary": dict(canary_ctl)}
                                if canary_ctl else {})
            if shed_fn is not None:
                self._shed_fn = shed_fn
            self._cond.notify_all()

    def clear_rollout(self) -> None:
        """Leave the split (promotion or rollback both end here): every
        replica rejoins the primary cohort, traffic/mirror counters stop,
        the observer detaches."""
        with self._cond:
            for rep in self._replicas.values():
                rep.cohort = "primary"
            self._canary_every = 0
            self._mirror_every = 0
            self._observer = None
            self._cohort_ctl = {}
            self._cond.notify_all()

    def cohort_members(self, cohort: str) -> list[int]:
        with self._cond:
            return sorted(r.executor_id for r in self._replicas.values()
                          if r.cohort == cohort)

    def healthy_replicas(self) -> list[int]:
        with self._cond:
            return sorted(r.executor_id for r in self._replicas.values()
                          if r.healthy)

    def replica_loads(self) -> dict[int, int]:
        """Outstanding (queued + in-flight) batches per replica — the same
        numbers least-outstanding routing picks by, exposed for autoscaling
        victim selection and ``cluster.stats()`` so the policy and the
        router can never disagree on per-replica load."""
        with self._cond:
            return {r.executor_id: _load(r)
                    for r in self._replicas.values()}

    # -- elastic membership (cluster.resize) ---------------------------------

    def add_replica(self, executor_id: int) -> bool:
        """Admit a freshly-joined serving node into routing (scale-out).
        Idempotent; returns True when a new replica was added."""
        with self._cond:
            if self._stop or executor_id in self._replicas:
                return False
            rep = self._replicas[executor_id] = _Replica(executor_id)
            self._healthy_gauge.set(
                sum(1 for r in self._replicas.values() if r.healthy))
        rep.thread = threading.Thread(
            target=self._worker, args=(rep,), daemon=True,
            name=f"serve-replica-{executor_id}")
        rep.thread.start()
        ttrace.event("replica_added", executor=executor_id)
        logger.info("serving replica %d admitted into routing", executor_id)
        self._publish_registry()
        return True

    def retire_replica(self, executor_id: int, timeout: float = 60.0) -> bool:
        """Drain one replica out of routing (scale-in): no new batches are
        routed to it, its queued/in-flight batches finish normally, then it
        is removed.  If the drain times out (or the replica dies mid-drain),
        its never-attempted queued batches re-route to the survivors without
        spending their retry.  Returns True when the drain completed clean,
        False on timeout/forced reroute; idempotent for unknown ids."""
        with self._cond:
            rep = self._replicas.get(executor_id)
            if rep is None:
                return True
            rep.draining = True
            self._draining_gauge.set(
                sum(1 for r in self._replicas.values() if r.draining))
            self._cond.notify_all()
        deadline = _monotonic() + timeout
        leftovers: list[MicroBatch] = []
        clean = True
        with self._cond:
            while _load(rep) and not self._stop:
                if not rep.healthy:
                    # died mid-drain: its worker already rerouted the queue
                    # via _mark_unhealthy_locked; whatever is left is ours
                    break
                if _monotonic() >= deadline:
                    clean = False
                    break
                self._cond.wait(0.2)
            leftovers, rep.queue = rep.queue, []
            rep.retired = True
            self._replicas.pop(executor_id, None)
            self._healthy_gauge.set(
                sum(1 for r in self._replicas.values() if r.healthy))
            self._draining_gauge.set(
                sum(1 for r in self._replicas.values() if r.draining))
            self._update_outstanding_locked()
            self._cond.notify_all()
        for batch in leftovers:
            # never attempted on the retiring replica: re-route without
            # spending the batch's one retry
            self.submit(batch, exclude=executor_id)
        if rep.thread is not None:
            rep.thread.join(timeout=10.0)
        # Close the client only once the worker has actually exited: a
        # worker still blocked mid-``infer_round`` past the join (node
        # compute longer than drain_timeout + 10s) is about to COMPLETE
        # that batch — yanking its socket here would fail it and spend its
        # one retry for nothing.  The worker's retired-exit path owns the
        # teardown in that case.
        with self._cond:
            worker_live = rep.thread is not None and rep.thread.is_alive()
            client, rep.client = (None, rep.client) if worker_live \
                else (rep.client, None)
        if client is not None:
            with contextlib.suppress(Exception):
                client.close()
        ttrace.event("replica_retired", executor=executor_id,
                     clean=clean and not leftovers)
        logger.info("serving replica %d drained out of routing%s",
                    executor_id,
                    "" if clean else " (drain timed out; queue rerouted)")
        self._publish_registry()
        return clean and not leftovers

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        pending: list[MicroBatch] = []
        with self._cond:
            if self._stop:
                return
            self._stop = True
            for rep in self._replicas.values():
                pending.extend(rep.queue)
                rep.queue = []
            self._cond.notify_all()
        err = RuntimeError("serving gateway closed with the batch in flight")
        for batch in pending:
            self._batcher.fail_batch(batch, err)
        for rep in self._replicas.values():
            if rep.thread is not None:
                rep.thread.join(timeout=10.0)
            if rep.client is not None:
                with contextlib.suppress(Exception):
                    rep.client.close()
                rep.client = None
        with self._cond:
            embed_clients, self._embed_clients = self._embed_clients, {}
        for client in embed_clients.values():
            with contextlib.suppress(Exception):
                client.close()
        self._recovery.join(timeout=10.0)
        # retract this router's registry entry: a closed gateway must not
        # keep presenting healthy replicas in statz / post-failover replay
        coord = getattr(self._cluster, "coordinator", None)
        if coord is not None and hasattr(coord, "note_serving_replicas"):
            with contextlib.suppress(Exception):
                coord.note_serving_replicas(self._registry_name, [])
