"""tensorflowonspark_tpu.serving — low-latency online inference gateway.

The request/response subsystem layered on the existing cluster (the batch
stack's missing half — see ``gateway.py`` for the architecture):

- :class:`ServingGateway` — driver-side handle (``cluster.serve``);
- :class:`ReactorFrontend` — the single-thread ``selectors`` reactor TCP
  endpoint: pipelined multiplexed connections, zero-copy out-of-order
  responses, fast-fail backpressure (``frontend.py``);
- :class:`GatewayClient` / :class:`GatewayClientPool` — the pipelined
  remote caller (many id-tagged requests outstanding per socket) and a
  connection pool for closed-loop caller fleets;
- :class:`MicroBatcher` — dynamic micro-batching + admission control;
- :class:`ReplicaRouter` — least-outstanding routing, death retry,
  incarnation-fenced recovery;
- :func:`serving_loop` — the resident node map_fun.

Tuning knobs: ``TOS_SERVE_QUEUE``, ``TOS_SERVE_MAX_BATCH``,
``TOS_SERVE_MAX_DELAY_MS``, ``TOS_SERVE_TIMEOUT``,
``TOS_SERVE_HANDSHAKE_TIMEOUT``, ``TOS_SERVE_CONN_OUTSTANDING`` (see the
README table).
"""

from tensorflowonspark_tpu.serving.batcher import (  # noqa: F401
    MicroBatch,
    MicroBatcher,
    PendingPrediction,
    ServeClosed,
    ServeQueueFull,
    ServeTimeout,
)
from tensorflowonspark_tpu.serving.frontend import ReactorFrontend  # noqa: F401
from tensorflowonspark_tpu.serving.gateway import (  # noqa: F401
    CTL_KEY,
    GatewayClient,
    GatewayClientPool,
    LegacyGatewayClient,
    ServingGateway,
)
from tensorflowonspark_tpu.serving.loop import serving_loop  # noqa: F401
from tensorflowonspark_tpu.serving.router import ReplicaRouter  # noqa: F401
