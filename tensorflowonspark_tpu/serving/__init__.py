"""tensorflowonspark_tpu.serving — low-latency online inference gateway.

The request/response subsystem layered on the existing cluster (the batch
stack's missing half — see ``gateway.py`` for the architecture):

- :class:`ServingGateway` — driver-side handle (``cluster.serve``);
- :class:`ReactorFrontend` — the single-thread ``selectors`` reactor TCP
  endpoint: pipelined multiplexed connections, zero-copy out-of-order
  responses, fast-fail backpressure (``frontend.py``);
- :class:`GatewayClient` / :class:`GatewayClientPool` — the pipelined
  remote caller (many id-tagged requests outstanding per socket) and a
  connection pool for closed-loop caller fleets;
- :class:`MicroBatcher` — dynamic micro-batching + admission control
  (per-tenant weighted DRR queues, token-bucket rate limits, and the
  brownout shed ladder — ``tenancy.py``);
- :class:`ReplicaRouter` — least-outstanding routing, death retry,
  incarnation-fenced recovery, cohort-split rollout routing;
- :class:`RolloutGovernor` — shadow/canary staged rollouts with
  auto-promote / auto-rollback (``gateway.rollout``, ``rollout.py``);
- :func:`serving_loop` — the resident node map_fun.

Tuning knobs: ``TOS_SERVE_QUEUE``, ``TOS_SERVE_MAX_BATCH``,
``TOS_SERVE_MAX_DELAY_MS``, ``TOS_SERVE_TIMEOUT``,
``TOS_SERVE_HANDSHAKE_TIMEOUT``, ``TOS_SERVE_CONN_OUTSTANDING``,
``TOS_SERVE_CANARY_PCT``, ``TOS_SERVE_ROLLOUT_WINDOW_SECS``,
``TOS_SERVE_TENANT_RATE``, ``TOS_SERVE_SHED_LADDER`` (see the README
table).
"""

from tensorflowonspark_tpu.serving.batcher import (  # noqa: F401
    MicroBatch,
    MicroBatcher,
    PendingPrediction,
    ServeClosed,
    ServeQueueFull,
    ServeThrottled,
    ServeTimeout,
)
from tensorflowonspark_tpu.serving.frontend import ReactorFrontend  # noqa: F401
from tensorflowonspark_tpu.serving.gateway import (  # noqa: F401
    CTL_KEY,
    GatewayClient,
    GatewayClientPool,
    LegacyGatewayClient,
    ServingGateway,
)
from tensorflowonspark_tpu.serving.loop import serving_loop  # noqa: F401
from tensorflowonspark_tpu.serving.rollout import (  # noqa: F401
    RolloutGovernor,
    RolloutState,
)
from tensorflowonspark_tpu.serving.router import ReplicaRouter  # noqa: F401
from tensorflowonspark_tpu.serving.tenancy import TenantQueues  # noqa: F401
