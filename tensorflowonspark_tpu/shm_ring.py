"""Python face of the native shared-memory ring (``native/shm_ring.cc``).

Same-host data-plane fast path: where the reference moved every sample
through a ``multiprocessing`` manager proxy (TFManager queues, SURVEY.md
§3.2), feeder and node here share a lock-free SPSC byte ring in POSIX shm —
no sockets, no proxy, one memcpy each way.  ``DataClient`` uses it
automatically when it detects the node is on its own host (dataserver.py);
everything falls back to TCP when the native lib can't build.

Security note: items are pickled.  The ring is 0600 in /dev/shm under a
random name, same-user-same-host only — the same trust domain as the TCP
path *after* its HMAC handshake, so no authentication layer is needed here.

SPSC contract: one pusher process/thread, one popper.  The request/reply
pattern uses a pair of rings (c2s, s2c).
"""

from __future__ import annotations

import ctypes
import os
import pickle
import secrets
from typing import Any

_LIB = None


class RingUnavailable(RuntimeError):
    pass


class RingClosed(EOFError):
    pass


class RingTimeout(TimeoutError):
    pass


def _lib():
    global _LIB
    if _LIB is None:
        from tensorflowonspark_tpu.native.build import build_native_lib

        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native", "shm_ring.cc")
        try:
            lib = ctypes.CDLL(build_native_lib(src, "libshm_ring.so",
                                               ("-lrt",)))
        except Exception as e:  # noqa: BLE001 - no compiler / no shm
            raise RingUnavailable(str(e)) from e
        lib.tos_ring_open.restype = ctypes.c_void_p
        lib.tos_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_int]
        lib.tos_ring_push.restype = ctypes.c_int
        lib.tos_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.tos_ring_push2.restype = ctypes.c_int
        # payload arg is c_void_p (not c_char_p) so writable buffers
        # (bytearray/memoryview) pass without a bytes() conversion copy
        lib.tos_ring_push2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64, ctypes.c_void_p,
                                       ctypes.c_uint64, ctypes.c_int]
        lib.tos_ring_next_size.restype = ctypes.c_int64
        lib.tos_ring_next_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tos_ring_pop.restype = ctypes.c_int64
        lib.tos_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_int]
        for fn in ("tos_ring_close_write", "tos_ring_detach"):
            getattr(lib, fn).restype = None
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.tos_ring_is_closed.restype = ctypes.c_int
        lib.tos_ring_is_closed.argtypes = [ctypes.c_void_p]
        lib.tos_ring_size.restype = ctypes.c_uint64
        lib.tos_ring_size.argtypes = [ctypes.c_void_p]
        lib.tos_ring_capacity.restype = ctypes.c_uint64
        lib.tos_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.tos_ring_unlink.restype = ctypes.c_int
        lib.tos_ring_unlink.argtypes = [ctypes.c_char_p]
        _LIB = lib
    return _LIB


def available() -> bool:
    try:
        _lib()
        return True
    except RingUnavailable:
        return False


def make_ring_name(prefix: str = "tosring") -> str:
    return f"/{prefix}_{os.getpid()}_{secrets.token_hex(8)}"


class ShmRing:
    """One directional ring.  ``create()`` on the owning side, ``attach()``
    on the peer; the creator should ``unlink()`` at teardown."""

    def __init__(self, name: str, handle: int, owner: bool):
        self.name = name
        self._h = handle
        self._owner = owner

    @classmethod
    def create(cls, name: str | None = None,
               capacity: int = 64 * 1024 * 1024) -> "ShmRing":
        name = name or make_ring_name()
        lib = _lib()
        lib.tos_ring_unlink(name.encode())  # clear any stale segment
        h = lib.tos_ring_open(name.encode(), capacity, 1)
        if not h:
            raise RingUnavailable(f"cannot create ring {name}")
        return cls(name, h, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        h = _lib().tos_ring_open(name.encode(), 0, 0)
        if not h:
            raise RingUnavailable(f"cannot attach ring {name}")
        return cls(name, h, owner=False)

    # -- raw bytes -----------------------------------------------------------
    #
    # Wire format: every ring record is 1 flag byte + payload.  Messages
    # larger than the ring are transparently segmented (WHOLE | MORE… LAST);
    # SPSC ordering guarantees segments arrive contiguously.  NB: a timeout
    # raised mid-segmented-put leaves a partial message in flight — callers
    # must treat RingTimeout as fatal for the ring (downgrade transport).

    _WHOLE, _MORE, _LAST = b"\x00", b"\x01", b"\x02"

    @property
    def capacity(self) -> int:
        return _lib().tos_ring_capacity(self._h)

    def _push_record(self, flag: bytes, payload, timeout: float | None) -> None:
        """Push [flag byte | payload] as one ring record.  ``payload`` is any
        1-D byte buffer (bytes/bytearray/memoryview, read-only or not); the
        native push2 assembles the record inside the ring, so there is no
        flag-prepend join copy and no staging copy of the payload."""
        if not self._h:
            raise RingClosed("ring detached")
        import numpy as _np

        # np.frombuffer wraps ANY contiguous buffer (including read-only
        # memoryviews, which ctypes.from_buffer rejects) without copying and
        # exposes its address; the array reference keeps the memory alive
        # across the native call.
        arr = _np.frombuffer(payload, dtype=_np.uint8)
        rc = _lib().tos_ring_push2(
            self._h, flag, 1, ctypes.c_void_p(arr.ctypes.data), arr.size,
            -1 if timeout is None else int(timeout * 1000))
        if rc == 1:
            return
        if rc == 0:
            raise RingTimeout(f"push timed out after {timeout}s")
        if rc == -1:
            raise RingClosed("ring closed")
        raise ValueError(f"record of {arr.size + 1} bytes exceeds ring capacity")

    def put_bytes(self, data, timeout: float | None = 600.0) -> None:
        max_payload = self.capacity // 2  # headroom so a segment always fits
        view = memoryview(data)
        if len(view) <= max_payload:
            self._push_record(self._WHOLE, view, timeout)
            return
        for start in range(0, len(view), max_payload):
            seg = view[start:start + max_payload]
            last = start + max_payload >= len(view)
            self._push_record(self._LAST if last else self._MORE, seg, timeout)

    def put_buffers(self, buffers, timeout: float | None = 600.0) -> None:
        """Batched push: several buffers become ONE logical record stream,
        each copied straight from its own memory into the ring (no join).

        This is the ring's zero-copy framing path: a whole feed chunk —
        frame header + K row buffers — goes in as one segmented record
        instead of one pickled blob, so the only per-byte work is the
        memcpy into shared memory.  Same mid-stream-timeout caveat as
        ``put_bytes``: a RingTimeout leaves partial segments in flight and
        the ring must be abandoned.
        """
        views: list = []
        for b in buffers:
            v = memoryview(b)
            if v.ndim != 1 or v.itemsize != 1:
                v = v.cast("B")
            if len(v):
                views.append(v)
        if not views:
            self._push_record(self._WHOLE, b"", timeout)
            return
        max_payload = self.capacity // 2
        segs: list = []
        for v in views:
            for start in range(0, len(v), max_payload):
                segs.append(v[start:start + max_payload])
        for i, seg in enumerate(segs):
            last = i == len(segs) - 1
            flag = (self._WHOLE if last and i == 0
                    else self._LAST if last else self._MORE)
            self._push_record(flag, seg, timeout)

    def _pop_record(self, timeout: float | None) -> bytearray:
        if not self._h:
            raise RingClosed("ring detached")
        lib = _lib()
        tmo = -1 if timeout is None else int(timeout * 1000)
        size = lib.tos_ring_next_size(self._h, tmo)
        if size == -1:
            raise RingClosed("ring closed and drained")
        if size == -3:
            raise RingTimeout(f"pop timed out after {timeout}s")
        # Pop straight into a WRITABLE bytearray (no staging string buffer +
        # raw[:n] copy): downstream zero-copy unpickling hands views of this
        # blob to numpy, and arrays received over the ring must be writable
        # exactly like their TCP-delivered twins.
        buf = bytearray(int(size))
        carr = (ctypes.c_char * len(buf)).from_buffer(buf) if buf \
            else ctypes.create_string_buffer(0)
        # next_size succeeded ⇒ the record is already available to this (the
        # only) consumer; pop non-blockingly so the two calls can't stack up
        # to 2x the requested timeout per record.
        n = lib.tos_ring_pop(self._h, carr, int(size), 0)
        del carr  # release the exported buffer so `buf` is resizable again
        if n == -1:
            raise RingClosed("ring closed and drained")
        if n == -3:
            raise RingTimeout(f"pop timed out after {timeout}s")
        assert n == size, (n, size)
        return buf

    def get_bytes(self, timeout: float | None = 600.0) -> bytearray:
        """One logical record as a WRITABLE bytearray (segments joined)."""
        rec = self._pop_record(timeout)
        flag, payload = bytes(rec[:1]), rec[1:]
        if flag == self._WHOLE:
            return payload
        parts = [payload]
        while flag == self._MORE:
            rec = self._pop_record(timeout)
            flag, payload = bytes(rec[:1]), rec[1:]
            parts.append(payload)
        if flag != self._LAST:
            raise ValueError(f"corrupt ring stream: unexpected flag {flag!r}")
        return bytearray(b"").join(parts)

    # -- pickled objects -----------------------------------------------------

    def put(self, obj: Any, timeout: float | None = 600.0) -> None:
        self.put_bytes(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL), timeout)

    def get(self, timeout: float | None = 600.0) -> Any:
        return pickle.loads(self.get_bytes(timeout))

    # -- lifecycle -----------------------------------------------------------

    def close_write(self) -> None:
        """Producer hangs up; consumers drain then see RingClosed."""
        _lib().tos_ring_close_write(self._h)

    @property
    def pending_bytes(self) -> int:
        return _lib().tos_ring_size(self._h)

    def detach(self) -> None:
        if self._h:
            _lib().tos_ring_detach(self._h)
            self._h = 0

    def unlink(self) -> None:
        _lib().tos_ring_unlink(self.name.encode())

    def __del__(self):  # best-effort; explicit detach preferred
        try:
            self.detach()
        except Exception:  # toslint: allow-silent(__del__ at interpreter teardown: the lib handle may be gone and logging is unsafe here)
            pass
