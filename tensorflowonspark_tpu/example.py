"""Minimal ``tf.train.Example`` protobuf codec — hand-rolled, no TF, no
generated protos.

The reference converts Spark DataFrame rows to/from ``tf.train.Example``
records through the TensorFlow runtime (``dfutil.toTFExample``/
``fromTFExample``, ``tensorflowonspark/dfutil.py:~100-230``).  The Example
schema is tiny and frozen, so this module implements exactly that subset of
proto wire format:

    Example    { Features features = 1; }
    Features   { map<string, Feature> feature = 1; }
    Feature    { oneof kind { BytesList bytes_list = 1;
                              FloatList float_list = 2;
                              Int64List int64_list = 3; } }
    BytesList  { repeated bytes value = 1; }
    FloatList  { repeated float value = 1 [packed]; }
    Int64List  { repeated int64 value = 1 [packed]; }

Encode always writes packed primitives (canonical proto3 behaviour, and what
TF emits); decode accepts both packed and unpacked.
"""

from __future__ import annotations

import struct
from typing import Iterator

_F32 = struct.Struct("<f")


# -- varint primitives -------------------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64  # proto int64 negative values use 10-byte varints
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= 1 << 63 else value


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, _tag(field, 2))
    _write_varint(out, len(payload))
    out += payload


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) for each field in ``buf``."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            value = buf[pos : pos + n]
            if len(value) < n:
                raise ValueError("truncated length-delimited field")
            pos += n
        elif wire == 5:  # 32-bit
            value = buf[pos : pos + 4]
            pos += 4
        elif wire == 1:  # 64-bit
            value = buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


# -- feature encode ----------------------------------------------------------

def _encode_bytes_list(values: list[bytes]) -> bytes:
    out = bytearray()
    for v in values:
        _write_len_delimited(out, 1, v if isinstance(v, bytes) else str(v).encode())
    return bytes(out)


def _encode_float_list(values: list[float]) -> bytes:
    payload = b"".join(_F32.pack(float(v)) for v in values)
    out = bytearray()
    _write_len_delimited(out, 1, payload)  # packed
    return bytes(out)


def _encode_int64_list(values: list[int]) -> bytes:
    payload = bytearray()
    for v in values:
        _write_varint(payload, int(v))
    out = bytearray()
    _write_len_delimited(out, 1, bytes(payload))  # packed
    return bytes(out)


def encode_feature(values) -> bytes:
    """Encode one Feature from a homogeneous list (bytes/str, float, or int)."""
    if not isinstance(values, (list, tuple)):
        values = [values]
    import numpy as np

    # np.float32 etc. are not isinstance of Python float; normalize first so
    # type dispatch below can't silently truncate a float into the int branch.
    values = [v.item() if isinstance(v, np.generic) else v for v in values]
    out = bytearray()
    if values and isinstance(values[0], (bytes, bytearray, str)):
        _write_len_delimited(out, 1, _encode_bytes_list(list(values)))
    elif values and isinstance(values[0], float):
        _write_len_delimited(out, 2, _encode_float_list(list(values)))
    else:  # ints (and empty lists default to int64, matching TF)
        _write_len_delimited(out, 3, _encode_int64_list(list(values)))
    return bytes(out)


def encode_example(features: dict) -> bytes:
    """Encode {name: value(s)} into a serialized ``tf.train.Example``.

    Value types map the way the reference's ``toTFExample`` did
    (``dfutil.py:~100-160``): bytes/str → bytes_list, float → float_list,
    int/bool → int64_list; lists must be homogeneous.
    """
    fmap = bytearray()
    for name in sorted(features):  # deterministic output
        entry = bytearray()
        _write_len_delimited(entry, 1, name.encode("utf-8"))
        _write_len_delimited(entry, 2, encode_feature(features[name]))
        _write_len_delimited(fmap, 1, bytes(entry))
    out = bytearray()
    _write_len_delimited(out, 1, bytes(fmap))
    return bytes(out)


# -- feature decode ----------------------------------------------------------

def _decode_packed_or_repeated(body: bytes, wire_expect: int, parse) -> list:
    values = []
    for field, wire, value in _iter_fields(body):
        if field != 1:
            continue
        if wire == 2 and wire_expect != 2:  # packed encoding
            values.extend(parse_packed(value, wire_expect, parse))
        else:
            values.append(parse(value))
    return values


def parse_packed(payload: bytes, wire: int, parse) -> list:
    values = []
    pos = 0
    if wire == 0:
        while pos < len(payload):
            v, pos = _read_varint(payload, pos)
            values.append(parse(v))
    elif wire == 5:
        while pos < len(payload):
            values.append(parse(payload[pos : pos + 4]))
            pos += 4
    return values


def decode_feature(buf: bytes):
    """Decode one Feature into a Python list (bytes, float, or int)."""
    for field, _wire, value in _iter_fields(buf):
        if field == 1:  # bytes_list
            return [bytes(v) for f, w, v in _iter_fields(value) if f == 1]
        if field == 2:  # float_list
            return _decode_packed_or_repeated(value, 5, lambda b: _F32.unpack(b)[0])
        if field == 3:  # int64_list
            return _decode_packed_or_repeated(value, 0, lambda v: _signed64(v))
    return []


def decode_example(buf: bytes) -> dict:
    """Decode a serialized ``tf.train.Example`` into {name: list-of-values}."""
    features: dict = {}
    for field, _wire, value in _iter_fields(buf):
        if field != 1:
            continue
        for f, _w, entry in _iter_fields(value):
            if f != 1:
                continue
            name, feat = None, b""
            for ef, _ew, ev in _iter_fields(entry):
                if ef == 1:
                    name = ev.decode("utf-8")
                elif ef == 2:
                    feat = ev
            if name is not None:
                features[name] = decode_feature(feat)
    return features
