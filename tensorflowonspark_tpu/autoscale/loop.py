"""The driver-side autoscaling loop: stats -> policy -> governor -> resize.

One daemon thread per :class:`Autoscaler`: every ``tick_secs`` it samples
``cluster.stats(window)``, asks the policy for a desired feedable-node
count, runs the :class:`~.policy.HysteresisGovernor` (cooldown, K-window
scale-in evidence, min/max bounds), and — when the governor fires —
calls ``cluster.resize(target)``.  Every decision that is not a plain
hold is flight-recorded (``scale_out`` / ``scale_in`` / ``cooldown_hold``)
with the stats snapshot that justified it, so a postmortem can replay why
the fleet moved; the same trail lands in ``run_report.json``'s
``autoscale`` block.

This is the closed loop the ROADMAP calls "follows traffic": ISSUE 8
built the live signals (rolling qps/p50/p99, serve queue depth, per-node
feed occupancy), ``cluster.resize`` (this ISSUE) built the actuator, and
this module is the controller between them — the whole-cluster analogue
of tf.data's occupancy-driven autotuning (Murray et al., 2101.12127) in
the spirit of the TF system paper's dynamic worker sets (Abadi et al.,
1605.08695).
"""

from __future__ import annotations

import logging
import threading
from tensorflowonspark_tpu.utils.locks import tos_named_lock
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.autoscale.policy import (
    HysteresisGovernor,
    Policy,
    QueueDepthBandPolicy,
)
from tensorflowonspark_tpu.telemetry import trace as ttrace
from tensorflowonspark_tpu.utils.envtune import env_float, env_int

logger = logging.getLogger(__name__)

# Decision-trail bound for the run report: at the default 5s tick this is
# well over an hour of non-hold decisions — plenty for a postmortem, and a
# week-long serving job cannot grow the driver's heap with it.
_DECISION_LOG_CAP = 1024


def _snapshot(stats: dict) -> dict:
    """The compact justification attached to every decision's flight event:
    the exact headline signals the policies read, nothing else."""
    serving = stats.get("serving") or {}
    out = {k: serving.get(k) for k in ("qps", "p50_ms", "p99_ms",
                                       "queue_depth", "inflight_batches",
                                       "replicas_healthy",
                                       "replicas_draining")}
    return {k: v for k, v in out.items() if v is not None}


class Autoscaler:
    """Telemetry-driven policy loop over ``cluster.resize`` (start it via
    ``cluster.autoscale(...)``, which also honours the ``TOS_AUTOSCALE``
    kill switch and stops the loop at shutdown).

    Defaults come from the ``TOS_AUTOSCALE_*`` knobs: bounds
    (``_MIN``/``_MAX``), cadence (``_TICK_SECS``) and the post-action
    cooldown (``_COOLDOWN_SECS``); ``scale_in_ticks`` is the K-consecutive
    under-target windows a shrink must earn.  ``window`` (default
    ``max(2 x tick, 5)``) is the rolling-stats horizon each tick reads.
    """

    def __init__(self, cluster, policy: Policy | None = None, *,
                 tier: str = "nodes",
                 min_nodes: int | None = None, max_nodes: int | None = None,
                 tick_secs: float | None = None,
                 cooldown_secs: float | None = None,
                 scale_in_ticks: int = 3,
                 window: float | None = None,
                 drain_timeout: float | None = None):
        if tier not in ("nodes", "ingest"):
            raise ValueError(f"tier must be 'nodes' or 'ingest', got {tier!r}")
        self._cluster = cluster
        # tier="ingest" scales the DATA-SERVICE pool (cluster.resize_ingest
        # over num_ingest) on the feed starvation signals; the default tier
        # scales the trainer/serving fleet exactly as before
        self.tier = tier
        if policy is None:
            if tier == "ingest":
                from tensorflowonspark_tpu.autoscale.policy import (
                    IngestBacklogPolicy,
                )

                policy = IngestBacklogPolicy()
            else:
                policy = QueueDepthBandPolicy()
        self.policy = policy
        self.tick_secs = (float(tick_secs) if tick_secs is not None
                          else env_float("TOS_AUTOSCALE_TICK_SECS", 5.0))
        cooldown = (float(cooldown_secs) if cooldown_secs is not None
                    else env_float("TOS_AUTOSCALE_COOLDOWN_SECS", 30.0))
        self.governor = HysteresisGovernor(
            min_nodes=(int(min_nodes) if min_nodes is not None
                       else env_int("TOS_AUTOSCALE_MIN", 1)),
            max_nodes=(int(max_nodes) if max_nodes is not None
                       else env_int("TOS_AUTOSCALE_MAX", 8)),
            cooldown_secs=cooldown,
            scale_in_ticks=scale_in_ticks)
        self.window = (float(window) if window is not None
                       else max(2.0 * self.tick_secs, 5.0))
        self._drain_timeout = drain_timeout
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = tos_named_lock("autoscale._lock")
        self._decisions: list[dict] = []
        self._counts = {"scale_out": 0, "scale_in": 0, "cooldown_hold": 0,
                        "resize_failures": 0}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        logger.info("autoscaler up: policy=%s bounds=[%d, %d] tick=%.1fs "
                    "cooldown=%.1fs scale_in_ticks=%d",
                    self.policy.name, self.governor.min_nodes,
                    self.governor.max_nodes, self.tick_secs,
                    self.governor.cooldown_secs, self.governor.scale_in_ticks)
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- the loop ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_secs):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive a bad tick
                logger.warning("autoscaler tick failed", exc_info=True)

    def tick(self) -> dict | None:
        """One decision cycle (public so tests and benches can drive the
        loop synchronously).  Returns the decision record for every
        non-hold outcome, else None."""
        coord = getattr(self._cluster, "coordinator", None)
        if coord is not None and getattr(coord, "crashed", None) \
                and coord.crashed():
            # control plane mid-failover (ISSUE 13): the stats streams were
            # wiped with the crash — a decision made against that vacuum
            # would scale on ghosts.  Hold; the journal-recovered epoch's
            # fresh windows feed the next tick.
            return None
        stats = self._cluster.stats(self.window)
        current = (self._cluster.num_ingest() if self.tier == "ingest"
                   else self._cluster.num_feedable())
        desired = self.policy.desired(stats, current)
        action, target = self.governor.decide(desired, current,
                                              time.monotonic())
        if action == "hold":
            return None
        snapshot = _snapshot(stats)
        if self.tier == "ingest":
            block = stats.get("ingest") or {}
            snapshot["starved_trainers"] = block.get("starved_trainers")
            snapshot["cache_hit_rate"] = block.get("cache_hit_rate")
        decision = {"action": action, "current": current,
                    "desired": desired, "target": target, "tier": self.tier,
                    "policy": self.policy.name, "stats": snapshot}
        # flight-record EVERY decision with its justification — including
        # cooldown holds, which are where "why didn't it scale?" lives
        ttrace.event(action, current=current, desired=desired,
                     target=target, policy=self.policy.name, **snapshot)
        with self._lock:
            self._counts[action] = self._counts.get(action, 0) + 1
            self._decisions.append(decision)
            del self._decisions[:-_DECISION_LOG_CAP]
        telemetry.counter(f"autoscale.{action}_total").inc()
        if action == "cooldown_hold":
            return decision
        logger.info("autoscaler: %s %d -> %d (desired %d, policy %s, %s)",
                    action, current, target, desired, self.policy.name,
                    snapshot)
        telemetry.gauge("autoscale.target_nodes" if self.tier == "nodes"
                        else "autoscale.target_ingest_workers").set(target)
        try:
            resize = (self._cluster.resize_ingest if self.tier == "ingest"
                      else self._cluster.resize)
            decision["resize"] = resize(
                target, drain_timeout=self._drain_timeout)
        except Exception as e:  # noqa: BLE001 - keep the loop alive; next tick retries
            with self._lock:
                self._counts["resize_failures"] += 1
            decision["error"] = str(e)
            logger.warning("autoscaler resize to %d failed: %s", target, e)
        return decision

    # -- reporting -----------------------------------------------------------

    def decisions(self) -> list[dict]:
        with self._lock:
            return [dict(d) for d in self._decisions]

    def report(self) -> dict:
        """The run report's per-policy autoscale summary."""
        with self._lock:
            counts = dict(self._counts)
            trail = [dict(d) for d in self._decisions]
        return {"policy": self.policy.describe(),
                "tier": self.tier,
                "bounds": [self.governor.min_nodes, self.governor.max_nodes],
                "tick_secs": self.tick_secs,
                "cooldown_secs": self.governor.cooldown_secs,
                "scale_in_ticks": self.governor.scale_in_ticks,
                "counts": counts,
                "decisions": trail}
