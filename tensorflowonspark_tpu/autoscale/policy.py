"""Autoscaling policies + the hysteresis governor.

A *policy* maps one ``cluster.stats(window)`` snapshot to a desired node
count — pure, stateless, unit-testable with literal dicts.  The
*governor* owns the state machine that keeps a policy from flapping:
cooldown after any action, K-consecutive-windows evidence before a
scale-in, min/max clamping.  The :class:`~tensorflowonspark_tpu.autoscale.
loop.Autoscaler` composes the two over a live cluster.

The split mirrors tf.data's autotuning (Murray et al., 2101.12127): the
signal model (occupancy, latency) is separate from the actuation schedule,
so policies stay one-screen readable and the anti-flap logic is tested
once.  Lineage for the signals themselves: ``serving.queue_depth`` is the
gateway's admission-queue occupancy, ``serving.p99_ms`` the rolling
request percentile, per-node ``feed.rows_consumed`` rates the training
throughput — all from ``cluster.stats()`` (ISSUE 8).
"""

from __future__ import annotations

import math
from typing import Any


def _serving(stats: dict) -> dict:
    return stats.get("serving") or {}


class Policy:
    """Base: map a rolling-stats snapshot to a desired feedable-node count.

    ``desired(stats, current)`` returns the count the policy would run at
    — the governor (not the policy) owns clamping, cooldown, and scale-in
    hysteresis, so policies are free to answer naively every tick.
    """

    name = "policy"

    def desired(self, stats: dict, current: int) -> int:
        raise NotImplementedError

    def describe(self) -> dict[str, Any]:
        """One-line parameter dump for the run report / flight events."""
        return {"name": self.name}


class QueueDepthBandPolicy(Policy):
    """Hold the serving admission-queue depth inside a band.

    Depth above ``high`` means requests are waiting on capacity — add
    ``step`` node(s); depth at/below ``low`` means the fleet is idle
    enough to shrink by one.  The gateway queue is the single earliest
    congestion signal (it grows the moment replicas stop keeping up,
    before latency percentiles move), which makes this the default policy.
    """

    name = "queue_depth_band"

    def __init__(self, low: float = 1.0, high: float = 16.0, step: int = 1):
        if low < 0 or high <= low:
            raise ValueError("need 0 <= low < high")
        self.low = float(low)
        self.high = float(high)
        self.step = max(1, int(step))

    def desired(self, stats: dict, current: int) -> int:
        depth = _serving(stats).get("queue_depth")
        if depth is None:
            return current
        if depth > self.high:
            return current + self.step
        if depth <= self.low:
            return current - 1
        return current

    def describe(self) -> dict[str, Any]:
        return {"name": self.name, "low": self.low, "high": self.high,
                "step": self.step}


class LatencyCeilingPolicy(Policy):
    """Hold rolling request p99 under a ceiling.

    p99 above ``ceiling_ms`` adds ``step`` node(s); p99 below
    ``relax_frac * ceiling_ms`` (default 30%) with traffic present shrinks
    by one.  Quiet windows (no qps, no percentile) leave the count alone —
    "no traffic" is the queue-depth/rows policies' call, not a latency
    signal.
    """

    name = "latency_ceiling"

    def __init__(self, ceiling_ms: float, relax_frac: float = 0.3,
                 step: int = 1):
        if ceiling_ms <= 0 or not 0 < relax_frac < 1:
            raise ValueError("need ceiling_ms > 0 and 0 < relax_frac < 1")
        self.ceiling_ms = float(ceiling_ms)
        self.relax_frac = float(relax_frac)
        self.step = max(1, int(step))

    def desired(self, stats: dict, current: int) -> int:
        serving = _serving(stats)
        p99 = serving.get("p99_ms")
        if p99 is None or not serving.get("qps"):
            return current
        if p99 > self.ceiling_ms:
            return current + self.step
        if p99 < self.relax_frac * self.ceiling_ms:
            return current - 1
        return current

    def describe(self) -> dict[str, Any]:
        return {"name": self.name, "ceiling_ms": self.ceiling_ms,
                "relax_frac": self.relax_frac, "step": self.step}


class RowsPerNodeFloorPolicy(Policy):
    """Shrink-to-fit for training feeds: keep per-node consumption above a
    floor.

    Sums the per-node ``counter`` rates (default ``feed.rows_consumed``,
    the rows/s each node's feed actually popped) and answers the largest
    node count that keeps rows/s-per-node >= ``min_rows_per_sec`` — i.e.
    it only ever shrinks an over-provisioned feed, one node per action
    (the governor rate-limits anyway).  Driver-fed training throughput is
    bounded by the driver, so "add nodes" is deliberately not this
    policy's call; compose it with a queue/latency policy when serving
    shares the cluster.
    """

    name = "rows_per_node_floor"

    def __init__(self, min_rows_per_sec: float,
                 counter: str = "feed.rows_consumed"):
        if min_rows_per_sec <= 0:
            raise ValueError("need min_rows_per_sec > 0")
        self.min_rows_per_sec = float(min_rows_per_sec)
        self.counter = counter

    def desired(self, stats: dict, current: int) -> int:
        total = 0.0
        seen = False
        for key, stream in (stats.get("streams") or {}).items():
            if key == "driver":
                continue
            rate = (stream.get("rates") or {}).get(self.counter)
            if rate is not None:
                seen = True
                total += rate
        if not seen:
            return current
        fit = int(math.floor(total / self.min_rows_per_sec))
        # shrink-to-fit only, one node at a time
        return min(current, max(1, fit, current - 1))

    def describe(self) -> dict[str, Any]:
        return {"name": self.name,
                "min_rows_per_sec": self.min_rows_per_sec,
                "counter": self.counter}


class IngestBacklogPolicy(Policy):
    """Scale the DATA-SERVICE tier on trainer starvation (the disaggregated
    ingest tier's satellite policy).

    Reads the ``ingest`` stats block (``cluster.stats()``): any starved
    trainer — a trainer whose prefetch-queue gauge reads empty — means
    decode capacity is behind consumption, so add ``step`` worker(s).
    With nobody starved and the pool's decode throughput per worker under
    ``min_rows_per_sec`` (decode capacity idling), shrink by one.  The
    signals are exactly the ``feed.queue_depth``/starvation gauges the
    node-local feed already exported — the tier reuses them, it does not
    invent new ones.  Drive it with ``cluster.autoscale(policy=...,
    tier="ingest")`` so the governor actuates ``cluster.resize_ingest``.
    """

    name = "ingest_backlog"

    def __init__(self, min_rows_per_sec: float = 1.0, step: int = 1):
        if min_rows_per_sec <= 0:
            raise ValueError("need min_rows_per_sec > 0")
        self.min_rows_per_sec = float(min_rows_per_sec)
        self.step = max(1, int(step))

    def desired(self, stats: dict, current: int) -> int:
        block = stats.get("ingest") or {}
        workers = block.get("workers") or {}
        if not workers:
            return current  # no live signal yet: never scale on a vacuum
        rates = [w.get("forwarded_rows_per_s") or w.get("rows_per_s") or 0.0
                 for w in workers.values()]
        # An empty trainer queue alone cannot distinguish "starving behind
        # decode" from "idle between train() calls" (both read depth 0, and
        # an idle feed still polls): only starvation WITH the pool actually
        # decoding is scale-out evidence — an idle cluster instead shrinks
        # through the under-floor branch below until the governor's min.
        if (block.get("starved_trainers") or 0) > 0 and any(
                r > 0.0 for r in rates):
            return current + self.step
        if rates and all(r < self.min_rows_per_sec for r in rates):
            return current - 1
        return current

    def describe(self) -> dict[str, Any]:
        return {"name": self.name,
                "min_rows_per_sec": self.min_rows_per_sec,
                "step": self.step}


class HysteresisGovernor:
    """The anti-flap state machine between a policy and ``cluster.resize``.

    Rules, in order:

    - the desired count is clamped to ``[min_nodes, max_nodes]``;
    - after ANY action, a ``cooldown_secs`` window holds further actions
      (``cooldown_hold``) — resizes are not free, and the stats window
      needs time to reflect the new capacity;
    - scale-OUT fires on a single over-target window (congestion is
      urgent);
    - scale-IN needs ``scale_in_ticks`` CONSECUTIVE under-target windows
      (idleness must prove itself) — one over-or-at-target window resets
      the evidence, and windows sampled inside a cooldown don't count
      (the evidence must be gathered entirely after the fleet settled),
      so a load oscillating around the threshold never flaps the fleet.

    Pure and clock-free: callers pass ``now`` (monotonic seconds), so unit
    tests drive it with literal timestamps.
    """

    def __init__(self, min_nodes: int = 1, max_nodes: int = 8,
                 cooldown_secs: float = 30.0, scale_in_ticks: int = 3):
        if not 1 <= min_nodes <= max_nodes:
            raise ValueError("need 1 <= min_nodes <= max_nodes")
        if cooldown_secs < 0 or scale_in_ticks < 1:
            raise ValueError("need cooldown_secs >= 0 and scale_in_ticks >= 1")
        self.min_nodes = int(min_nodes)
        self.max_nodes = int(max_nodes)
        self.cooldown_secs = float(cooldown_secs)
        self.scale_in_ticks = int(scale_in_ticks)
        self._cooldown_until = float("-inf")
        self._under_streak = 0

    def decide(self, desired: int, current: int, now: float) -> tuple[str, int]:
        """(action, target): action is ``hold`` / ``cooldown_hold`` /
        ``scale_out`` / ``scale_in``; target is the count to resize to
        (== current unless the action scales)."""
        desired = max(self.min_nodes, min(self.max_nodes, int(desired)))
        if desired == current:
            self._under_streak = 0
            return ("hold", current)
        if now < self._cooldown_until:
            # Windows inside the cooldown are NOT shrink evidence: the
            # fleet just changed and the stats window is still settling —
            # counting them would let a scale-in fire on the first tick
            # after a scale-out's cooldown expires, oscillating the fleet
            # with period == cooldown_secs on bursty load.
            self._under_streak = 0
            return ("cooldown_hold", current)
        if desired < current:
            self._under_streak += 1
        else:
            self._under_streak = 0
        if desired > current:
            self._cooldown_until = now + self.cooldown_secs
            return ("scale_out", desired)
        if self._under_streak >= self.scale_in_ticks:
            self._under_streak = 0
            self._cooldown_until = now + self.cooldown_secs
            return ("scale_in", desired)
        return ("hold", current)

    def cooling_down(self, now: float) -> bool:
        return now < self._cooldown_until
