"""Elastic autoscaling: the policy half of ``cluster.resize``.

The mechanism (grow/shrink/rebalance a live cluster) lives in
``cluster.TPUCluster.resize``; this package supplies what drives it:

- :mod:`~tensorflowonspark_tpu.autoscale.policy` — pure stats->count
  policies (:class:`QueueDepthBandPolicy`, :class:`LatencyCeilingPolicy`,
  :class:`RowsPerNodeFloorPolicy`, the data-service tier's
  :class:`IngestBacklogPolicy`) and the anti-flap
  :class:`HysteresisGovernor`;
- :mod:`~tensorflowonspark_tpu.autoscale.loop` — the
  :class:`Autoscaler` thread composing them over a live cluster
  (``cluster.autoscale(...)`` starts one).
"""

from tensorflowonspark_tpu.autoscale.loop import Autoscaler
from tensorflowonspark_tpu.autoscale.policy import (
    HysteresisGovernor,
    IngestBacklogPolicy,
    LatencyCeilingPolicy,
    Policy,
    QueueDepthBandPolicy,
    RowsPerNodeFloorPolicy,
)

__all__ = [
    "Autoscaler",
    "HysteresisGovernor",
    "IngestBacklogPolicy",
    "LatencyCeilingPolicy",
    "Policy",
    "QueueDepthBandPolicy",
    "RowsPerNodeFloorPolicy",
]
