"""Cluster-wide metric aggregation and human/machine-readable reports.

The coordinator keeps one raw snapshot per node (replaced key-by-key as
heartbeat deltas arrive); this module turns ``{node_key: snapshot}`` into

- an **aggregated snapshot** (``aggregate_snapshots``): counters summed
  across nodes, histogram digests merged, cluster-wide percentiles pooled
  from the nodes' shipped samples, per-node detail preserved under
  ``"nodes"`` — the ``cluster.metrics()`` payload;
- a **text report** (``debug_dump``) for eyeballs and bug reports;
- an **end-of-run JSON run report** (``build_run_report``), written next to
  the job's checkpoints/logs at shutdown — throughput, restarts, span
  percentiles, per-node detail (the tf.data-paper "built-in per-stage
  counters" idea applied run-level).
"""

from __future__ import annotations

import json
import time
from typing import Any

from tensorflowonspark_tpu.telemetry.registry import percentile_of

#: Percentiles rendered for every merged histogram.
PERCENTILES = (50.0, 90.0, 99.0)


def aggregate_snapshots(nodes: dict[str, dict]) -> dict:
    """Merge per-node snapshots into one cluster view.

    ``nodes`` maps a node key (stringified executor id, or ``"driver"``) to
    a registry snapshot (``{"counters": ..., "gauges": ...,
    "histograms": {name: digest [+ "recent" samples]}}``).  Counter values
    are cumulative per process, so the aggregate is their plain sum; gauges
    stay per-node (a cluster-summed gauge is rarely meaningful); histogram
    digests merge exactly (count/sum/min/max) and percentiles are estimated
    from the pooled per-node samples.
    """
    counters: dict[str, int] = {}
    hists: dict[str, dict] = {}
    samples: dict[str, list[float]] = {}
    for snap in nodes.values():
        for name, value in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + value
        for name, d in (snap.get("histograms") or {}).items():
            agg = hists.setdefault(name, {"count": 0, "sum": 0.0,
                                          "min": None, "max": None})
            agg["count"] += int(d.get("count") or 0)
            agg["sum"] += float(d.get("sum") or 0.0)
            for key, pick in (("min", min), ("max", max)):
                v = d.get(key)
                if v is not None:
                    agg[key] = v if agg[key] is None else pick(agg[key], v)
            samples.setdefault(name, []).extend(d.get("recent") or ())
    for name, agg in hists.items():
        pool = sorted(samples.get(name) or ())
        for q in PERCENTILES:
            agg[f"p{q:g}"] = percentile_of(pool, q)
        if agg["count"]:
            agg["mean"] = agg["sum"] / agg["count"]
    return {"nodes": _strip_samples(nodes), "counters": counters,
            "histograms": hists}


def _strip_samples(nodes: dict[str, dict]) -> dict[str, dict]:
    """Per-node detail without the raw sample lists (digest-only)."""
    out: dict[str, dict] = {}
    for key, snap in nodes.items():
        hists = {name: {k: v for k, v in d.items() if k != "recent"}
                 for name, d in (snap.get("histograms") or {}).items()}
        out[key] = {"counters": dict(snap.get("counters") or {}),
                    "gauges": dict(snap.get("gauges") or {}),
                    "histograms": hists}
    return out


def debug_dump(aggregated: dict) -> str:
    """Render an ``aggregate_snapshots`` result as a text report."""
    lines: list[str] = ["== cluster metrics =="]
    counters = aggregated.get("counters") or {}
    if counters:
        lines.append("-- counters (cluster total) --")
        width = max(len(n) for n in counters)
        for name in sorted(counters):
            lines.append(f"  {name:<{width}}  {counters[name]}")
    hists = aggregated.get("histograms") or {}
    if hists:
        lines.append("-- spans (cluster merged) --")
        for name in sorted(hists):
            d = hists[name]
            parts = [f"count={d.get('count')}"]
            if d.get("count"):
                parts.append(f"mean={d.get('mean'):.6g}")
                parts.append(f"min={d.get('min'):.6g}")
                parts.append(f"max={d.get('max'):.6g}")
                for q in PERCENTILES:
                    v = d.get(f"p{q:g}")
                    if v is not None:
                        parts.append(f"p{q:g}={v:.6g}")
            lines.append(f"  {name}  " + " ".join(parts))
    for key in sorted(aggregated.get("nodes") or {}):
        snap = aggregated["nodes"][key]
        lines.append(f"-- node {key} --")
        for kind in ("counters", "gauges"):
            for name in sorted(snap.get(kind) or {}):
                lines.append(f"  {name} = {snap[kind][name]}")
        for name in sorted(snap.get("histograms") or {}):
            d = snap["histograms"][name]
            lines.append(f"  {name} count={d.get('count')} sum={d.get('sum')}")
    return "\n".join(lines)


def _gauge_max(aggregated: dict, name: str):
    """Largest per-node value of a gauge, or None when no node reports it
    (gauges stay per-node in the aggregate; for the serving frontend's
    connection/outstanding gauges the driver is the only reporter, so max
    IS the value)."""
    vals = [snap["gauges"][name]
            for snap in (aggregated.get("nodes") or {}).values()
            if name in (snap.get("gauges") or {})]
    return max(vals) if vals else None


def _hist_ms(aggregated: dict, name: str, q: str):
    """A merged histogram's percentile in milliseconds, or None."""
    v = ((aggregated.get("histograms") or {}).get(name) or {}).get(q)
    return round(v * 1e3, 3) if v is not None else None


def build_run_report(aggregated: dict, *, wall_secs: float | None = None,
                     extras: dict | None = None) -> dict:
    """End-of-run JSON document: the aggregate + derived headline numbers.

    Headlines are best-effort derivations from well-known counter names —
    absent instrumentation just omits them (``None``), it never fails the
    report.
    """
    counters = aggregated.get("counters") or {}
    rx_bytes = counters.get("dataplane.rx_bytes")
    ingest_bytes = counters.get("ingest.bytes_read")
    serve_requests = counters.get("serve.requests_total")
    serving = None
    if serve_requests:
        # serving headlines: gateway qps/latency plus the reactor
        # frontend's health next to them (connections, pipelining depth,
        # frame counts, loop lag) — the wire endpoint is a single thread,
        # so its loop-lag p99 is the first thing to check when TCP p99
        # diverges from in-process
        serving = {
            "requests_total": serve_requests,
            "qps": (round(serve_requests / wall_secs, 1)
                    if wall_secs else None),
            "request_p50_ms": _hist_ms(aggregated, "serve.request_secs", "p50"),
            "request_p99_ms": _hist_ms(aggregated, "serve.request_secs", "p99"),
            "frontend_frames_in": counters.get("serve.frontend.frames_in"),
            "frontend_frames_out": counters.get("serve.frontend.frames_out"),
            "frontend_connections_open": _gauge_max(
                aggregated, "serve.frontend.connections"),
            "frontend_outstanding_requests": _gauge_max(
                aggregated, "serve.frontend.outstanding"),
            "frontend_loop_lag_p99_ms": _hist_ms(
                aggregated, "serve.frontend.loop_lag_secs", "p99"),
        }
    ingest_tier = None
    fwd_rows = counters.get("ingest.rows_forwarded")
    cache_hits = counters.get("ingest.cache_hits", 0)
    cache_misses = counters.get("ingest.cache_misses", 0)
    if fwd_rows or cache_hits or cache_misses:
        # the disaggregated data-service tier ran (or the chunk cache was
        # live node-locally): the run's ingest postmortem block
        ingest_tier = {
            "chunks_forwarded": counters.get("ingest.chunks_forwarded"),
            "rows_forwarded": fwd_rows,
            "forwarded_mb": (
                round(counters["ingest.bytes_forwarded"] / 1e6, 3)
                if counters.get("ingest.bytes_forwarded") else None),
            "cache_hits": cache_hits,
            "cache_misses": cache_misses,
            "cache_hit_rate": (
                round(cache_hits / (cache_hits + cache_misses), 4)
                if (cache_hits + cache_misses) else None),
            "cache_evictions": counters.get("ingest.cache_evictions", 0),
            "forward_errors": counters.get("ingest.forward_errors", 0),
        }
    collective = None
    if counters.get("collective.rounds_total") \
            or counters.get("collective.formations_total") \
            or counters.get("collective.evictions_total"):
        # the sync-training postmortem block: how many rounds/formations
        # ran, how often the group aborted and re-formed, and the gray-
        # failure tallies (suspicion votes filed, quorum evictions,
        # probation readmissions) — the first place to look when a sync
        # run degraded to W-1 or thrashed
        collective = {
            "rounds_total": counters.get("collective.rounds_total", 0),
            "formations_total": counters.get(
                "collective.formations_total", 0),
            "reforms_total": counters.get("collective.reforms_total", 0),
            "aborts_total": counters.get("collective.aborts_total", 0),
            "suspects_total": counters.get("collective.suspects_total", 0),
            "evictions_total": counters.get(
                "collective.evictions_total", 0),
            "readmits_total": counters.get("collective.readmits_total", 0),
            "form_p50_ms": _hist_ms(aggregated, "collective.form_secs",
                                    "p50"),
            "all_reduce_p50_ms": _hist_ms(
                aggregated, "collective.all_reduce_secs", "p50"),
        }
    report: dict[str, Any] = {
        "schema": "tos-run-report-v1",
        "written_at": time.time(),
        "wall_secs": wall_secs,
        "throughput_mb_per_s": (
            round(rx_bytes / wall_secs / 1e6, 3)
            if rx_bytes and wall_secs else None),
        # DIRECT-mode twin of the driver-pump number: bytes the nodes read
        # straight from storage (cluster aggregate), which never transit
        # the data plane and so never land in dataplane.rx_bytes
        "ingest_mb_per_s": (
            round(ingest_bytes / wall_secs / 1e6, 3)
            if ingest_bytes and wall_secs else None),
        "records_ingested": counters.get("ingest.records_read"),
        "ingest_tier": ingest_tier,
        "rows_fed": counters.get("dataplane.rows_in"),
        "rows_consumed": counters.get("feed.rows_consumed"),
        "serving": serving,
        "collective": collective,
        "restarts_total": counters.get("elastic.restarts_total", 0),
        "faults_injected": counters.get("faultinject.injected_total", 0),
        "counters": counters,
        "histograms": aggregated.get("histograms") or {},
        "nodes": aggregated.get("nodes") or {},
    }
    if extras:
        report.update(extras)
    return report


def write_run_report(path: str, report: dict) -> str:
    """Write the report JSON (pretty, stable key order) and return ``path``."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    return path
