"""Process-local metrics primitives — stdlib-only, hot-path-safe.

The data plane meters every frame it sends (dataserver.py), so the
primitives here are designed around one constraint: an increment on the hot
path must cost nanoseconds and take **no lock**.

- ``Counter``: per-thread cells.  Each thread mutates only its own dict
  slot (``cells[tid] = cells.get(tid, 0) + n`` — the owning thread is the
  only writer of that key, and dict item assignment is atomic under the
  GIL), so ``inc()`` is lock-free AND exact: no increment can be lost to a
  read-modify-write race the way a shared ``self._value += n`` could.
  ``value()`` sums the cells.
- ``Gauge``: last-write-wins float (a single attribute store is atomic).
- ``Histogram``: bounded reservoir (Algorithm R, deterministic per-name
  seed) + running count/sum/min/max digest, guarded by a small lock —
  histograms meter *spans* (rendezvous latency, per-partition feed time),
  which are orders of magnitude rarer than data-plane increments.
- ``timed(name)``: context manager observing its wall duration into a
  histogram.

``MetricsRegistry`` interns one instance per metric name and renders
JSON-safe snapshots for the control plane (the coordinator heartbeat
piggyback in ``node.py`` — see ``collect_changed``).  A disabled registry
(``TOS_METRICS=0``) hands out shared no-op singletons so instrumented code
pays only a dict miss.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Iterable

# Default bounded-reservoir size: enough for stable p99 estimates on the
# span histograms while keeping a snapshot's wire footprint small.
RESERVOIR_SIZE = 256
# Per-collection cap on the "recent samples" outbox that rides heartbeats
# (the coordinator pools these for cluster-wide percentiles).
OUTBOX_SIZE = 64


class Counter:
    """Monotonic counter with lock-free, exact increments (see module doc)."""

    __slots__ = ("name", "_cells")

    def __init__(self, name: str):
        self.name = name
        self._cells: dict[int, int] = {}

    def inc(self, amount: int = 1) -> None:
        cells = self._cells
        tid = threading.get_ident()
        cells[tid] = cells.get(tid, 0) + amount

    def value(self) -> int:
        while True:
            try:
                return sum(self._cells.values())
            except RuntimeError:
                # a thread inserted its first cell mid-iteration; reread
                continue


class Gauge:
    """Last-write-wins instantaneous value (attribute store is atomic)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: float | None = None

    def set(self, value: float) -> None:
        self._value = float(value)

    def value(self) -> float | None:
        return self._value


class Histogram:
    """Running digest + bounded reservoir of observed values (spans)."""

    __slots__ = ("name", "_lock", "count", "total", "min", "max",
                 "_reservoir", "_reservoir_size", "_rng", "_outbox")

    def __init__(self, name: str, reservoir_size: int = RESERVOIR_SIZE):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._reservoir: list[float] = []
        self._reservoir_size = reservoir_size
        # deterministic per-name stream so identical runs sample identically
        # (crc32, not hash(): str hashing is per-process randomized)
        self._rng = random.Random(0xC0FFEE ^ zlib.crc32(name.encode("utf-8")))
        self._outbox: list[float] = []

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value
            if len(self._reservoir) < self._reservoir_size:
                self._reservoir.append(value)
            else:
                # Algorithm R: keep each of the N observations with
                # probability reservoir_size/N
                idx = self._rng.randrange(self.count)
                if idx < self._reservoir_size:
                    self._reservoir[idx] = value
            if len(self._outbox) < OUTBOX_SIZE:
                self._outbox.append(value)

    def percentile(self, q: float) -> float | None:
        """Estimate the q-th percentile (0..100) from the reservoir."""
        with self._lock:
            samples = sorted(self._reservoir)
        return percentile_of(samples, q)

    def digest(self) -> dict:
        """JSON-safe running summary (no samples)."""
        with self._lock:
            return {"count": self.count, "sum": self.total,
                    "min": self.min, "max": self.max}

    def drain_outbox(self) -> list[float]:
        """Samples observed since the last drain (capped at OUTBOX_SIZE);
        the wire-delta path ships these for cluster-wide percentiles."""
        with self._lock:
            out, self._outbox = self._outbox, []
            return out

    def restore_outbox(self, samples: list[float]) -> None:
        """Give drained samples back (the carrying send failed) so the
        cluster percentile pool doesn't silently lose them; bounded — on
        overflow the oldest restored samples are dropped."""
        with self._lock:
            merged = list(samples) + self._outbox
            self._outbox = merged[-OUTBOX_SIZE:]

    def reservoir(self) -> list[float]:
        with self._lock:
            return list(self._reservoir)


def percentile_of(samples: list[float], q: float) -> float | None:
    """Nearest-rank percentile of an already-sorted sample list."""
    if not samples:
        return None
    if len(samples) == 1:
        return samples[0]
    rank = (q / 100.0) * (len(samples) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(samples) - 1)
    frac = rank - lo
    return samples[lo] * (1.0 - frac) + samples[hi] * frac


class _Timer:
    """``with registry.timed(name):`` — observes wall seconds on exit."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.perf_counter() - self._t0)


# -- no-op variants (TOS_METRICS=0) -------------------------------------------


class _NullCounter:
    __slots__ = ()
    name = "<disabled>"

    def inc(self, amount: int = 1) -> None:
        return None

    def value(self) -> int:
        return 0


class _NullGauge:
    __slots__ = ()
    name = "<disabled>"

    def set(self, value: float) -> None:
        return None

    def value(self) -> None:
        return None


class _NullHistogram:
    __slots__ = ()
    name = "<disabled>"
    count = 0
    total = 0.0
    min = None
    max = None

    def observe(self, value: float) -> None:
        return None

    def percentile(self, q: float) -> None:
        return None

    def digest(self) -> dict:
        return {"count": 0, "sum": 0.0, "min": None, "max": None}

    def drain_outbox(self) -> list:
        return []

    def restore_outbox(self, samples: list) -> None:
        return None

    def reservoir(self) -> list:
        return []


class _NullTimer:
    __slots__ = ()

    def __enter__(self) -> "_NullTimer":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_COUNTER = _NullCounter()
_NULL_GAUGE = _NullGauge()
_NULL_HISTOGRAM = _NullHistogram()
_NULL_TIMER = _NullTimer()


class MetricsRegistry:
    """Process-local registry interning one metric object per name."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()  # creation only — never the hot path
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    # -- metric accessors (hot path: one dict get) ---------------------------

    def counter(self, name: str) -> Counter | _NullCounter:
        if not self.enabled:
            return _NULL_COUNTER
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge | _NullGauge:
        if not self.enabled:
            return _NULL_GAUGE
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram | _NullHistogram:
        if not self.enabled:
            return _NULL_HISTOGRAM
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def timed(self, name: str) -> _Timer | _NullTimer:
        if not self.enabled:
            return _NULL_TIMER
        return _Timer(self.histogram(name))

    # -- snapshots -----------------------------------------------------------

    def snapshot(self, include_samples: bool = False) -> dict:
        """Full JSON-safe snapshot: ``{"counters": {name: int},
        "gauges": {name: float}, "histograms": {name: digest}}``.
        ``include_samples=True`` adds each histogram's reservoir under
        ``"recent"`` (the shape the cluster aggregation pools)."""
        counters = {n: c.value() for n, c in list(self._counters.items())}
        gauges = {n: g.value() for n, g in list(self._gauges.items())
                  if g.value() is not None}
        hists = {}
        for n, h in list(self._histograms.items()):
            d = h.digest()
            if not d["count"]:
                continue
            if include_samples:
                d["recent"] = h.reservoir()
            hists[n] = d
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    def collect_changed(self, last: dict | None) -> tuple[dict, dict]:
        """Compact wire delta for the heartbeat piggyback.

        Returns ``(payload, state)``: ``payload`` holds only the entries
        whose cumulative value moved since ``last`` (the previous call's
        returned ``state``) — but every value in it is **absolute**, so the
        receiver merges by replacement and a lost heartbeat can never lose
        counts.  Histograms additionally carry the samples observed since
        the last drain (``"recent"``, capped) for cluster-wide percentiles.
        """
        last = last or {"counters": {}, "gauges": {}, "hist_counts": {}}
        payload: dict = {}
        counters = {n: c.value() for n, c in list(self._counters.items())}
        changed_c = {n: v for n, v in counters.items()
                     if v != last["counters"].get(n)}
        if changed_c:
            payload["counters"] = changed_c
        gauges = {n: g.value() for n, g in list(self._gauges.items())
                  if g.value() is not None}
        changed_g = {n: v for n, v in gauges.items()
                     if v != last["gauges"].get(n)}
        if changed_g:
            payload["gauges"] = changed_g
        hist_counts: dict[str, int] = {}
        changed_h: dict[str, dict] = {}
        for n, h in list(self._histograms.items()):
            d = h.digest()
            hist_counts[n] = d["count"]
            if not d["count"] or d["count"] == last["hist_counts"].get(n):
                continue
            recent = h.drain_outbox()
            if recent:
                d["recent"] = recent
            changed_h[n] = d
        if changed_h:
            payload["histograms"] = changed_h
        state = {"counters": counters, "gauges": gauges,
                 "hist_counts": hist_counts}
        return payload, state

    def drain_recent(self) -> dict[str, list[float]]:
        """Drain every histogram's outbox: the samples observed since the
        last drain, per name.  Used by the DRIVER's rolling-stats sampler
        (the driver sends no heartbeats, so its outboxes have no other
        consumer); node processes must leave this to ``collect_changed``."""
        out: dict[str, list[float]] = {}
        for name, h in list(self._histograms.items()):
            recent = h.drain_outbox()
            if recent:
                out[name] = recent
        return out

    def restore_recent(self, payload: dict | None) -> None:
        """Return a failed delta's drained histogram samples to their
        outboxes (``collect_changed`` drains destructively, and counters/
        digests re-send themselves by being absolute — samples are the one
        thing a lost ping would otherwise lose)."""
        for name, d in ((payload or {}).get("histograms") or {}).items():
            recent = d.get("recent")
            if recent:
                self.histogram(name).restore_outbox(recent)

    def reset(self) -> None:
        """Drop every metric (tests / the bench's on-vs-off comparison)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def iter_metric_names(snapshot: dict) -> Iterable[tuple[str, str, Any]]:
    """(kind, name, value/digest) triples of one snapshot, sorted."""
    for kind in ("counters", "gauges", "histograms"):
        for name in sorted(snapshot.get(kind) or {}):
            yield kind, name, snapshot[kind][name]
