"""tensorflowonspark_tpu.telemetry — cluster-wide metrics and span tracing.

The framework's observability substrate (stdlib-only):

- **Process-local registry** — ``counter(name)`` / ``gauge(name)`` /
  ``histogram(name)`` / ``timed(name)`` intern one metric per name in this
  process.  Counter increments are lock-free and exact (per-thread cells),
  so the data plane meters every frame without measurable overhead; see
  ``registry.py``.
- **Transport** — nodes piggyback compact deltas of their registry on the
  control-plane heartbeats they already send (``node.py``); the coordinator
  merges them into a per-node store and serves the aggregated cluster view
  through a ``metrics`` control-plane op (``coordinator.py``).
- **Sinks** — ``cluster.metrics()`` (aggregated dict), ``cluster.
  debug_dump()`` (text), ``cluster.stats()`` (rolling-window live stats,
  the ``statz`` op), periodic TensorBoard scalar export through
  ``summary.SummaryWriter``, and an end-of-run JSON run report written at
  shutdown (``cluster.py``; ``report.py`` builds the aggregates).
- **Distributed tracing + flight recorder** — ``trace.py``: sampled
  spans with cross-process context propagation (``TOS_TRACE``), shipped
  on the same heartbeats and merged into a Perfetto-loadable
  ``trace.json`` by ``trace_export.py``; a bounded ring of structured
  events (deaths/restarts/retries/resyncs/reloads/faults) feeds the run
  report's ``"flight"`` timeline and crash dumps.

Master switch: ``TOS_METRICS`` (default on).  Disabled, every accessor
returns a shared no-op object, so instrumentation costs one dict miss.

Usage inside a ``map_fun`` (via ``ctx.metrics``) or anywhere in-process::

    from tensorflowonspark_tpu import telemetry
    telemetry.counter("myjob.records_scored").inc(len(batch))
    telemetry.gauge("myjob.steps_per_sec").set(rate)
    with telemetry.timed("myjob.step_secs"):
        state = step(state, batch)
"""

from __future__ import annotations

import threading

from tensorflowonspark_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    OUTBOX_SIZE,
    RESERVOIR_SIZE,
    percentile_of,
)
from tensorflowonspark_tpu.telemetry.report import (  # noqa: F401
    aggregate_snapshots,
    build_run_report,
    debug_dump,
    write_run_report,
)
from tensorflowonspark_tpu.telemetry import trace  # noqa: F401

_lock = threading.Lock()
_registry: MetricsRegistry | None = None


def get_registry() -> MetricsRegistry:
    """The process-local registry, created on first use from ``TOS_METRICS``."""
    global _registry
    reg = _registry
    if reg is None:
        with _lock:
            if _registry is None:
                from tensorflowonspark_tpu.utils.envtune import env_bool

                _registry = MetricsRegistry(enabled=env_bool("TOS_METRICS", True))
            reg = _registry
    return reg


def reset(enabled: bool | None = None) -> MetricsRegistry:
    """Replace the process registry (tests and the bench's metrics-on/off
    comparison only): re-reads ``TOS_METRICS`` unless ``enabled`` is given.
    Metric objects handed out before the reset keep working but report into
    the abandoned registry."""
    global _registry
    with _lock:
        if enabled is None:
            from tensorflowonspark_tpu.utils.envtune import env_bool

            enabled = env_bool("TOS_METRICS", True)
        _registry = MetricsRegistry(enabled=enabled)
        return _registry


def enabled() -> bool:
    return get_registry().enabled


def counter(name: str):
    return get_registry().counter(name)


def gauge(name: str):
    return get_registry().gauge(name)


def histogram(name: str):
    return get_registry().histogram(name)


def timed(name: str):
    return get_registry().timed(name)


def snapshot(include_samples: bool = False) -> dict:
    return get_registry().snapshot(include_samples=include_samples)


def collect_changed(last: dict | None) -> tuple[dict, dict]:
    return get_registry().collect_changed(last)
