"""Sampled distributed tracing + flight recorder — the "where did it go" half
of the telemetry subsystem (stdlib-only).

The metrics registry (``registry.py``) answers "how much"; this module
answers "where did this request's 40 ms go" and "what happened in the 2 s
before that node died":

- **Spans** — structured records ``(trace_id, span_id, parent, monotonic
  start, duration, tags)`` written into **lock-free per-thread bounded
  rings**: each thread appends only to its own ring (list-slot assignment
  is atomic under the GIL, mirroring the registry's per-thread counter
  cells), so recording a span on the serving hot path costs an append and
  never takes a lock.  A full ring overwrites its oldest entries; the
  drain reports how many were lost.
- **Sampling** — ``TOS_TRACE`` (default off) gates everything; when on,
  ``TOS_TRACE_SAMPLE`` picks every ``round(1/rate)``-th root
  deterministically (a counter, not an RNG — identical runs sample
  identical requests, which is what the trace tests pin).  Child spans
  never re-sample: a context handed across threads/processes means the
  root already won the lottery.
- **Context propagation** — a :class:`TraceContext` is a plain
  ``(trace_id, span_id)`` pair, JSON- and pickle-safe, carried in wire
  frames (v3 ``infer_round``/``end_partition``) and queue markers so one
  request's spans assemble across processes.
- **Flight recorder** — every process keeps a separate bounded ring of
  structured *events* (deaths, restarts, retries, resyncs, reloads, fault
  injections; ``TOS_FLIGHT_EVENTS`` sizes it, 0 disables) independent of
  the trace switch, plus ``flight_snapshot()``/``dump_flight()`` so a
  chaos exit leaves a readable timeline behind.
- **Transport** — ``collect_delta()`` drains new spans/events for the
  heartbeat piggyback (``node.py``), stamped with this process's current
  clock-offset estimate (driver-monotonic = local-monotonic + offset, the
  NTP-style midpoint estimate from heartbeat RTTs) so the export can
  merge per-node streams onto one timeline (``trace_export.py``).

Disabled (the default), every accessor returns ``None`` / a shared no-op
span, so instrumented code pays one attribute check.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, NamedTuple

#: Per-thread span-ring capacity: recent-window postmortems need seconds of
#: history, the heartbeat drain empties it every ~2s — 2048 spans/thread
#: absorbs bursts well past both.
RING_SIZE = 2048
#: Max spans shipped per heartbeat delta (the rest ride the next one, or are
#: counted dropped by the ring overwrite if the producer outruns the drain).
DRAIN_SPAN_CAP = 1024
#: Flight-event ring default capacity (TOS_FLIGHT_EVENTS overrides; 0 off).
FLIGHT_EVENTS_DEFAULT = 256


class TraceContext(NamedTuple):
    """Wire-portable span identity: share ``trace_id``, parent ``span_id``.

    Serialized as a plain 2-tuple (pickle) / 2-list (JSON); ``coerce``
    accepts either back.
    """

    trace_id: int
    span_id: int

    @classmethod
    def coerce(cls, value) -> "TraceContext | None":
        if value is None:
            return None
        try:
            tid, sid = value
            return cls(int(tid), int(sid))
        except (TypeError, ValueError):
            return None


class _Ring:
    """Bounded append-only ring owned by ONE writer thread.

    ``buf[n % cap] = item; n += 1`` — the owning thread is the only writer,
    slot assignment is atomic under the GIL, and readers (the drain, the
    flight snapshot) tolerate racing a concurrent overwrite: they read
    whole immutable dicts, either the old span or the new one.
    """

    __slots__ = ("buf", "cap", "n", "owner")

    def __init__(self, cap: int):
        self.buf: list = [None] * cap
        self.cap = cap
        self.n = 0
        self.owner: threading.Thread | None = None  # writer, for dead-ring pruning

    def append(self, item) -> None:
        self.buf[self.n % self.cap] = item
        self.n += 1

    def read_from(self, cursor: int) -> tuple[list, int, int]:
        """(items, new_cursor, dropped) — entries appended since ``cursor``
        that are still in the ring."""
        n = self.n  # snapshot; concurrent appends land in the next drain
        start = max(cursor, n - self.cap)
        items = [self.buf[i % self.cap] for i in range(start, n)]
        return [x for x in items if x is not None], n, start - cursor

    def tail(self, limit: int) -> list:
        n = self.n
        start = max(0, n - min(self.cap, limit))
        return [x for x in (self.buf[i % self.cap] for i in range(start, n))
                if x is not None]


class _LiveSpan:
    """``with tracer.span(name, parent=ctx):`` — times the block and records
    it on exit; ``.ctx`` is the context to hand to children (including
    remote ones, before the span ends)."""

    __slots__ = ("_tracer", "name", "ctx", "_parent", "_tags", "_t0")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 parent: int | None, tags: dict | None):
        self._tracer = tracer
        self.name = name
        self.ctx = ctx
        self._parent = parent
        self._tags = tags

    def __enter__(self) -> "_LiveSpan":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._tracer.record_span(self.name, self.ctx, self._parent,
                                 self._t0, time.monotonic() - self._t0,
                                 self._tags)


class _NullSpan:
    """Shared no-op stand-in: disabled tracer / unsampled request."""

    __slots__ = ()
    ctx = None
    name = "<off>"

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


NULL_SPAN = _NullSpan()


class Tracer:
    """Process-local trace recorder (one per process, like the metrics
    registry).  All public methods are safe to call with tracing disabled —
    they return ``None``/no-ops and cost an attribute check."""

    def __init__(self, enabled: bool = False, sample: float = 0.01,
                 flight_events: int = FLIGHT_EVENTS_DEFAULT,
                 ring_size: int = RING_SIZE):
        self.enabled = bool(enabled)
        sample = min(1.0, float(sample))
        # deterministic counter sampling: every period-th root is traced
        self._period = max(1, round(1.0 / sample)) if sample > 0 else 0
        self._seq = itertools.count()        # CPython next() is atomic
        self._ids = itertools.count(1)
        # span ids carry per-process random high bits so two processes can
        # never mint the same id inside one merged trace; ids need no
        # determinism (sampling has it), so urandom is fine here
        self._id_base = int.from_bytes(os.urandom(6), "big") << 24
        self._ring_size = ring_size
        self._local = threading.local()
        self._rings_lock = threading.Lock()
        self._rings: list[_Ring] = []
        self._cursors: dict[int, int] = {}   # id(ring) -> drain cursor
        self.dropped = 0                     # spans lost to ring overwrite
        # drained-but-unshipped carryover (span-cap overflow, failed
        # heartbeat restore) — owned by the single drain thread, like
        # ``_cursors``; bounded so a dead coordinator can't grow it forever
        self._pending_spans: list = []
        self._pending_events: list = []
        # flight events: rare, multi-writer -> one small locked ring
        self._events_cap = max(0, int(flight_events))
        self._events = _Ring(self._events_cap) if self._events_cap else None
        self._events_lock = threading.Lock()
        self._events_cursor = 0
        #: driver-monotonic = local-monotonic + offset (heartbeat RTT
        #: midpoint estimate; None until the first heartbeat, 0.0 on the
        #: driver itself).  Last-write-wins float: atomic attribute store.
        self.clock_offset: float | None = None
        self.clock_rtt: float | None = None

    # -- id allocation / sampling ---------------------------------------------

    def _new_id(self) -> int:
        # addition, not OR: injective for ANY counter value, so a process
        # that mints more than 2^24 ids (long fully-sampled soak) can never
        # alias an earlier id — OR would wrap into the base bits
        return self._id_base + next(self._ids)

    def sample(self) -> TraceContext | None:
        """Root sampling decision: a fresh root context for every
        ``round(1/TOS_TRACE_SAMPLE)``-th call, else None.  Deterministic —
        a counter, not an RNG."""
        if not self.enabled or not self._period:
            return None
        if next(self._seq) % self._period:
            return None
        return TraceContext(self._new_id(), self._new_id())

    def derive(self, parent: TraceContext | None) -> TraceContext | None:
        """A child context under ``parent`` (same trace, fresh span id) —
        for spans whose context must exist before they end."""
        if not self.enabled or parent is None:
            return None
        return TraceContext(parent[0], self._new_id())

    # -- recording ------------------------------------------------------------

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(self._ring_size)
            ring.owner = threading.current_thread()
            self._local.ring = ring
            with self._rings_lock:
                self._rings.append(ring)
        return ring

    def record_span(self, name: str, ctx: TraceContext | None,
                    parent: int | None, t0: float, dur: float,
                    tags: dict | None = None) -> None:
        """Append one finished span.  No-op when disabled or ``ctx`` is
        None (the unsampled path), so call sites need no guard."""
        if not self.enabled or ctx is None:
            return
        span = {"n": name, "t": ctx[0], "s": ctx[1], "p": parent,
                "t0": t0, "d": dur, "th": threading.get_ident()}
        if tags:
            span["tags"] = tags
        self._ring().append(span)

    def record_child(self, name: str, parent: TraceContext | None,
                     t0: float, dur: float,
                     tags: dict | None = None) -> TraceContext | None:
        """Record a retrospective child span under ``parent``; returns the
        child's context (None when unsampled/disabled)."""
        ctx = self.derive(parent)
        if ctx is not None:
            self.record_span(name, ctx, parent[1], t0, dur, tags)
        return ctx

    def span(self, name: str, parent: TraceContext | None = None,
             tags: dict | None = None, root: bool = False):
        """Context manager timing a live block.  ``parent=None`` records
        nothing unless ``root=True``, which applies root sampling."""
        if not self.enabled:
            return NULL_SPAN
        if parent is None:
            if not root:
                return NULL_SPAN
            ctx = self.sample()
            if ctx is None:
                return NULL_SPAN
            return _LiveSpan(self, name, ctx, None, tags)
        return _LiveSpan(self, name, self.derive(parent), parent[1], tags)

    # -- flight recorder ------------------------------------------------------

    def event(self, kind: str, **fields) -> None:
        """Record one structured flight event (death/restart/retry/resync/
        reload/fault...).  Independent of the trace switch — gated only by
        ``TOS_FLIGHT_EVENTS`` (0 disables).  Rare by contract, so a small
        lock is fine."""
        if self._events is None:
            return
        ev = {"kind": kind, "t0": time.monotonic(), "wall": time.time()}
        if fields:
            ev.update(fields)
        with self._events_lock:
            self._events.append(ev)

    def flight_snapshot(self, span_limit: int = 512) -> dict:
        """Recent history for a postmortem dump: every flight event still in
        the ring plus the most recent spans of every thread, oldest first."""
        with self._events_lock:
            events = self._events.tail(self._events_cap) if self._events else []
        with self._rings_lock:
            rings = list(self._rings)
        spans: list = []
        for ring in rings:
            spans.extend(ring.tail(span_limit))
        spans.sort(key=lambda s: s["t0"])
        return {"events": list(events), "spans": spans,
                "clock_offset": self.clock_offset}

    # -- transport (heartbeat piggyback) --------------------------------------

    def collect_delta(self, span_cap: int = DRAIN_SPAN_CAP) -> dict | None:
        """New spans/events since the last collect, for the heartbeat
        piggyback; None when there is nothing to ship.  Spans only travel
        while tracing is on; flight events travel whenever their ring is
        enabled.  Single-consumer: the heartbeat thread (it owns the drain
        cursors and the pending carryover)."""
        payload: dict = {}
        if self.enabled:
            with self._rings_lock:
                rings = list(self._rings)
            spans, self._pending_spans = self._pending_spans, []
            dead: list[_Ring] = []
            for ring in rings:
                got, cursor, lost = ring.read_from(
                    self._cursors.get(id(ring), 0))
                self._cursors[id(ring)] = cursor
                self.dropped += lost
                spans.extend(got)
                # a dead writer appends nothing more: once its ring is fully
                # drained, drop it (a long soak with elastic restarts mints a
                # 2048-slot ring per short-lived recording thread otherwise)
                if (ring.owner is not None and not ring.owner.is_alive()
                        and cursor >= ring.n):
                    dead.append(ring)
            if dead:
                with self._rings_lock:
                    for ring in dead:
                        self._rings.remove(ring)
                        self._cursors.pop(id(ring), None)
            if spans:
                spans.sort(key=lambda s: s["t0"])
                if len(spans) > span_cap:
                    # overflow rides the next beat (bounded: past 4 beats'
                    # worth the oldest are dropped and counted)
                    carry = spans[:-span_cap]
                    spans = spans[-span_cap:]
                    excess = len(carry) - 4 * span_cap
                    if excess > 0:
                        self.dropped += excess
                        carry = carry[excess:]
                    self._pending_spans = carry
                payload["spans"] = spans
        if self._events is not None:
            events, self._pending_events = self._pending_events, []
            with self._events_lock:
                got_ev, self._events_cursor, _ = self._events.read_from(
                    self._events_cursor)
            events.extend(got_ev)
            if events:
                payload["events"] = events
        if not payload:
            return None
        if self.clock_offset is not None:
            payload["offset"] = self.clock_offset
            payload["rtt"] = self.clock_rtt
        if self.dropped:
            payload["dropped"] = self.dropped
        return payload

    def collect_final(self) -> dict | None:
        """Everything still unshipped, uncapped — the one-shot drain for
        paths with no next beat (deregister's final delta, the driver's
        export gather): the span-cap defer contract must not strand the
        carryover when this is the last collect."""
        return self.collect_delta(span_cap=1 << 62)

    def restore_delta(self, payload: dict | None) -> None:
        """Give a failed heartbeat's drained delta back so the next beat
        re-ships it: unlike metric deltas (absolute values, implicitly
        re-sent), drained spans and flight events are not re-derivable.
        Same single-consumer contract as ``collect_delta``."""
        if not payload:
            return
        spans = payload.get("spans")
        if spans:
            self._pending_spans = list(spans) + self._pending_spans
        events = payload.get("events")
        if events:
            self._pending_events = list(events) + self._pending_events

    def note_clock(self, offset: float, rtt: float) -> None:
        """Adopt a heartbeat's clock estimate when it beats (or refreshes)
        the current one: the lowest-RTT midpoint is the least skewed, but a
        stale low-RTT estimate must not pin forever against drift — a new
        reading within 2x the best RTT refreshes it, and every rejected
        reading relaxes the bar a little so a permanently degraded network
        (best-ever RTT no longer achievable) re-arms within ~15 beats
        instead of freezing the offset for the rest of the run."""
        best = self.clock_rtt
        if best is None or rtt <= 2.0 * best:
            self.clock_offset = float(offset)
            self.clock_rtt = float(rtt) if best is None else min(best, rtt)
        else:
            self.clock_rtt = best * 1.05


# -- process-local singleton ---------------------------------------------------

_lock = threading.Lock()
_tracer: Tracer | None = None


def get_tracer() -> Tracer:
    """The process tracer, created on first use from the TOS_TRACE knobs."""
    global _tracer
    t = _tracer
    if t is None:
        with _lock:
            if _tracer is None:
                from tensorflowonspark_tpu.utils.envtune import (
                    env_bool,
                    env_float,
                    env_int,
                )

                _tracer = Tracer(
                    enabled=env_bool("TOS_TRACE", False),
                    sample=env_float("TOS_TRACE_SAMPLE", 0.01),
                    flight_events=env_int("TOS_FLIGHT_EVENTS",
                                          FLIGHT_EVENTS_DEFAULT, minimum=0))
            t = _tracer
    return t


def reset(enabled: bool | None = None, sample: float | None = None,
          flight_events: int | None = None) -> Tracer:
    """Replace the process tracer (tests / the bench's off-vs-on compare):
    re-reads the env knobs unless overridden."""
    global _tracer
    with _lock:
        from tensorflowonspark_tpu.utils.envtune import (
            env_bool,
            env_float,
            env_int,
        )

        _tracer = Tracer(
            enabled=(env_bool("TOS_TRACE", False) if enabled is None
                     else enabled),
            sample=(env_float("TOS_TRACE_SAMPLE", 0.01) if sample is None
                    else sample),
            flight_events=(env_int("TOS_FLIGHT_EVENTS",
                                   FLIGHT_EVENTS_DEFAULT, minimum=0)
                           if flight_events is None else flight_events))
        return _tracer


def enabled() -> bool:
    return get_tracer().enabled


def sample() -> TraceContext | None:
    return get_tracer().sample()


def derive(parent: TraceContext | None) -> TraceContext | None:
    return get_tracer().derive(parent)


def span(name: str, parent: TraceContext | None = None,
         tags: dict | None = None, root: bool = False):
    return get_tracer().span(name, parent, tags, root=root)


def record_span(name: str, ctx: TraceContext | None, parent: int | None,
                t0: float, dur: float, tags: dict | None = None) -> None:
    get_tracer().record_span(name, ctx, parent, t0, dur, tags)


def record_child(name: str, parent: TraceContext | None, t0: float,
                 dur: float, tags: dict | None = None) -> TraceContext | None:
    return get_tracer().record_child(name, parent, t0, dur, tags)


def event(kind: str, **fields) -> None:
    get_tracer().event(kind, **fields)


def collect_delta() -> dict | None:
    return get_tracer().collect_delta()


def collect_final() -> dict | None:
    return get_tracer().collect_final()


def flight_snapshot(span_limit: int = 512) -> dict:
    return get_tracer().flight_snapshot(span_limit)


def dump_flight(path: str, node: str = "") -> str:
    """Write this process's flight snapshot as JSON (the chaos-exit
    postmortem; ``faultinject`` calls this in the instant before a
    self-SIGKILL).  Returns ``path``."""
    snap = flight_snapshot()
    snap["schema"] = "tos-flight-v1"
    snap["node"] = node
    snap["pid"] = os.getpid()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(snap, f)
        f.write("\n")
    return path


def map_time(t0: float, offset: float | None) -> float:
    """Local monotonic -> driver-monotonic (identity when no estimate)."""
    return t0 + (offset or 0.0)


def event_origin(key: str) -> str:
    """The recording process behind a stream key: a chaos dump
    (``flight:node0``) and the heartbeat-shipped stream (``node0``) share
    one origin, so their common events can be deduplicated."""
    return key[len("flight:"):] if key.startswith("flight:") else key


def merge_events(streams: dict[str, dict]) -> list[dict]:
    """Flatten per-stream flight events onto the driver timeline: each
    event gains ``node`` and ``t`` (driver-monotonic seconds), ordered by
    ``t``.  ``streams`` maps a node key to ``{"events": [...],
    "offset": float|None}`` (the trace-stream / flight-dump shape).

    A chaos dump repeats events its process already shipped on heartbeats
    (the drain advances a cursor, the dump tails the whole ring), so events
    identical per origin are emitted once — heartbeat copy preferred (its
    stream carries them with the offset they shipped under)."""
    out: list[dict] = []
    seen: set = set()
    for key in sorted(streams, key=lambda k: (k.startswith("flight:"), k)):
        stream = streams[key]
        offset = stream.get("clock_offset", stream.get("offset"))
        for ev in stream.get("events") or ():
            ident = (event_origin(key), ev.get("kind"), ev.get("t0"),
                     ev.get("wall"))
            if ident in seen:
                continue
            seen.add(ident)
            ev = dict(ev)
            ev["node"] = key
            ev["t"] = map_time(float(ev.get("t0", 0.0)), offset)
            out.append(ev)
    out.sort(key=lambda e: e["t"])
    return out


def coerce_context(value: Any) -> TraceContext | None:
    """Best-effort TraceContext from a wire value (tuple/list/None)."""
    return TraceContext.coerce(value)
