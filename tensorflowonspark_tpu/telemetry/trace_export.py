"""Merge per-process span streams into one Chrome-trace-format timeline.

Every process records spans against its own ``time.monotonic()`` clock;
the heartbeat transport ships each node's NTP-style clock-offset estimate
(driver-monotonic = node-monotonic + offset, midpoint of the heartbeat
round-trip) along with its spans.  This module folds the per-node streams
onto the driver timeline and emits the Chrome trace event format — one
``trace.json`` loadable in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``:

- each stream becomes one "process" track (metadata ``process_name``
  events name them ``driver`` / ``node 0`` / ...);
- spans are complete (``ph: "X"``) events, microsecond timestamps, with
  trace/span/parent ids and tags under ``args`` (Perfetto's flow/args
  panes show the cross-process request assembly);
- flight-recorder events are instant (``ph: "i"``) events on the same
  timeline, so a chaos kill renders as a mark between the victim's last
  span and the router's retry.

Standalone CLI (merge + validate a run's per-node files)::

    python -m tensorflowonspark_tpu.telemetry.trace_export <run_dir>

reads every ``trace_<key>.json`` stream (written at ``cluster.shutdown()``)
and ``flight_<key>.json`` postmortem dump (written on chaos exit) in
``run_dir`` and writes ``run_dir/trace.json``.
"""

from __future__ import annotations

import glob
import json
import math
import os
import sys

from tensorflowonspark_tpu.telemetry.trace import event_origin, map_time

STREAM_SCHEMA = "tos-trace-stream-v1"


def build_stream(key: str, spans: list, events: list,
                 offset: float | None) -> dict:
    """One per-process stream document (the ``trace_<key>.json`` shape)."""
    return {"schema": STREAM_SCHEMA, "node": key,
            "clock_offset": offset, "spans": list(spans),
            "events": list(events)}


def _stream_offset(stream: dict) -> float | None:
    off = stream.get("clock_offset", stream.get("offset"))
    return float(off) if off is not None else None


def merge_streams(streams: dict[str, dict]) -> dict:
    """``{key: stream}`` -> Chrome trace document.

    ``stream`` is a ``build_stream`` document (or a flight dump: same
    ``spans``/``events``/``clock_offset`` fields).  Timestamps shift so
    the earliest event lands at t=0.
    """
    raw: list[tuple[float, dict]] = []  # (driver-mono seconds, event)
    trace_events: list[dict] = []
    keys = sorted(streams)
    pids = {key: i + 1 for i, key in enumerate(keys)}
    # a chaos dump (flight:nodeN) repeats spans/events its process already
    # shipped on heartbeats into the nodeN stream — emit each once, the
    # heartbeat copy preferred (non-flight streams walk first)
    seen_spans: set = set()
    seen_events: set = set()
    for key in sorted(keys, key=lambda k: (k.startswith("flight:"), k)):
        stream = streams[key]
        offset = _stream_offset(stream)
        pid = pids[key]
        trace_events.append({"ph": "M", "name": "process_name", "pid": pid,
                             "tid": 0, "args": {"name": key}})
        for span in stream.get("spans") or ():
            ident = (span["t"], span["s"])  # span ids are process-unique
            if ident in seen_spans:
                continue
            seen_spans.add(ident)
            t = map_time(float(span["t0"]), offset)
            ev = {"ph": "X", "cat": "span", "name": str(span["n"]),
                  "pid": pid, "tid": int(span.get("th") or 0) % (1 << 31),
                  "ts": t, "dur": max(0.0, float(span.get("d") or 0.0)) * 1e6,
                  "args": {"trace_id": f"{span['t']:x}",
                           "span_id": f"{span['s']:x}",
                           "parent": (f"{span['p']:x}"
                                      if span.get("p") else None),
                           **(span.get("tags") or {})}}
            raw.append((t, ev))
        for fev in stream.get("events") or ():
            ident = (event_origin(key), fev.get("kind"),
                     fev.get("t0"), fev.get("wall"))
            if ident in seen_events:
                continue
            seen_events.add(ident)
            t = map_time(float(fev.get("t0", 0.0)), offset)
            args = {k: v for k, v in fev.items()
                    if k not in ("kind", "t0", "t", "node")}
            raw.append((t, {"ph": "i", "cat": "flight", "s": "g",
                            "name": str(fev.get("kind", "event")),
                            "pid": pid, "tid": 0, "ts": t, "args": args}))
    t_base = min((t for t, _ in raw), default=0.0)
    for t, ev in sorted(raw, key=lambda p: p[0]):
        ev["ts"] = round((t - t_base) * 1e6, 3)
        trace_events.append(ev)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"format": "tos-trace-v1", "streams": keys}}


def validate_chrome_trace(doc: dict) -> int:
    """Schema check of a merged document; returns the event count or raises
    ``ValueError`` — the tier-1 export test and the CLI both run this, so a
    trace that Perfetto would reject fails loudly here first."""
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("traceEvents must be a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            raise ValueError(f"event {i}: unsupported ph {ph!r}")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"event {i}: missing name")
        if not isinstance(ev.get("pid"), int):
            raise ValueError(f"event {i}: missing pid")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or not math.isfinite(ts) or ts < 0:
            raise ValueError(f"event {i}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if (not isinstance(dur, (int, float)) or not math.isfinite(dur)
                    or dur < 0):
                raise ValueError(f"event {i}: bad dur {dur!r}")
    return len(events)


def write_stream(path: str, stream: dict) -> str:
    _write_doc(path, stream)
    return path


def _write_doc(path: str, doc: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.write("\n")


def write_merged(path: str, streams: dict[str, dict]) -> str:
    """Merge, validate, write; returns ``path``."""
    doc = merge_streams(streams)
    validate_chrome_trace(doc)
    _write_doc(path, doc)
    return path


def load_run_dir(run_dir: str) -> dict[str, dict]:
    """Collect every per-process stream in a run directory: the
    ``trace_<key>.json`` files shutdown wrote plus any ``flight_<key>.json``
    chaos dumps (their key gains a ``flight:`` prefix so a node that left
    both contributes two distinguishable tracks)."""
    streams: dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "trace_*.json"))):
        key = os.path.basename(path)[len("trace_"):-len(".json")]
        with open(path, encoding="utf-8") as f:
            streams[key] = json.load(f)
    for path in sorted(glob.glob(os.path.join(run_dir, "flight_*.json"))):
        key = os.path.basename(path)[len("flight_"):-len(".json")]
        with open(path, encoding="utf-8") as f:
            streams[f"flight:{key}"] = json.load(f)
    return streams


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print("usage: python -m tensorflowonspark_tpu.telemetry.trace_export "
              "<run_dir>", file=sys.stderr)
        return 2
    run_dir = argv[0]
    streams = load_run_dir(run_dir)
    if not streams:
        print(f"no trace_*.json / flight_*.json streams in {run_dir}",
              file=sys.stderr)
        return 1
    out = os.path.join(run_dir, "trace.json")
    doc = merge_streams(streams)
    n = validate_chrome_trace(doc)
    _write_doc(out, doc)
    n_spans = sum(len(s.get("spans") or ()) for s in streams.values())
    n_events = sum(len(s.get("events") or ()) for s in streams.values())
    print(f"{out}: {n} trace events ({n_spans} spans, {n_events} flight "
          f"events, {len(streams)} streams) — load it at "
          "https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
