"""Staged serving rollouts + per-tenant fairness (ISSUE 16).

Layers under test, bottom-up:

- tenancy units — DRR interleaving, token buckets, the brownout ladder,
  and the ladder-spec fallback, against bare :class:`TenantQueues` (no
  cluster, no clock slack);
- governor units — the verdict logic against a fake gateway: infra errors
  (dead replica, chaos kill) must NEVER roll back, NaN output / shadow
  divergence / model-attributable errors must, and a clean window
  promotes;
- faultinject grammar — the new ``bad_model`` / ``hot_tenant`` actions
  (string secondary keys ride the plan);
- end-to-end — real 2-node clusters:

  * ``bad_model`` on the canary cohort -> auto-rollback within one
    governor window, zero failed requests, rollback journaled (plus the
    tenant wire-compat assertions: tenant-tagged v2 frames and the
    id-less legacy client sharing one gateway);
  * ``kill_coordinator`` mid-canary -> the rollout rides out a
    control-plane failover (journal replay restores the in-flight state)
    and then promotes;
  * SIGKILL of the canary REPLICA mid-rollout -> no spurious rollback
    (infra exclusion), the restarted replica rejoins the canary cohort
    serving the CANDIDATE bundle, and promotion converges the fleet;
  * ``hot_tenant`` flood at 10x the rate limit -> only the hot tenant is
    shed (429-equivalent ``ServeThrottled``), other tenants' p99 stays
    within 2x their uncontended baseline.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster as tcluster
from tensorflowonspark_tpu import faultinject, serving, telemetry
from tensorflowonspark_tpu.checkpoint import bundle_signature, export_bundle
from tensorflowonspark_tpu.models import linear as linmod
from tensorflowonspark_tpu.serving import (
    GatewayClient,
    LegacyGatewayClient,
    RolloutGovernor,
    RolloutState,
    ServeThrottled,
    TenantQueues,
)
from tensorflowonspark_tpu.serving.rollout import divergence, nan_fraction
from tensorflowonspark_tpu.serving.tenancy import _parse_ladder

LINEAR = {"model": "linear", "in_dim": 4, "out_dim": 4}


# -- tenancy units -------------------------------------------------------------


class _Req:
    """Just enough request surface for TenantQueues (rows/offset/tenant)."""

    def __init__(self, tenant, nrows=1):
        self.tenant = tenant
        self.rows = list(range(nrows))
        self.offset = 0
        self.t_submit = time.monotonic()


def test_tenant_queues_drr_interleaves_backlogged_tenant():
    """A tenant with a deep backlog must not monopolize batch fill: the
    light tenant's rows land within the first DRR rotation turns."""
    q = TenantQueues(queue_limit=64, rate=0.0)
    q.append(_Req("bulk", 100))
    q.append(_Req("light", 8))
    order = []
    for _ in range(6):
        req = q.next_for_batch()
        take = min(4, len(req.rows) - req.offset)
        req.offset += take
        order.append(req.tenant)
        q.charge(req, take)
    assert "light" in order[:4], order
    assert set(q.depths()) <= {"bulk", "light"}


def test_tenant_queues_weighted_drr_grants_proportional_deficit():
    """A weight-3 tenant drains ~3x the rows of a weight-1 tenant per
    rotation cycle (quantum x weight deficit grants)."""
    q = TenantQueues(queue_limit=256, rate=0.0,
                     weights={"gold": 3.0, "bronze": 1.0})
    q.append(_Req("gold", 120))
    q.append(_Req("bronze", 120))
    pulled = {"gold": 0, "bronze": 0}
    for _ in range(16):
        req = q.next_for_batch()
        take = min(4, len(req.rows) - req.offset)
        req.offset += take
        pulled[req.tenant] += take
        q.charge(req, take)
    assert pulled["gold"] >= 2 * pulled["bronze"], pulled


def test_tenant_queues_token_bucket_throttles_and_refills():
    q = TenantQueues(queue_limit=64, rate=20.0)
    assert q.admission_error("t", 20) is None  # the full burst fits
    err = q.admission_error("t", 1)
    assert isinstance(err, ServeThrottled)
    assert "rate" in str(err)
    time.sleep(0.3)  # ~6 tokens refill at 20 rows/s
    assert q.admission_error("t", 2) is None


def test_tenant_queues_brownout_sheds_only_over_share_tenant():
    """Level-2 brownout: the tenant past its weight-proportional queue
    share is shed; a tenant under its share is still admitted."""
    q = TenantQueues(queue_limit=10, rate=0.0, ladder="0.5,0.8")
    for _ in range(7):
        q.append(_Req("pig"))
    q.append(_Req("mouse"))
    assert q.shed_level() == 2
    err = q.admission_error("pig", 1)
    assert isinstance(err, ServeThrottled) and "brownout" in str(err)
    assert q.admission_error("mouse", 1) is None
    # remove() keeps the count honest (expiry path)
    victim = next(iter(q))
    q.remove(victim)
    assert len(q) == 7


def test_parse_ladder_falls_back_on_bad_spec():
    assert _parse_ladder("0.3,0.9") == (0.3, 0.9)
    assert _parse_ladder("junk") == (0.5, 0.8)
    assert _parse_ladder("") == (0.5, 0.8)
    assert _parse_ladder("2.0") == (0.5, 0.8)  # fractions, not multiples


# -- faultinject grammar -------------------------------------------------------


def test_fault_plan_parses_bad_model_and_hot_tenant():
    plan = faultinject.FaultPlan.parse(
        "bad_model:nan=1,ms=50;hot_tenant:mult=10,tenant=burst")
    armed = {a.name: a for a in plan._actions}
    assert armed["bad_model"].threshold == 1
    assert armed["bad_model"].extra["ms"] == 50.0
    assert armed["hot_tenant"].threshold == 10
    assert armed["hot_tenant"].extra["tenant"] == "burst"


# -- governor units (fake gateway) ---------------------------------------------


class _FakeGateway:
    def __init__(self):
        self.promoted: list = []
        self.rolled_back: list = []
        self.journal: list = []

    def _promote_rollout(self, gov):
        self.promoted.append(gov.state.candidate)

    def _rollback_rollout(self, gov, reason):
        self.rolled_back.append(reason)

    def _note_rollout(self, payload):
        self.journal.append(payload)


def _governor(**kw):
    gw = _FakeGateway()
    state = RolloutState(candidate="/cand", prior="/prior", canary=[1],
                         pct=50, shadow=True)
    kw.setdefault("window_secs", 0.4)
    kw.setdefault("min_canary_samples", 1)
    kw.setdefault("poll_secs", 0.05)
    return gw, RolloutGovernor(gw, state, **kw)


def test_governor_promotes_clean_window_and_ignores_infra_errors():
    """Transport failures (the chaos-kill class) are recovery's problem:
    a canary throwing ConnectionError/FaultInjected must still promote."""
    gw, gov = _governor()
    for _ in range(4):
        gov.observe("primary", 0, True, 0.01, [np.ones(2)], None, None)
        gov.observe("canary", 1, True, 0.01, [np.ones(2)], None, None)
    gov.observe("canary", 1, False, 0.0, None, ConnectionError("dead"), None)
    gov.observe("canary", 1, False, 0.0, None,
                faultinject.FaultInjected("sever"), None)
    gov.start()
    assert gov.wait(10.0) == "promoted"
    assert gw.promoted == ["/cand"] and not gw.rolled_back
    assert gw.journal[-1]["status"] == "promoted"
    assert gov.status()["infra_errors"] == 2


def test_governor_rolls_back_on_nan_outputs():
    gw, gov = _governor()
    gov.observe("primary", 0, True, 0.01, [np.ones(2)], None, None)
    gov.observe("canary", 1, True, 0.01, [np.array([np.nan, 1.0])], None,
                None)
    gov.start()
    assert gov.wait(10.0) == "rolled_back"
    assert gw.rolled_back and "NaN" in gw.rolled_back[0]
    assert gov.state.rollback_secs() is not None
    assert gw.journal[-1]["status"] == "rolled_back"


def test_governor_rolls_back_on_shadow_divergence():
    gw, gov = _governor()
    primary_out = [np.array([1.0, 2.0])]
    gov.observe("canary", 1, True, 0.01, [np.array([1.0, 3.5])], None,
                primary_out)  # mirror: canary answer vs primary's
    gov.start()
    assert gov.wait(10.0) == "rolled_back"
    assert "diverges" in gw.rolled_back[0]


def test_governor_rolls_back_on_model_errors_absent_on_primary():
    gw, gov = _governor()
    gov.observe("primary", 0, True, 0.01, [np.ones(2)], None, None)
    gov.observe("canary", 1, False, 0.01, None,
                RuntimeError("bad output head"), None)
    gov.start()
    assert gov.wait(10.0) == "rolled_back"
    assert "model-attributable" in gw.rolled_back[0]


def test_governor_manual_promote_and_stop_abort():
    gw, gov = _governor(auto_promote=False, window_secs=0.1)
    gov.observe("canary", 1, True, 0.01, [np.ones(2)], None, None)
    gov.start()
    time.sleep(0.3)
    assert gov.active()  # auto_promote off: a clean window does NOT resolve
    assert gov.promote() == "promoted"
    assert gw.promoted == ["/cand"]

    gw2, gov2 = _governor()
    gov2.stop()  # never started/resolved -> aborted + journaled
    assert gov2.state.status == "aborted"
    assert gw2.journal[-1]["status"] == "aborted"


def test_divergence_and_nan_helpers():
    assert divergence([np.ones(2)], [np.ones(2)]) == 0.0
    assert divergence([{"y": np.ones(2)}], [{"z": np.ones(2)}]) == 1.0
    assert divergence([np.ones(3)], [np.ones(2)]) == 1.0  # shape mismatch
    assert divergence([np.array([np.nan])], [np.ones(1)]) == 1.0
    assert divergence([3], [3]) == 0.0 and divergence([3], [4]) > 0
    assert nan_fraction([np.array([np.nan, 1.0])]) == 0.5
    assert nan_fraction([np.ones(4)]) == 0.0


# -- end-to-end ----------------------------------------------------------------


@pytest.fixture
def arm_driver_faults(monkeypatch):
    """Arm TOS_FAULTINJECT in the DRIVER process (kill_coordinator and
    hot_tenant live there) and guarantee disarm afterwards."""
    def arm(spec: str) -> None:
        monkeypatch.setenv("TOS_FAULTINJECT", spec)
        faultinject.init_from_env(force=True)

    yield arm
    monkeypatch.delenv("TOS_FAULTINJECT", raising=False)
    faultinject.init_from_env(force=True)


def _serve_cluster(tmp_path, *, scale=2.0, elastic=False, per_node_env=None,
                   env=None, max_batch=4, log_dir=""):
    export = str(tmp_path / "bundle")
    export_bundle(export, linmod.init_params(LINEAR, scale=scale), LINEAR)
    cluster = tcluster.run(
        serving.serving_loop,
        {"export_dir": export, "max_batch": max_batch},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        heartbeat_interval=0.5,
        per_node_env=per_node_env,
        env=env,
        log_dir=log_dir,
        reservation_timeout=120.0,
        elastic=elastic,
    )
    return cluster, export


def _candidate(tmp_path, scale):
    cand = str(tmp_path / "candidate")
    export_bundle(cand, linmod.init_params(LINEAR, scale=scale), LINEAR)
    return cand


@pytest.mark.chaos
def test_bad_model_canary_auto_rolls_back_with_zero_failed_requests(
        tmp_path, monkeypatch):
    """The headline acceptance: stage a candidate that the ``bad_model``
    chaos hook corrupts (NaN outputs on CANDIDATE bundles only); the
    governor must detect it and roll the canaries back within one window,
    with every driven request answered (primary answers always correct)
    and the rollback journaled.  The same boot pins the tenant wire
    compatibility: tenant-tagged pipelined frames and the id-less legacy
    client share the gateway."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    telemetry.reset()
    chaos = {"TOS_FAULTINJECT": "bad_model:nan=1"}
    cluster, export = _serve_cluster(
        tmp_path, scale=2.0, per_node_env=[dict(chaos), dict(chaos)])
    try:
        gw = cluster.serve(export, max_batch=4, max_delay_ms=2.0,
                           reload_poll_secs=0)
        base = np.arange(4, dtype=np.float32)

        # -- wire-compat satellite (before any rollout exists) --
        host, port = gw.endpoint
        np.testing.assert_allclose(
            gw.predict([base], timeout=60.0, tenant="driver-side")[0],
            base * 2.0)
        modern = GatewayClient("127.0.0.1", port, cluster.authkey,
                               tenant="team-a")
        legacy = LegacyGatewayClient("127.0.0.1", port, cluster.authkey)
        try:
            np.testing.assert_allclose(
                modern.predict([base + 1], timeout=60.0)[0], (base + 1) * 2.0)
            np.testing.assert_allclose(  # per-call override rides the frame
                modern.predict([base + 2], timeout=60.0, tenant="team-b")[0],
                (base + 2) * 2.0)
            # the id-less 3-tuple wire shape still answers (anonymous tenant)
            np.testing.assert_allclose(
                legacy.predict([base + 3], timeout=60.0)[0], (base + 3) * 2.0)
            assert legacy.ping()
        finally:
            modern.close()
            legacy.close()

        # -- the rollout: candidate identical in weights, corrupted by chaos
        cand = _candidate(tmp_path, scale=2.0)
        gov = gw.rollout(cand, canary_pct=50, shadow=True, window_secs=3.0)
        assert gw._router.cohort_members("canary") == [0]
        errors: list = []
        driven = 0
        deadline = time.monotonic() + 60.0
        while gov.active() and time.monotonic() < deadline:
            try:
                gw.predict([base + driven], timeout=30.0)
            except Exception as e:  # noqa: BLE001 - asserted empty below
                errors.append(repr(e))
            driven += 1
        assert gov.wait(30.0) == "rolled_back", gov.status()
        # zero failed requests: canary answers may be NaN pre-rollback (that
        # is what canarying risks), but nothing ever errored or misrouted
        assert not errors, errors[:3]
        assert "NaN" in (gov.state.reason or "") or \
            "diverges" in (gov.state.reason or ""), gov.state.reason
        # rollback within one governor window of detection
        assert gov.status()["rollback_secs"] is not None
        assert gov.status()["rollback_secs"] < 30.0
        assert telemetry.counter("serve.rollbacks_total").value() == 1
        assert telemetry.counter("serve.shadow_mirrors").value() >= 1
        # the split is gone and the PRIOR bundle serves everywhere
        assert gw._router.cohort_members("canary") == []
        for i in range(6):
            np.testing.assert_allclose(
                gw.predict([base + i], timeout=60.0)[0], (base + i) * 2.0)
        # journaled: the coordinator's rollout registry has the abort story
        reg = cluster.coordinator.rollout_state()
        assert any(v.get("status") == "rolled_back"
                   and v.get("candidate") == cand for v in reg.values()), reg
        # a fresh rollout is allowed after resolution (state machine back
        # to idle) — and refusing fleet reloads mid-rollout was enforced
        assert gw.rollout_status()["status"] == "rolled_back"
    finally:
        cluster.shutdown(timeout=120.0)


@pytest.mark.chaos
def test_rollout_survives_coordinator_kill_then_promotes(
        tmp_path, monkeypatch, arm_driver_faults):
    """``kill_coordinator`` mid-canary: the data plane keeps serving, the
    rollout keeps governing, and the journal replay restores the in-flight
    rollout state across the failover — after which promotion converges
    the fleet onto the candidate."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_RESTART_BACKOFF_BASE", "0.2")
    telemetry.reset()
    cluster, export = _serve_cluster(tmp_path, scale=2.0,
                                     env={"TOS_FAULTINJECT": ""},
                                     log_dir=str(tmp_path / "logs"))
    try:
        gw = cluster.serve(export, max_batch=4, max_delay_ms=2.0,
                           listen=False, reload_poll_secs=0)
        base = np.arange(4, dtype=np.float32)
        cand = _candidate(tmp_path, scale=3.0)
        gov = gw.rollout(cand, canary_pct=50, shadow=False,
                         auto_promote=False, window_secs=2.0,
                         latency_factor=50.0, latency_floor_secs=5.0)
        # arm AFTER the rollout is in flight so the crash cannot land
        # inside the canary ctl round — the scenario is a failover UNDER
        # an established rollout (heartbeats advance the op clock)
        arm_driver_faults("kill_coordinator:after_ops=10")
        driven = 0
        deadline = time.monotonic() + 90.0
        while cluster.coordinator.epoch < 1 and time.monotonic() < deadline:
            out = gw.predict([base + driven], timeout=30.0)[0]
            # canary-routed answers are x3 (the candidate), primary x2 —
            # never junk, never an error
            ok2 = np.allclose(out, (base + driven) * 2.0)
            ok3 = np.allclose(out, (base + driven) * 3.0)
            assert ok2 or ok3, out
            driven += 1
            time.sleep(0.01)
        assert cluster.coordinator.epoch >= 1, \
            "the coordinator kill never fired mid-canary"
        # still mid-canary: the failover neither resolved nor aborted it
        assert gov.active()
        assert telemetry.counter("serve.rollbacks_total").value() == 0
        # journal replay restored the IN-FLIGHT rollout state
        reg = cluster.coordinator.rollout_state()
        assert any(v.get("status") == "canary" and v.get("candidate") == cand
                   and v.get("canary") == [0] for v in reg.values()), reg
        # operator promotes; the fleet converges on the candidate
        assert gov.promote() == "promoted"
        deadline = time.monotonic() + 60.0
        streak = 0
        while streak < 6 and time.monotonic() < deadline:
            out = gw.predict([base], timeout=30.0)[0]
            streak = streak + 1 if np.allclose(out, base * 3.0) else 0
        assert streak >= 6, "fleet never converged on the promoted candidate"
        reg = cluster.coordinator.rollout_state()
        assert any(v.get("status") == "promoted" for v in reg.values()), reg
        assert gw.export_dir == cand  # the watcher now tracks the candidate
    finally:
        cluster.shutdown(timeout=120.0)
    assert cluster.coordinator.errors() == []


@pytest.mark.chaos
def test_canary_replica_sigkill_no_spurious_rollback_and_cohort_rejoin(
        tmp_path, monkeypatch):
    """SIGKILL the canary REPLICA mid-rollout: the in-flight canary batch
    retries on the primary cohort (every request still answered), the
    governor must NOT read the transport failure as a model regression,
    and the supervised restart must rejoin the replica into the CANARY
    cohort serving the CANDIDATE bundle (recovery replays the cohort's
    reload ctl)."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "4")
    monkeypatch.setenv("TOS_RESTART_BACKOFF_BASE", "0.2")
    telemetry.reset()
    cluster, export = _serve_cluster(
        tmp_path, scale=2.0, elastic=True,
        per_node_env=[{"TOS_FAULTINJECT": "kill:after_batches=3,incarnation=0"},
                      {}])
    try:
        gw = cluster.serve(export, max_batch=4, max_delay_ms=2.0,
                           listen=False, reload_poll_secs=0)
        base = np.arange(4, dtype=np.float32)
        cand = _candidate(tmp_path, scale=3.0)
        gov = gw.rollout(cand, canary_pct=50, shadow=False,
                         auto_promote=False, window_secs=2.0,
                         latency_factor=50.0, latency_floor_secs=5.0)
        assert gw._router.cohort_members("canary") == [0]
        errors: list = []
        driven = 0
        deadline = time.monotonic() + 60.0
        while (telemetry.counter("serve.replica_failures").value() == 0
               and time.monotonic() < deadline):
            try:
                out = gw.predict([base + driven], timeout=90.0)[0]
                assert (np.allclose(out, (base + driven) * 2.0)
                        or np.allclose(out, (base + driven) * 3.0)), out
            except Exception as e:  # noqa: BLE001 - asserted empty below
                errors.append(repr(e))
            driven += 1
        assert not errors, errors[:3]
        assert telemetry.counter("serve.replica_failures").value() >= 1, \
            "the canary kill never fired"
        # requests keep flowing with the canary DOWN: cohort fallback +
        # demotion-retry keep every answer on the healthy primary (x3 only
        # if the supervised restart already rejoined with the candidate)
        for i in range(8):
            out = gw.predict([base + i], timeout=90.0)[0]
            assert (np.allclose(out, (base + i) * 2.0)
                    or np.allclose(out, (base + i) * 3.0)), out
        # the governor saw only infra errors: NO rollback
        assert gov.active(), gov.status()
        assert telemetry.counter("serve.rollbacks_total").value() == 0
        # the supervised restart rejoins replica 0 into the CANARY cohort
        # (recovery replays the candidate reload before re-admission)
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline and (
                gw.healthy_replicas() != [0, 1]
                or gw._router.cohort_members("canary") != [0]):
            time.sleep(0.5)
        assert gw.healthy_replicas() == [0, 1]
        assert gw._router.cohort_members("canary") == [0]
        # the rejoined canary serves the CANDIDATE: drive until a x3 answer
        # proves the replayed ctl loaded it (canary takes every 2nd batch)
        deadline = time.monotonic() + 60.0
        seen_candidate = False
        while not seen_candidate and time.monotonic() < deadline:
            out = gw.predict([base], timeout=60.0)[0]
            seen_candidate = np.allclose(out, base * 3.0)
        assert seen_candidate, \
            "restarted canary never served the candidate bundle"
        assert gov.promote() == "promoted"
        assert gw._router.cohort_members("canary") == []
    finally:
        cluster.shutdown(timeout=120.0)
    assert telemetry.counter("elastic.restarts_total").value() >= 1


@pytest.mark.chaos
def test_hot_tenant_flood_sheds_only_the_hot_tenant(tmp_path, monkeypatch,
                                                    arm_driver_faults):
    """``hot_tenant`` drives one tenant to 10x its rate limit: ONLY that
    tenant sees shed (``ServeThrottled``) responses, every other tenant's
    request stream stays error-free with p99 within 2x its uncontended
    baseline."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_SERVE_TENANT_RATE", "400")
    telemetry.reset()
    # the chaos hook multiplies the HOT tenant's bucket charge by 10
    arm_driver_faults("hot_tenant:mult=10,tenant=hot")
    cluster, export = _serve_cluster(tmp_path, scale=2.0,
                                     env={"TOS_FAULTINJECT": ""})
    try:
        gw = cluster.serve(export, max_batch=8, max_delay_ms=2.0,
                           listen=False, reload_poll_secs=0)
        base = np.arange(4, dtype=np.float32)

        def drive(tenant, secs, out_lat, out_err, rows=1, pace=0.02):
            deadline = time.monotonic() + secs
            i = 0
            while time.monotonic() < deadline:
                t0 = time.monotonic()
                try:
                    got = gw.predict([base + i] * rows, timeout=30.0,
                                     tenant=tenant)
                    np.testing.assert_allclose(got[0], (base + i) * 2.0)
                    out_lat.append(time.monotonic() - t0)
                except ServeThrottled:
                    out_err.append("throttled")
                i += 1
                if pace:
                    time.sleep(pace)

        # phase 1: uncontended baseline for the well-behaved tenants
        base_lat: dict = {"a": [], "b": []}
        base_err: dict = {"a": [], "b": []}
        threads = [threading.Thread(target=drive,
                                    args=(t, 2.5, base_lat[t], base_err[t]))
                   for t in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not base_err["a"] and not base_err["b"]

        # phase 2: the hot tenant floods (16-row requests, no pacing =
        # 10x its effective 40 rows/s budget) while a and b keep their
        # modest pace
        lat: dict = {"a": [], "b": [], "hot": []}
        errs: dict = {"a": [], "b": [], "hot": []}
        threads = [threading.Thread(target=drive,
                                    args=(t, 4.0, lat[t], errs[t]))
                   for t in ("a", "b")]
        threads.append(threading.Thread(
            target=drive, args=("hot", 4.0, lat["hot"], errs["hot"]),
            kwargs={"rows": 16, "pace": 0.0}))
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # only the hot tenant was shed — and it WAS shed (the flood did
        # not ride the queue at everyone else's expense)
        assert errs["hot"], "hot tenant was never throttled at 10x its rate"
        assert not errs["a"] and not errs["b"], (errs["a"][:2], errs["b"][:2])
        assert telemetry.counter("serve.throttled_total").value() >= 1
        assert lat["a"] and lat["b"]
        for t in ("a", "b"):
            p99_base = float(np.percentile(base_lat[t], 99))
            p99_hot = float(np.percentile(lat[t], 99))
            # within 2x uncontended (+ a small absolute floor so a single
            # scheduler hiccup on the 1-core CI box cannot flake the run)
            assert p99_hot <= max(2.0 * p99_base, p99_base + 0.25), (
                t, p99_base, p99_hot)
    finally:
        cluster.shutdown(timeout=120.0)


def test_bundle_signature_tracks_reexport(tmp_path):
    export = str(tmp_path / "sig")
    export_bundle(export, linmod.init_params(LINEAR, scale=2.0), LINEAR)
    sig1 = bundle_signature(export)
    assert sig1 and all(len(entry) == 3 for entry in sig1)
    assert bundle_signature(export) == sig1  # stable while untouched
    time.sleep(0.01)
    export_bundle(export, linmod.init_params(LINEAR, scale=3.0), LINEAR)
    assert bundle_signature(export) != sig1
    assert bundle_signature(str(tmp_path / "missing")) == ()
