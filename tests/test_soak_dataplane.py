"""Bounded soak of the data plane: many small partitions through feed and
inference round-trips — shakes ring/TCP framing, EndPartition bookkeeping,
and the ordered exactly-count invariant at a partition count well above what
the e2e tests use (reference regime: hundreds of Spark partitions)."""

import pytest
import tensorflowonspark_tpu as tos
from tensorflowonspark_tpu.cluster import InputMode

import mapfuns


@pytest.mark.slow
def test_many_partition_train_and_inference(tmp_path):
    # 60 uneven partitions (sizes 0..~12) x 2 epochs through 2 nodes
    items = list(range(300))
    parts, i = [], 0
    size = 0
    while i < len(items):
        parts.append(items[i : i + size])
        i += size
        size = (size + 1) % 13
    parts.append(items[i:])
    data = tos.PartitionedDataset.from_partitions(parts)
    assert data.num_partitions >= 40

    cluster = tos.run(mapfuns.sum_batches, {"out_dir": str(tmp_path), "batch_size": 7},
                      num_executors=2, input_mode=InputMode.STREAMING,
                      reservation_timeout=60)
    cluster.train(data, num_epochs=2, shuffle_seed=5)
    cluster.shutdown()
    totals = counts = 0
    for i in range(2):
        t, c = (tmp_path / f"node_{i}.txt").read_text().split()
        totals += float(t)
        counts += int(c)
    assert counts == 600
    assert totals == 2 * sum(items)

    # inference: 47 uneven partitions, ordered exactly-count
    c2 = tos.run(mapfuns.echo_inference, {}, num_executors=2,
                 input_mode=InputMode.STREAMING, reservation_timeout=60)
    vals = list(range(211))
    preds = c2.inference(tos.PartitionedDataset.from_iterable(vals, 47))
    c2.shutdown()
    assert preds == [v * 2 for v in vals]
