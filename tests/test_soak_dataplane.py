"""Bounded soak of the data plane: many small partitions through feed and
inference round-trips — shakes ring/TCP framing, EndPartition bookkeeping,
and the ordered exactly-count invariant at a partition count well above what
the e2e tests use (reference regime: hundreds of Spark partitions)."""

import os

import pytest
import tensorflowonspark_tpu as tos
from tensorflowonspark_tpu.cluster import InputMode

import mapfuns


@pytest.mark.slow
def test_many_partition_train_and_inference(tmp_path):
    # 60 uneven partitions (sizes 0..~12) x 2 epochs through 2 nodes
    items = list(range(300))
    parts, i = [], 0
    size = 0
    while i < len(items):
        parts.append(items[i : i + size])
        i += size
        size = (size + 1) % 13
    parts.append(items[i:])
    data = tos.PartitionedDataset.from_partitions(parts)
    assert data.num_partitions >= 40

    cluster = tos.run(mapfuns.sum_batches, {"out_dir": str(tmp_path), "batch_size": 7},
                      num_executors=2, input_mode=InputMode.STREAMING,
                      reservation_timeout=60)
    cluster.train(data, num_epochs=2, shuffle_seed=5)
    cluster.shutdown()
    totals = counts = 0
    for i in range(2):
        t, c = (tmp_path / f"node_{i}.txt").read_text().split()
        totals += float(t)
        counts += int(c)
    assert counts == 600
    assert totals == 2 * sum(items)

    # inference: 47 uneven partitions, ordered exactly-count
    c2 = tos.run(mapfuns.echo_inference, {}, num_executors=2,
                 input_mode=InputMode.STREAMING, reservation_timeout=60)
    vals = list(range(211))
    preds = c2.inference(tos.PartitionedDataset.from_iterable(vals, 47))
    c2.shutdown()
    assert preds == [v * 2 for v in vals]


@pytest.mark.slow
@pytest.mark.chaos
def test_randomized_chaos_soak(tmp_path, monkeypatch):
    """Randomized fault schedule over an elastic many-partition train: one
    node's data socket severs at a random op, the other is SIGKILLed after a
    random number of batches and supervised-restarted — the job must still
    deliver every item.  The seed is printed on failure; pin it with
    ``TOS_CHAOS_SEED`` to reproduce (the deterministic single-fault variants
    live in ``test_elastic.py`` and stay tier-1)."""
    import random

    seed = int(os.environ.get("TOS_CHAOS_SEED", random.randrange(100000)))
    rng = random.Random(seed)
    # bound kill_after so the victim is always killed MID-partition (its
    # queue backlog never spans a partition boundary): consumed + capacity
    # + in-flight put < items-per-partition
    kill_after = rng.randint(2, 6)        # 3*6 + 4 + 1 < 25
    sever_after = rng.randint(1, 6)       # each node feeds 6 partitions
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "4")
    monkeypatch.setenv("TOS_RESTART_BACKOFF_BASE", "0.2")
    items = list(range(300))
    parts = [items[i * 25:(i + 1) * 25] for i in range(12)]
    per_node_env = [
        {"TOS_FAULTINJECT": f"sever:after_data_ops={sever_after}"},
        {"TOS_FAULTINJECT": f"kill:after_batches={kill_after},incarnation=0"},
    ]
    cluster = tos.run(
        mapfuns.elastic_sum_batches,
        {"batch_size": 3, "out_dir": str(tmp_path)},
        num_executors=2, input_mode=InputMode.STREAMING,
        queue_capacity=4, heartbeat_interval=0.5,
        per_node_env=per_node_env, log_dir=str(tmp_path / "logs"),
        reservation_timeout=120.0, elastic=True)
    try:
        cluster.train(parts, num_epochs=1)
        cluster.shutdown(timeout=120.0)
        seen = set()
        count = 0
        for f in tmp_path.glob("seen_*.txt"):
            vals = [int(x) for x in f.read_text().split()]
            seen.update(vals)
            count += len(vals)
        assert seen == set(items), f"lost items with TOS_CHAOS_SEED={seed}"
        assert count >= len(items)
    except BaseException:
        print(f"chaos soak failed; reproduce with TOS_CHAOS_SEED={seed}")
        raise
