"""Coordinator rendezvous tests (reference: ``test/test_reservation.py``)."""

import threading
import time

import pytest

from tensorflowonspark_tpu.coordinator import CoordinatorClient, CoordinatorServer


def test_register_and_await():
    server = CoordinatorServer(expected=3)
    addr = server.start()
    infos = []

    def node(i):
        c = CoordinatorClient(addr)
        ident = c.register({"host": "127.0.0.1", "data_port": 1000 + i})
        nodes = c.await_cluster(timeout=10)
        infos.append((ident, nodes))
        c.close()

    threads = [threading.Thread(target=node, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    cluster = server.await_registrations(timeout=10)
    for t in threads:
        t.join()
    server.stop()

    assert len(cluster) == 3
    assert [m["executor_id"] for m in cluster] == [0, 1, 2]
    assert cluster[0]["job_name"] == "chief"
    assert {m["job_name"] for m in cluster[1:]} == {"worker"}
    # every client saw the same complete cluster
    for _, nodes in infos:
        assert [m["executor_id"] for m in nodes] == [0, 1, 2]
    # assigned ids are unique
    assert sorted(i["executor_id"] for i, _ in infos) == [0, 1, 2]


def test_await_timeout():
    server = CoordinatorServer(expected=2)
    addr = server.start()
    c = CoordinatorClient(addr)
    c.register({})
    with pytest.raises(TimeoutError):
        server.await_registrations(timeout=0.3)
    c.close()
    server.stop()


def test_reduce_and_barrier():
    server = CoordinatorServer(expected=3)
    addr = server.start()
    results = {}

    def node(i):
        c = CoordinatorClient(addr)
        c.register({})
        results[(i, "sum")] = c.reduce("g1", i, kind="sum", timeout=10)
        results[(i, "all")] = c.reduce("g2", i > 0, kind="all", timeout=10)
        results[(i, "any")] = c.reduce("g3", i == 2, kind="any", timeout=10)
        results[(i, "gather")] = sorted(c.reduce("g4", i, kind="gather", timeout=10))
        c.barrier("b1", i, timeout=10)
        c.close()

    threads = [threading.Thread(target=node, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.stop()
    for i in range(3):
        assert results[(i, "sum")] == 3
        assert results[(i, "all")] is False
        assert results[(i, "any")] is True
        assert results[(i, "gather")] == [0, 1, 2]


def test_error_reporting_and_heartbeat_stop():
    server = CoordinatorServer(expected=1)
    addr = server.start()
    c = CoordinatorClient(addr)
    c.register({})
    assert c.heartbeat(0) is False
    c.report_error(0, "Traceback: boom")
    server.signal_stop()
    assert c.heartbeat(0) is True
    errs = server.errors()
    assert len(errs) == 1 and "boom" in errs[0]["traceback"]
    c.close()
    server.stop()


def test_update_meta():
    server = CoordinatorServer(expected=1)
    addr = server.start()
    c = CoordinatorClient(addr)
    c.register({"host": "h"})
    c.update_meta(0, {"tb_url": "http://x:1"})
    assert server.cluster_info()[0]["tb_url"] == "http://x:1"
    c.close()
    server.stop()


def test_authkey_handshake_accepts_matching_key():
    server = CoordinatorServer(expected=1, authkey=b"sekrit")
    addr = server.start()
    c = CoordinatorClient(addr, authkey=b"sekrit")
    ident = c.register({"host": "h"})
    assert ident["executor_id"] == 0
    c.close()
    server.stop()


def test_authkey_handshake_rejects_bad_key():
    server = CoordinatorServer(expected=1, authkey=b"sekrit")
    addr = server.start()
    with pytest.raises(ConnectionError):
        CoordinatorClient(addr, authkey=b"wrong")
    # an unauthenticated client (speaks raw JSON frames into the nonce
    # exchange) must also be refused before any op is served
    c = CoordinatorClient.__new__(CoordinatorClient)
    import socket

    raw = socket.create_connection(addr, timeout=5)
    try:
        with pytest.raises(Exception):
            c.address = addr
            c._lock = threading.Lock()
            c._sock = raw
            c._gen = 0
            c.register({"host": "h"})
        assert server.cluster_info() == []  # nothing got registered
    finally:
        raw.close()
    # the server stays alive and still serves a properly-keyed client
    ok = CoordinatorClient(addr, authkey=b"sekrit")
    ok.register({"host": "h"})
    ok.close()
    server.stop()


def test_start_advertises_routable_address():
    """The advertised address is baked into remote-consumed NodeConfigs, so
    it must never be the wildcard or loopback (VERDICT r4 missing #1) —
    but ONLY an authenticated server may bind the network; without an
    authkey the default stays loopback (no open register/stop channel)."""
    from tensorflowonspark_tpu.utils.net import local_ip

    server = CoordinatorServer(expected=1, authkey=b"k")
    addr = server.start()
    assert addr[0] == local_ip()
    assert addr[0] != "0.0.0.0"
    c = CoordinatorClient(addr, authkey=b"k")
    c.register({})
    c.close()
    server.stop()

    unauth = CoordinatorServer(expected=1)
    addr = unauth.start()
    assert addr[0] == "127.0.0.1"
    unauth.stop()


def test_pinned_interface_refuses_loopback():
    """With the bind pinned to the routable interface, a loopback dial is
    refused — proving formation does not secretly depend on same-host."""
    import socket

    from tensorflowonspark_tpu.utils.net import local_ip

    ip = local_ip()
    if ip == "127.0.0.1":
        pytest.skip("no routable interface on this host")
    server = CoordinatorServer(expected=1)
    addr = server.start(host=ip)
    assert addr[0] == ip
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", addr[1]), timeout=2)
    c = CoordinatorClient(addr)
    c.register({})
    c.close()
    server.stop()


def test_dead_node_detection():
    server = CoordinatorServer(expected=1)
    addr = server.start()
    c = CoordinatorClient(addr)
    c.register({})
    assert server.dead_nodes(heartbeat_timeout=5.0) == []
    time.sleep(0.2)
    assert server.dead_nodes(heartbeat_timeout=0.1) == [0]
    c.heartbeat(0)
    assert server.dead_nodes(heartbeat_timeout=0.15) == []
    c.close()
    server.stop()


def test_reduce_begin_pipelined():
    """Pipelined votes resolve to the same result as sync votes and may be
    mixed with them in one generation (the batch-iterator's active hosts
    pipeline while dry hosts vote synchronously)."""
    server = CoordinatorServer(expected=2)
    addr = server.start()
    results = {}

    def active_host():
        c = CoordinatorClient(addr)
        c.register({})
        pending = None
        for r in range(5):
            if pending is not None:
                results[("active", r - 1)] = pending()
            pending = c.reduce_begin(f"v:{r}", r >= 4, kind="all", timeout=10, count=2)
        results[("active", 4)] = pending()
        c.close()

    def dry_host():
        c = CoordinatorClient(addr)
        c.register({})
        for r in range(5):
            results[("dry", r)] = c.reduce(f"v:{r}", r >= 4, kind="all",
                                           timeout=10, count=2)
        c.close()

    ts = [threading.Thread(target=active_host), threading.Thread(target=dry_host)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    server.stop()
    for r in range(5):
        want = r >= 4  # all-reduce of (r>=4, r>=4)
        assert results[("active", r)] is want
        assert results[("dry", r)] is want


def test_deregister_and_mark_dead():
    """Clean exits deregister and are never flagged; mark_dead records one
    error per death and stops tracking, and a late in-flight heartbeat
    cannot resurrect a deregistered node."""
    server = CoordinatorServer(expected=2)
    addr = server.start()
    c0, c1 = CoordinatorClient(addr), CoordinatorClient(addr)
    c0.register({})
    c1.register({})
    c0.deregister(0)
    time.sleep(0.2)
    assert server.dead_nodes(heartbeat_timeout=0.1) == [1]  # 0 exited cleanly
    c0.heartbeat(0)  # late ping after deregister: must not resurrect
    assert server.dead_nodes(heartbeat_timeout=10.0) == []
    time.sleep(0.2)
    assert server.dead_nodes(heartbeat_timeout=0.1) == [1]
    server.mark_dead([1])
    assert server.dead_nodes(heartbeat_timeout=0.0) == []  # reported once
    errs = server.errors()
    assert len(errs) == 1 and errs[0]["executor_id"] == 1
    assert "stopped heartbeating" in errs[0]["traceback"]
    c0.close()
    c1.close()
    server.stop()
