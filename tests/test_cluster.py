"""End-to-end cluster lifecycle tests with real node processes
(reference: ``test/test_TFCluster.py`` over ``local-cluster[2,1,1024]``,
SURVEY.md §4 — no mocks, real processes + real sockets)."""

import os

import pytest

import tensorflowonspark_tpu as tos
from tensorflowonspark_tpu.cluster import InputMode

from tests import mapfuns


def test_input_mode_aliases():
    assert InputMode.TENSORFLOW is InputMode.DIRECT
    assert InputMode.SPARK is InputMode.STREAMING


def test_run_and_shutdown_noop():
    cluster = tos.run(mapfuns.noop, num_executors=2, reservation_timeout=60)
    assert len(cluster.cluster_info) == 2
    assert cluster.cluster_info[0]["job_name"] == "chief"
    # driver-side authoritative chip numbering from registered device facts
    plan = cluster.chip_plan()
    assert [a.executor_id for a in plan] == [0, 1]
    counts = [(m.get("device") or {}).get("num_devices") or 0
              for m in cluster.cluster_info]
    assert [a.num_chips for a in plan] == [int(c) for c in counts]
    assert plan[1].chip_start == plan[0].num_chips  # disjoint, contiguous
    cluster.shutdown()


def test_roles_and_ctx(tmp_path):
    args = {"out_dir": str(tmp_path)}
    cluster = tos.run(
        mapfuns.writes_role, args, num_executors=3, eval_node=True, reservation_timeout=60
    )
    cluster.shutdown()
    roles = sorted((tmp_path / f"role_{i}.txt").read_text() for i in range(3))
    assert roles == ["chief:0:3", "evaluator:0:3", "worker:0:3"]


def test_train_streaming_sums(tmp_path):
    args = {"out_dir": str(tmp_path), "batch_size": 5}
    cluster = tos.run(
        mapfuns.sum_batches,
        args,
        num_executors=2,
        input_mode=InputMode.STREAMING,
        reservation_timeout=60,
    )
    data = tos.PartitionedDataset.from_iterable(range(100), 4)
    # shuffle_seed reorders partitions per epoch; exactly-once delivery and
    # the global sum are order-invariant, so the invariants below also pin
    # the shuffled path
    cluster.train(data, num_epochs=2, shuffle_seed=13)
    cluster.shutdown()
    totals, counts = 0.0, 0
    for i in range(2):
        t, c = (tmp_path / f"node_{i}.txt").read_text().split()
        totals += float(t)
        counts += int(c)
    assert counts == 200  # every item delivered exactly once per epoch
    assert totals == 2 * sum(range(100))


def test_train_streams_file_references(tmp_path):
    """STREAMING a dataset of file REFERENCES (VERDICT r4 item 5 stretch):
    the driver ships shard paths, each node reads its shards' bytes itself
    — the Spark data-locality analogue.  Every row of every shard must be
    consumed exactly once across the cluster."""
    from tensorflowonspark_tpu import dfutil

    rows = [{"x": [float(i)], "label": i} for i in range(60)]
    data = tos.PartitionedDataset.from_iterable(rows, 6)
    dfutil.save_as_tfrecords(data, str(tmp_path / "shards"))

    refs = tos.PartitionedDataset.from_file_references(
        str(tmp_path / "shards" / "part-*"), num_partitions=2)
    assert refs.num_partitions == 2
    # only paths travel the wire
    assert all(isinstance(p, str) for part in (0, 1)
               for p in refs.iter_partition(part))

    out = tmp_path / "out"
    out.mkdir()
    cluster = tos.run(
        mapfuns.read_referenced_shards,
        {"out_dir": str(out)},
        num_executors=2,
        input_mode=InputMode.STREAMING,
        reservation_timeout=60,
    )
    cluster.train(refs, num_epochs=1)
    cluster.shutdown()
    total, count = 0, 0
    for i in range(2):
        t, c = (out / f"node_{i}.txt").read_text().split()
        total += int(t)
        count += int(c)
    assert count == 60                 # every row of every shard, exactly once
    assert total == sum(range(60))


def test_inference_ordered_exact(tmp_path):
    cluster = tos.run(
        mapfuns.echo_inference,
        {},
        num_executors=2,
        input_mode=InputMode.STREAMING,
        reservation_timeout=60,
    )
    data = tos.PartitionedDataset.from_iterable(range(57), 5)
    results = cluster.inference(data)
    cluster.shutdown()
    assert results == [x * 2 for x in range(57)]  # ordered, exactly-count


def test_inference_stream_lazy_and_bounded():
    """inference_stream restores the lazy-RDD property (VERDICT r2 item 8):
    partitions are read and yielded incrementally; with a small window the
    workers must NOT run ahead of the consumer, bounding driver memory."""
    reads: list[int] = []

    def part_fn(p):
        def gen():
            reads.append(p)
            yield from range(p * 10, p * 10 + 10)
        return gen

    data = tos.PartitionedDataset([part_fn(p) for p in range(10)])
    cluster = tos.run(
        mapfuns.echo_inference, {}, num_executors=2,
        input_mode=InputMode.STREAMING, reservation_timeout=60,
    )
    try:
        stream = cluster.inference_stream(data, window=2)
        p0, res0 = next(stream)
        assert p0 == 0 and res0 == [x * 2 for x in range(10)]
        # window=2 + 2 workers: at most window + workers partitions may have
        # been READ from the dataset before the consumer advanced
        assert len(reads) <= 4, f"unbounded read-ahead: {reads}"
        rest = list(stream)
    finally:
        cluster.shutdown()
    assert [p for p, _ in rest] == list(range(1, 10))
    assert all(res == [x * 2 for x in range(p * 10, p * 10 + 10)]
               for p, res in rest)
    assert sorted(reads) == list(range(10))  # every partition read exactly once


def test_error_propagation():
    cluster = tos.run(mapfuns.failing, num_executors=2, reservation_timeout=60)
    with pytest.raises(RuntimeError, match="intentional failure"):
        cluster.shutdown()


def test_early_termination_fast_drain(tmp_path):
    args = {"consume": 3}
    cluster = tos.run(
        mapfuns.early_terminator,
        args,
        num_executors=1,
        input_mode=InputMode.STREAMING,
        reservation_timeout=60,
    )
    # far more data than the node will consume; must not hang
    data = tos.PartitionedDataset.from_iterable(range(50_000), 2)
    cluster.train(data)
    cluster.shutdown()


def test_consensus_excludes_evaluator(tmp_path):
    """all_done must be scoped to data nodes or it deadlocks with eval_node."""
    args = {"out_dir": str(tmp_path)}
    cluster = tos.run(
        mapfuns.consensus_with_eval, args, num_executors=3, eval_node=True,
        reservation_timeout=60,
    )
    cluster.shutdown(timeout=60)
    rounds = [int((tmp_path / f"rounds_{i}.txt").read_text()) for i in range(2)]
    assert rounds == [2, 2]


def test_global_done_consensus(tmp_path):
    args = {"out_dir": str(tmp_path)}
    cluster = tos.run(mapfuns.barrier_user, args, num_executors=3, reservation_timeout=60)
    cluster.shutdown()
    rounds = [int((tmp_path / f"rounds_{i}.txt").read_text()) for i in range(3)]
    # all nodes leave the loop on the same (last) round: consensus, not local state
    assert rounds == [3, 3, 3]


def test_cluster_forms_over_routable_ip_only(tmp_path):
    """Real off-box parity (VERDICT r4 missing #1): nodes are handed ONLY the
    driver's routable IP, the coordinator is pinned to that interface (so a
    loopback dial would be refused — see
    test_pinned_interface_refuses_loopback), and no ``127.0.0.1`` leaks into
    any remote-consumed metadata (NodeConfig.coordinator_addr, registered
    hosts)."""
    import pickle

    from tensorflowonspark_tpu.launcher import SubprocessLauncher
    from tensorflowonspark_tpu.utils.net import local_ip

    ip = local_ip()
    if ip == "127.0.0.1":
        pytest.skip("no routable interface on this host")

    captured = []

    class CapturingLauncher(SubprocessLauncher):
        def launch(self, configs, log_dir=None):
            captured.extend(configs)
            super().launch(configs, log_dir)

    cluster = tos.run(mapfuns.noop, num_executors=2, reservation_timeout=60,
                      launcher=CapturingLauncher(), coordinator_host=ip)
    try:
        assert len(captured) == 2
        for cfg in captured:
            assert cfg.coordinator_addr[0] == ip
            # nothing loopback anywhere in the node-consumed config
            assert b"127.0.0.1" not in pickle.dumps(cfg.coordinator_addr)
        for m in cluster.cluster_info:
            assert m["host"] == ip, f"registered host leaked loopback: {m['host']}"
    finally:
        cluster.shutdown()


def test_env_tunable_timeouts(monkeypatch):
    """TOS_RESERVATION_TIMEOUT / TOS_FEED_TIMEOUT env defaults (reference:
    TFOS_SERVER_TIMEOUT-style ops knobs) apply when the kwargs are omitted;
    explicit kwargs always win; junk values fall back with a warning."""
    monkeypatch.setenv("TOS_RESERVATION_TIMEOUT", "7.5")
    monkeypatch.setenv("TOS_FEED_TIMEOUT", "33")
    cluster = tos.run(mapfuns.noop, num_executors=1)
    try:
        assert cluster.feed_timeout == 33.0
    finally:
        cluster.shutdown()
    monkeypatch.setenv("TOS_FEED_TIMEOUT", "not-a-number")
    cluster = tos.run(mapfuns.noop, num_executors=1, reservation_timeout=60)
    try:
        assert cluster.feed_timeout == 600.0  # junk ignored
    finally:
        cluster.shutdown()
