"""DataFeed / FeedQueues semantics (reference ``TFNode.DataFeed`` spec,
SURVEY.md §3.2 + §4 'queue/timeout edge cases')."""

import threading

from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues
from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition


def make_feed(**kw):
    queues = FeedQueues()
    return queues, DataFeed(queues, **kw)


def test_next_batch_full_and_partial():
    queues, feed = make_feed()
    q = queues.get_queue("input")
    for i in range(5):
        q.put(i)
    q.put(EndPartition())
    q.put(EndOfFeed())
    assert feed.next_batch(3) == [0, 1, 2]
    # partial batch at end of partition
    assert feed.next_batch(3) == [3, 4]
    assert not feed.should_stop()
    # end of feed -> empty batch, done_feeding set
    assert feed.next_batch(3) == []
    assert feed.should_stop()


def test_empty_partition_skipped():
    queues, feed = make_feed()
    q = queues.get_queue("input")
    q.put(EndPartition())  # empty partition should not yield an empty batch
    q.put(7)
    q.put(EndOfFeed())
    assert feed.next_batch(2) == [7]


def test_none_is_ordinary_data():
    # Delta from the reference (which used bare None as end-of-feed): samples
    # with optional fields must survive the feed; only EndOfFeed terminates.
    queues, feed = make_feed()
    q = queues.get_queue("input")
    q.put(None)
    q.put(1)
    q.put(EndOfFeed())
    assert feed.next_batch(5) == [None, 1]
    assert feed.should_stop()


def test_input_mapping_columns():
    queues, feed = make_feed(input_mapping={"col_x": "x", "col_y": "y"})
    q = queues.get_queue("input")
    q.put((1, 10))
    q.put((2, 20))
    q.put(EndPartition())
    batch = feed.next_batch(5)
    assert batch == {"x": [1, 2], "y": [10, 20]}


def test_batch_results_roundtrip():
    queues, feed = make_feed(train_mode=False)
    feed.batch_results([1, 2, 3])
    out = queues.get_queue("output")
    assert [out.get() for _ in range(3)] == [1, 2, 3]


def test_terminate_drains_input():
    queues, feed = make_feed()
    q = queues.get_queue("input")
    for i in range(50):
        q.put(i)
    feed.terminate()
    assert queues.get("state") == "terminating"
    assert q.qsize() == 0
    assert feed.should_stop()


def test_blocking_get_unblocked_by_producer():
    queues, feed = make_feed()
    q = queues.get_queue("input")
    got = []

    def consumer():
        got.extend(feed.next_batch(2))

    t = threading.Thread(target=consumer)
    t.start()
    q.put(41)
    q.put(42)
    t.join(5)
    assert got == [41, 42]
