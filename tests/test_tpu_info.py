"""tpu_info (gpu_info replacement) and profiling subsystem."""

import glob
import os

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu import profiling, tpu_info


def test_device_summary_reports_cpu_platform():
    s = tpu_info.device_summary()
    assert s["platform"] == "cpu"
    assert s["num_devices"] == 8  # conftest virtual devices
    assert len(s["coords"]) == 8


def test_is_tpu_available_false_on_cpu():
    assert tpu_info.is_tpu_available() is False


def test_plan_topology_contiguous_no_overlap():
    plan = tpu_info.plan_topology([4, 4, 8])
    assert [a.chip_start for a in plan] == [0, 4, 8]
    assert tpu_info.total_chips(plan) == 16
    seen = set()
    for a in plan:
        assert not (seen & set(a.chip_ids))
        seen |= set(a.chip_ids)
    assert seen == set(range(16))


def test_default_mesh_axes():
    assert tpu_info.default_mesh_axes(16) == {"dp": 16, "tp": 1}
    assert tpu_info.default_mesh_axes(16, model_parallel=4) == {"dp": 4, "tp": 4}


def test_chip_visibility_env_tpu_square_and_linear():
    env = tpu_info.chip_visibility_env([0, 1, 2, 3])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
    env = tpu_info.chip_visibility_env([4, 5])
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"


def test_chip_visibility_env_cpu_simulation():
    env = tpu_info.chip_visibility_env([], platform="cpu", simulate_chips=8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "device_count=8" in env["XLA_FLAGS"]
    assert env["JAX_NUM_CPU_DEVICES"] == "8"
    assert env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] == "gloo"


def test_bounds_from_coords_dense_box():
    # 2x2x1 host block (v2/v3 host layout)
    coords = [[0, 0, 0], [1, 0, 0], [0, 1, 0], [1, 1, 0]]
    assert tpu_info.bounds_from_coords(coords) == "2,2,1"
    # offset boxes are still dense
    coords = [[2, 4, 0], [3, 4, 0]]
    assert tpu_info.bounds_from_coords(coords) == "2,1,1"
    assert tpu_info.bounds_from_coords([[5, 7, 1]]) == "1,1,1"


def test_bounds_from_coords_holes_and_dupes_are_none():
    # hole: 3 chips spanning a 2x2 box
    assert tpu_info.bounds_from_coords([[0, 0, 0], [1, 0, 0], [1, 1, 0]]) is None
    # duplicate coordinate
    assert tpu_info.bounds_from_coords([[0, 0, 0], [0, 0, 0]]) is None
    # malformed: 2-d coords
    assert tpu_info.bounds_from_coords([[0, 0], [1, 0]]) is None
    # empty
    assert tpu_info.bounds_from_coords([]) is None


def test_profiler_trace_writes_tensorboard_profile(tmp_path):
    log_dir = str(tmp_path / "prof")

    x = jnp.ones((64, 64))
    f = jax.jit(lambda a: a @ a)

    def step():
        return f(x).block_until_ready()

    profiling.profile_steps(log_dir, step, warmup=1, steps=2)
    produced = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, f"no xplane trace under {log_dir}"
