"""Ring/Ulysses sequence parallelism vs dense attention (8 virtual CPU devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.ops import attention as att
from tensorflowonspark_tpu.parallel import mesh as meshlib
from tensorflowonspark_tpu.parallel import sp as splib


def global_qkv(b=4, s=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = meshlib.make_mesh(dp=2, sp=4)
    q, k, v = global_qkv()
    ref = att.mha_reference(q, k, v, causal=causal)
    out = splib.sequence_parallel_attention(mesh, q, k, v, causal=causal,
                                            impl="ring")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    mesh = meshlib.make_mesh(dp=2, sp=4)
    q, k, v = global_qkv()
    ref = att.mha_reference(q, k, v, causal=causal)
    out = splib.sequence_parallel_attention(mesh, q, k, v, causal=causal,
                                            impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_sp8():
    mesh = meshlib.make_mesh(sp=8)
    q, k, v = global_qkv(b=2, s=64)
    ref = att.mha_reference(q, k, v, causal=True)
    out = splib.sequence_parallel_attention(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_grads_match_dense():
    mesh = meshlib.make_mesh(sp=4, dp=2)
    q, k, v = global_qkv(b=2, s=32, h=2, d=8)

    def loss_ring(q, k, v):
        o = splib.sequence_parallel_attention(mesh, q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(att.mha_reference(q, k, v, causal=True).astype(jnp.float32) ** 2)

    # jit the grads: one cached program instead of op-by-op eager tracing
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ring_attention_jit_with_sharded_inputs():
    # Under jit with mesh-sharded operands (the way a model would call it).
    mesh = meshlib.make_mesh(sp=4, dp=2)
    q, k, v = global_qkv()
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(("dp", "fsdp"), "sp", "tp", None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    fn = jax.jit(lambda q, k, v: splib.sequence_parallel_attention(
        mesh, q, k, v, causal=True))
    out = fn(qs, ks, vs)
    ref = att.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
