"""Sharded embedding tier (ISSUE 19): plan/init/checkpoint units, sparse
collective exactness on a real 2-node cluster, the 2-node sharded
wide-and-deep run matching the single-process unsharded reference
bit-for-bit, SIGKILL-of-a-shard-owner chaos recovery, and the sharded
serving fan-out end to end.

The parity tests compare sha256 digests of whole param/table trees, not
tolerances: the sparse path owns ONE summation kernel (``combine_csr``,
rank-order concat + unbuffered ``np.add.at``) and the dense ring's
world-2 mean is commutative-exact, so a sharded trajectory that drifts
by one ulp from the reference is a bug, not noise.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import cluster as tcluster
from tensorflowonspark_tpu.checkpoint import (
    latest_embedding_step,
    restore_embedding_shard,
    save_embedding_shard,
)
from tensorflowonspark_tpu.collective import ops as cops
from tensorflowonspark_tpu.collective import pack_csr, unpack_csr
from tensorflowonspark_tpu.collective.transport import payload_nbytes
from tensorflowonspark_tpu.embedding import (
    EmbeddingShard,
    ShardedTable,
    ShardPlan,
    init_rows,
)
from tensorflowonspark_tpu.launcher import SubprocessLauncher

import mapfuns


# -- plan / init units --------------------------------------------------------


def test_plan_bounds_ownership_partition():
    plan = ShardPlan.even("t", 10, 3, 3)
    assert plan.bounds == (0, 3, 6, 10)
    assert plan.world == 3
    assert plan.range_of(2) == (6, 10)
    assert plan.rows_of(2) == 4
    ids = np.array([0, 2, 3, 5, 6, 9], np.int64)
    assert plan.owner_of(ids).tolist() == [0, 0, 1, 1, 2, 2]
    idx = plan.partition(ids)
    # partition index arrays cover every position exactly once
    assert sorted(np.concatenate(idx).tolist()) == list(range(ids.size))
    assert ids[idx[1]].tolist() == [3, 5]
    with pytest.raises(ValueError, match="outside"):
        plan.owner_of(np.array([10], np.int64))


def test_plan_manifest_roundtrip_and_reshard():
    plan = ShardPlan.even("wide_deep", 101, 5, 2)
    block = plan.to_manifest()
    assert ShardPlan.from_manifest(block) == plan
    # reshard to a different world keeps geometry, re-cuts bounds
    r3 = plan.reshard(3)
    assert r3.total_rows == 101 and r3.dim == 5 and r3.world == 3
    assert r3.bounds[0] == 0 and r3.bounds[-1] == 101


def test_init_rows_slices_are_block_deterministic():
    # a slice crossing the 4096-row block boundary must equal the same
    # slice of a full-table init: shard init never depends on the cut
    total, dim = 5000, 3
    full = init_rows(total, dim, 0, total, seed=7)
    np.testing.assert_array_equal(init_rows(total, dim, 4000, 4500, seed=7),
                                  full[4000:4500])
    # different seed, different table
    assert not np.array_equal(init_rows(total, dim, 0, 8, seed=8), full[:8])


def test_shard_create_zero_cols_and_range_checks():
    plan = ShardPlan.even("t", 12, 4, 2)
    shard = EmbeddingShard.create(plan, 1, seed=3, zero_cols=(3,))
    assert (shard.lo, shard.hi) == (6, 12)
    assert shard.rows.shape == (6, 4)
    np.testing.assert_array_equal(shard.rows[:, 3], np.zeros(6, np.float32))
    # first columns carry the deterministic init
    np.testing.assert_array_equal(shard.rows[:, :3],
                                  init_rows(12, 4, 6, 12, seed=3)[:, :3])
    with pytest.raises(ValueError, match="outside"):
        shard.lookup(np.array([2], np.int64))  # rank 0's rows


# -- CSR wire payloads --------------------------------------------------------


def test_pack_unpack_csr_roundtrip_and_metering():
    ids = np.array([3, 1, 7], np.int64)
    vals = np.arange(6, dtype=np.float32).reshape(3, 2)
    payload = pack_csr(ids, vals)
    got_ids, got_vals = unpack_csr(payload)
    np.testing.assert_array_equal(got_ids, ids)
    np.testing.assert_array_equal(got_vals, vals)
    assert payload_nbytes(payload) == ids.nbytes + vals.nbytes
    # id-only request frames (the lookup request leg)
    req = pack_csr(ids, None)
    assert unpack_csr(req)[1] is None
    assert payload_nbytes(req) == ids.nbytes
    with pytest.raises(ValueError, match="mismatch"):
        pack_csr(ids, vals[:2])


def test_combine_csr_exact_sum_and_order():
    dim = 2
    # duplicates within one contributor AND across contributors
    u, acc = cops.combine_csr(
        [np.array([5, 1, 5], np.int64), np.array([1, 9], np.int64)],
        [np.array([[1, 2], [3, 4], [10, 20]], np.float32),
         np.array([[100, 200], [7, 8]], np.float32)],
        dim)
    assert u.tolist() == [1, 5, 9]
    np.testing.assert_array_equal(
        acc, np.array([[103, 204], [11, 22], [7, 8]], np.float32))
    # empty combine keeps the dim
    u0, a0 = cops.combine_csr([np.empty(0, np.int64)], [None], dim)
    assert u0.size == 0 and a0.shape == (0, dim)


# -- shard checkpoints: save / reassemble / gaps ------------------------------


def test_shard_checkpoint_reassembles_any_range(tmp_path):
    total, dim = 12, 3
    full = init_rows(total, dim, 0, total, seed=1)
    save_embedding_shard(str(tmp_path), "t", 4, 0, 5, full[0:5])
    save_embedding_shard(str(tmp_path), "t", 4, 5, 12, full[5:12])
    # any [lo, hi) reassembles from the covering files, bit for bit —
    # including ranges straddling the original cut (train W != serve W)
    np.testing.assert_array_equal(
        restore_embedding_shard(str(tmp_path), "t", 4, 3, 9, dim),
        full[3:9])
    np.testing.assert_array_equal(
        restore_embedding_shard(str(tmp_path), "t", 4, 0, 12, dim), full)
    assert latest_embedding_step(str(tmp_path), "t") == 4
    # a coverage gap is an error, not silent zeros
    os.remove(os.path.join(str(tmp_path), "embed_t", "step_4",
                           "shard_5_12.npz"))
    with pytest.raises(FileNotFoundError):
        restore_embedding_shard(str(tmp_path), "t", 4, 3, 9, dim)


# -- world-1 table: the reference path ----------------------------------------


def test_world1_table_lookup_update_math(monkeypatch):
    plan = ShardPlan.even("t", 8, 2, 1)
    shard = EmbeddingShard(plan, 0, np.ones((8, 2), np.float32))
    table = ShardedTable(shard, None)
    ids = np.array([[3, 3], [5, 3]], np.int64)
    out = table.lookup(ids)
    assert out.shape == (2, 2, 2)
    np.testing.assert_array_equal(out, np.ones((2, 2, 2), np.float32))
    # update: id 3 appears 3x with grad 1 -> summed 3, scaled 0.5, lr 0.5
    grads = np.ones((2, 2, 2), np.float32)
    n = table.apply_gradients(ids, grads, lr=0.5, scale=0.5)
    assert n == 2  # unique rows updated
    np.testing.assert_array_equal(
        shard.rows[3], np.array([1 - 0.5 * 0.5 * 3] * 2, np.float32))
    np.testing.assert_array_equal(
        shard.rows[5], np.array([1 - 0.5 * 0.5 * 1] * 2, np.float32))
    # dedup off must produce the same math (combine_csr still exact-sums)
    monkeypatch.setenv("TOS_EMBED_DEDUP", "0")
    shard2 = EmbeddingShard(plan, 0, np.ones((8, 2), np.float32))
    table2 = ShardedTable(shard2, None)
    np.testing.assert_array_equal(table2.lookup(ids), out)
    table2.apply_gradients(ids, grads, lr=0.5, scale=0.5)
    np.testing.assert_array_equal(shard2.rows, shard.rows)


def test_maybe_checkpoint_every_knob(tmp_path, monkeypatch):
    plan = ShardPlan.even("t", 4, 2, 1)
    table = ShardedTable(EmbeddingShard.create(plan, 0, seed=0), None)
    assert table.maybe_checkpoint(str(tmp_path), 3) is False  # disabled
    monkeypatch.setenv("TOS_EMBED_CKPT_EVERY", "2")
    assert table.maybe_checkpoint(str(tmp_path), 3) is False
    assert table.maybe_checkpoint(str(tmp_path), 4) is True
    assert latest_embedding_step(str(tmp_path), "t") == 4


# -- wide_deep dense-model plumbing (satellite 1) -----------------------------


def test_wide_deep_dense_ids_and_registry():
    from tensorflowonspark_tpu.models import wide_deep
    from tensorflowonspark_tpu.models.registry import build

    config = {"model": "wide_deep_dense", "vocab_size": 97, "embed_dim": 4}
    assert wide_deep.table_total_rows(config) == 26 * 97
    feats = mapfuns.criteo_batch(0, 0, 4)["features"]
    ids = wide_deep.flat_categorical_ids(feats, 97)
    assert ids.shape == (4, 26) and ids.dtype == np.int64
    # column c's ids live in [c*vocab, (c+1)*vocab) — disjoint offsets
    for c in range(26):
        assert (ids[:, c] // 97 == c).all()
    model = build(config)
    assert model.vocab_size == 97 and model.embed_dim == 4


def test_wide_deep_monolithic_vocab_plumbed():
    """The footgun fix: registry configs carry vocab_size through to the
    monolithic model (tests must not silently build 100k-vocab tables)."""
    from tensorflowonspark_tpu.models.registry import build

    model = build({"model": "wide_deep", "vocab_size": 1009})
    assert model.vocab_size == 1009


# -- cluster: sparse collectives (satellite 3) --------------------------------


def test_sparse_collectives_cluster_probe(tmp_path):
    cluster = tcluster.run(
        mapfuns.embedding_probe, {}, num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        launcher=SubprocessLauncher(), log_dir=str(tmp_path),
        reservation_timeout=120.0)
    cluster.shutdown(timeout=180.0)
    probes = {m["executor_id"]: m.get("embed_probe")
              for m in cluster.coordinator.cluster_info()}
    assert all(p is not None for p in probes.values()), probes
    plan = ShardPlan.even("probe", 40, 3, 2)
    for eid, p in probes.items():
        r = p["rank"]
        assert p["world"] == 2 and r == eid
        # all-to-all echo: received[src] == src's payload for us
        assert p["echo_ids"] == [[s * 100 + r] for s in range(2)]
        # exact-sum reduce-scatter: rank r contributed rows of (r+1) for
        # ids [1, 1, 30+r, 7]; expected per-id sums in rank order
        lo, hi = plan.range_of(r)
        expect = {}
        for src in range(2):
            for i in (1, 1, 30 + src, 7):
                if lo <= i < hi:
                    expect[i] = expect.get(i, 0.0) + float(src + 1)
        got = dict(zip(p["got_ids"],
                       [row[0] for row in p["got_rows"]]))
        assert got == dict(sorted(expect.items())), (r, got, expect)
        # every received row is constant across dim
        for row in p["got_rows"]:
            assert row == [row[0]] * 3
        # dense parity: scatter of the sparse result == the dense
        # all-reduced gradient's slice, bit for bit
        assert p["dense_match"] is True
        # empty-partition edge: ids 0/2 all belong to rank 0
        if lo <= 0 < hi:
            assert p["empty_ids"] == [0, 2]
        else:
            assert p["empty_ids"] == []
            assert p["empty_shape"] == [0, 3]


# -- cluster: 2-node sharded run == single-process reference ------------------


WD_CONFIG = {"model": "wide_deep_dense", "vocab_size": 97, "embed_dim": 4,
             "hidden": (8,), "bf16": False}


def _reference_sharded_run(config, steps, bsz, table_seed, lr=0.125,
                           ranks=2):
    """Single-process unsharded replay of the SAME per-node batch schedule:
    world-1 table (plain gathers/updates over the full table), dense grads
    combined with the ring's commutative world-2 mean, sparse grads
    combined through the same two-level rank-order ``combine_csr`` the
    distributed reduce-scatter pins."""
    import jax
    import optax

    from tensorflowonspark_tpu.models import wide_deep

    dim = int(config["embed_dim"]) + 1
    plan = ShardPlan.even("wide_deep", wide_deep.table_total_rows(config),
                          dim, 1)
    shard = EmbeddingShard.create(plan, 0, seed=table_seed,
                                  zero_cols=(dim - 1,))
    table = ShardedTable(shard, None)
    model = wide_deep.build_wide_deep_dense(config)
    params = wide_deep.init_dense_params(model, jax.random.PRNGKey(0))
    grad_fn = wide_deep.make_sharded_grad_fn(model)
    optimizer = optax.sgd(lr)
    opt_state = optimizer.init(params)
    vocab = int(config["vocab_size"])
    losses = [[] for _ in range(ranks)]
    scale = np.float32(1.0 / ranks)
    for step in range(steps):
        per_rank = []
        for r in range(ranks):
            batch = mapfuns.criteo_batch(r, step, bsz)
            ids = wide_deep.flat_categorical_ids(batch["features"], vocab)
            rows = table.lookup(ids)
            (loss, _aux), (dg, rg) = grad_fn(params, rows, batch)
            per_rank.append((ids, np.asarray(jax.device_get(rg)), dg))
            losses[r].append(float(loss))
        import jax as _jax
        dg = _jax.tree.map(
            lambda a, b: ((np.asarray(a, np.float32)
                           + np.asarray(b, np.float32))
                          / np.float32(ranks)),
            per_rank[0][2], per_rank[1][2])
        updates, opt_state = optimizer.update(dg, opt_state, params)
        params = optax.apply_updates(params, updates)
        locals_ = [cops.combine_csr([ids], [g.reshape(ids.size, dim)], dim)
                   for ids, g, _ in per_rank]
        cu, ca = cops.combine_csr([u for u, _ in locals_],
                                  [a for _, a in locals_], dim)
        shard.apply_grad_rows(cu, ca * scale, lr)
    return jax.device_get(params), shard, losses


def test_sharded_train_matches_single_process_bitwise(tmp_path):
    """ISSUE 19 acceptance: 2-node sharded wide-and-deep sync training ==
    the single-process unsharded reference, bit for bit — digests of the
    dense params AND the reassembled table are equal after N steps."""
    steps, bsz, table_seed = 4, 8, 11
    cluster = tcluster.run(
        mapfuns.train_wide_deep_sharded,
        {"model_config": WD_CONFIG, "steps": steps, "batch_size": bsz,
         "table_seed": table_seed},
        num_executors=2, input_mode=tcluster.InputMode.STREAMING,
        launcher=SubprocessLauncher(), log_dir=str(tmp_path),
        reservation_timeout=120.0)
    cluster.shutdown(timeout=300.0)
    metas = {m["executor_id"]: m.get("sharded_train")
             for m in cluster.coordinator.cluster_info()}
    assert all(v is not None for v in metas.values()), metas

    ref_params, ref_shard, ref_losses = _reference_sharded_run(
        WD_CONFIG, steps, bsz, table_seed)
    ref_dense = mapfuns.tree_digest(ref_params)
    plan = ref_shard.plan.reshard(2)
    for eid, meta in metas.items():
        assert meta["steps"] == steps
        # per-step losses replay exactly (same params, same rows, same
        # jitted program)
        assert meta["losses"] == ref_losses[eid]
        # dense halves identical on both nodes and equal to the reference
        assert meta["dense_digest"] == ref_dense
        # each node's shard == the reference table's slice for its range
        lo, hi = plan.range_of(eid)
        assert meta["shard_range"] == [lo, hi]
        assert meta["shard_digest"] == mapfuns.tree_digest(
            {"rows": ref_shard.rows[lo:hi]})
        # the sparse path actually exchanged ids/rows (not a local fallback)
        assert meta["stats"]["ids_sent"] > 0
        assert meta["stats"]["grad_rows_sent"] > 0
        assert meta["stats"]["lookups"] == steps


# -- cluster: SIGKILL a shard owner mid-step (satellite 2) --------------------


def test_sharded_embed_chaos_kill_shard_owner(tmp_path, monkeypatch):
    """SIGKILL the node owning the upper shard range mid-sync-step: the
    survivor aborts the poisoned round, the supervised restart rejoins at
    the generation barrier, everyone min-votes the newest complete
    (shard + dense) checkpoint, restores, and replays — exact step
    accounting and digests equal to the fault-free run."""
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "3")
    config = {"model": "wide_deep_dense", "vocab_size": 53, "embed_dim": 3,
              "hidden": (8,), "bf16": False}
    steps, bsz = 4, 8
    model_dir = str(tmp_path / "ckpt")
    os.makedirs(model_dir, exist_ok=True)
    cluster = tcluster.run(
        mapfuns.sharded_embed_chaos,
        {"model_config": config, "steps": steps, "batch_size": bsz,
         "model_dir": model_dir},
        num_executors=2, input_mode=tcluster.InputMode.STREAMING,
        launcher=SubprocessLauncher(), log_dir=str(tmp_path),
        heartbeat_interval=0.5, elastic=True,
        env={"TOS_FAULTINJECT":
             "kill_collective:after_rounds=3,executor=1,incarnation=0"},
        reservation_timeout=120.0)
    # poll metas with a deadline BEFORE shutdown: the driver must observe
    # both nodes' final meta (including the restarted incarnation's)
    deadline = time.monotonic() + 240.0
    metas = {}
    while time.monotonic() < deadline:
        metas = {m["executor_id"]: m.get("embed_chaos")
                 for m in cluster.coordinator.cluster_info()}
        if all(v is not None for v in metas.values()):
            break
        time.sleep(0.5)
    cluster.shutdown(timeout=300.0)
    assert all(v is not None for v in metas.values()), metas

    ref_params, ref_shard, _losses = _reference_sharded_run(
        config, steps, bsz, table_seed=5)
    ref_dense = mapfuns.tree_digest(ref_params)
    plan = ref_shard.plan.reshard(2)
    assert metas[1]["incarnation"] == 1          # the victim restarted
    assert max(m["reforms"] for m in metas.values()) >= 1
    assert max(m["generation"] for m in metas.values()) >= 2
    for eid, meta in metas.items():
        assert meta["steps"] == steps            # exact step accounting
        assert meta["dense_digest"] == ref_dense
        lo, hi = plan.range_of(eid)
        assert meta["shard_digest"] == mapfuns.tree_digest(
            {"rows": ref_shard.rows[lo:hi]})
    assert cluster.supervisor.restart_count(1) == 1


# -- pipeline + serving: estimator-driven sharded train -> gateway ------------


def test_estimator_sharded_train_and_gateway_serving(tmp_path):
    """The whole tier end to end: TPUEstimator drives a sync sharded
    train over streamed synthetic-Criteo rows (the embedding plan rides
    the manifest), the export carries the dense bundle + per-node shard
    files, and a fresh 2-replica serve cluster answers through the
    gateway's lookup fan-out — predictions equal the local dense-model
    computation over the reassembled table."""
    import jax

    from tensorflowonspark_tpu import pipeline, serving
    from tensorflowonspark_tpu.models import wide_deep

    config = {"model": "wide_deep_dense", "vocab_size": 101, "embed_dim": 4,
              "hidden": (8,), "bf16": False}
    dim = int(config["embed_dim"]) + 1
    plan = ShardPlan.even("wide_deep", wide_deep.table_total_rows(config),
                          dim, 2)
    export = str(tmp_path / "export")
    rows = wide_deep.synthetic_criteo(64, seed=3)
    est = pipeline.TPUEstimator(
        mapfuns.estimator_wide_deep_sharded,
        {"model_config": config, "lr": 0.125})
    est.setNumExecutors(2).setEpochs(1).setBatchSize(8)
    est.set("export_dir", export)
    est.set("log_dir", str(tmp_path / "logs"))
    est.set("train_mode", "sync")
    est.set("embedding_plan", plan.to_manifest())
    est.set("steps", 3)
    from tensorflowonspark_tpu.pipeline import PartitionedDataset

    est.fit(PartitionedDataset.from_iterable(rows, 2))

    # the export is a sharded bundle: dense config block + shard files
    with open(os.path.join(export, "bundle.json")) as f:
        bundle_config = json.load(f)
    block = bundle_config["sharded_embedding"]
    assert block["name"] == "wide_deep"
    assert block["total_rows"] == plan.total_rows and block["dim"] == dim
    full_rows = restore_embedding_shard(export, "wide_deep", block["step"],
                                        0, plan.total_rows, dim)
    # the manifest carried the plan to the nodes
    metas = {m["executor_id"]: m.get("sharded_train")
             for m in est.last_cluster_info}
    assert all(v is not None for v in metas.values()), metas
    for meta in metas.values():
        assert meta["manifest_embedding"] == plan.to_manifest()
        assert meta["stats"]["ids_sent"] > 0

    # serve: 2 replicas, each resident with its re-sharded range, embed
    # queue pair for the router's lookup fan-out
    serve_cluster = tcluster.run(
        serving.serving_loop, {"export_dir": export, "max_batch": 8},
        num_executors=2, input_mode=tcluster.InputMode.STREAMING,
        queues=("input", "output", "error", "embed", "embed_out"),
        launcher=SubprocessLauncher(), log_dir=str(tmp_path),
        heartbeat_interval=0.5, reservation_timeout=120.0)
    try:
        gw = serve_cluster.serve(export, max_batch=8, max_delay_ms=5.0,
                                 reload_poll_secs=0)
        query = [np.asarray(r["features"], np.float32)
                 for r in wide_deep.synthetic_criteo(6, seed=99)]
        out = gw.predict(query, timeout=120.0)
        assert len(out) == 6
        # local expectation: gather from the reassembled table, apply the
        # dense bundle
        from tensorflowonspark_tpu.checkpoint import load_bundle

        params, _cfg = load_bundle(export)
        model = wide_deep.build_wide_deep_dense(config)
        feats = np.stack(query)
        ids = wide_deep.flat_categorical_ids(feats, 101)
        emb = full_rows[ids]
        expect = np.asarray(model.apply(
            {"params": params} if "params" not in params else params,
            feats, emb))
        np.testing.assert_allclose(np.asarray([float(o) for o in out]),
                                   expect, rtol=1e-5, atol=1e-6)
    finally:
        serve_cluster.shutdown(timeout=300.0)
