"""SummaryWriter event files must be readable by TensorBoard's own loader."""

import glob
import importlib.util

import pytest

from tensorflowonspark_tpu.summary import SummaryWriter

HAVE_TB = importlib.util.find_spec("tensorboard") is not None


def test_writes_event_file(tmp_path):
    with SummaryWriter(str(tmp_path)) as w:
        w.add_scalar("loss", 1.5, step=1)
        w.add_scalars({"loss": 1.0, "acc": 0.5}, step=2)
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1


@pytest.mark.skipif(not HAVE_TB, reason="tensorboard not installed")
@pytest.mark.slow
def test_tensorboard_can_parse(tmp_path):
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    with SummaryWriter(str(tmp_path)) as w:
        for step in range(5):
            w.add_scalar("loss", 10.0 - step, step=step)
        w.add_scalar("acc", 0.9, step=4)

    acc = EventAccumulator(str(tmp_path))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == {"loss", "acc"}
    events = acc.Scalars("loss")
    assert [e.step for e in events] == list(range(5))
    assert events[0].value == pytest.approx(10.0)
    assert events[4].value == pytest.approx(6.0)
