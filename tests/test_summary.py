"""SummaryWriter event files must be readable by TensorBoard's own loader."""

import glob
import importlib.util

import pytest

from tensorflowonspark_tpu.summary import SummaryWriter

HAVE_TB = importlib.util.find_spec("tensorboard") is not None


def test_writes_event_file(tmp_path):
    with SummaryWriter(str(tmp_path)) as w:
        w.add_scalar("loss", 1.5, step=1)
        w.add_scalars({"loss": 1.0, "acc": 0.5}, step=2)
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1


def test_close_is_idempotent_and_unregisters_atexit(tmp_path):
    import atexit

    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 1.0, step=1)
    w.close()
    w.close()  # double close (explicit + context manager / atexit) is a no-op
    # the atexit hook was unregistered: interpreter exit won't re-close
    atexit.unregister(w.close)  # no-op if already gone; must not raise
    with pytest.raises(ValueError):
        w._writer.write(b"x")  # underlying file really closed


def test_records_hit_disk_at_flush_boundaries_without_close(tmp_path):
    """Elastic-restart robustness: a writer with flush_secs=0 flushes at
    every record boundary, so a SIGKILLed node leaves a complete event file
    from the OS's point of view — no truncated mid-record tail."""
    import glob as g

    w = SummaryWriter(str(tmp_path), flush_secs=0.0)
    for step in range(3):
        w.add_scalar("loss", float(step), step=step)
    path = g.glob(str(tmp_path / "events.out.tfevents.*"))[0]
    import os

    size_before_close = os.path.getsize(path)
    w.close()
    # nothing was still buffered: close added no bytes
    assert os.path.getsize(path) == size_before_close
    from tensorflowonspark_tpu.tfrecord import read_records

    records = list(read_records(path))
    assert len(records) == 4  # file_version event + 3 scalars


@pytest.mark.skipif(not HAVE_TB, reason="tensorboard not installed")
@pytest.mark.slow
def test_tensorboard_can_parse(tmp_path):
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    with SummaryWriter(str(tmp_path)) as w:
        for step in range(5):
            w.add_scalar("loss", 10.0 - step, step=step)
        w.add_scalar("acc", 0.9, step=4)

    acc = EventAccumulator(str(tmp_path))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == {"loss", "acc"}
    events = acc.Scalars("loss")
    assert [e.step for e in events] == list(range(5))
    assert events[0].value == pytest.approx(10.0)
    assert events[4].value == pytest.approx(6.0)
