"""Unit tests for the toslint framework and every checker.

Contract per checker: at least one fixture it FIRES on and one compliant
rewrite it stays QUIET on — so a checker that silently stops matching (an
ast refactor, a rename) fails here, not by letting rot back in.  Plus the
baseline round-trip (add finding -> baseline suppresses -> removing the
entry re-fires) and CLI determinism.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from tensorflowonspark_tpu.analysis import core
from tensorflowonspark_tpu.utils import envtune, knobs

PKG = "tensorflowonspark_tpu"


def lint(src: str, path: str, checker: str) -> list[core.Finding]:
    return core.analyze_source(textwrap.dedent(src), path, [checker])


# -- knob discipline ----------------------------------------------------------


def test_knob_fires_on_raw_environ_get():
    found = lint(
        """
        import os
        def f():
            return os.environ.get("TOS_FOO")
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert len(found) == 1 and "TOS_FOO" in found[0].message


def test_knob_fires_on_environ_subscript_and_module_constant():
    found = lint(
        """
        import os
        KEY = "TOS_BAR"
        def f():
            a = os.environ["TOS_FOO"]
            b = os.environ.get(KEY)
            return a, b
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert {f.anchor for f in found} == {"f@TOS_FOO", "f@TOS_BAR"}


def test_knob_quiet_on_non_tos_names_and_inside_envtune():
    quiet = lint(
        """
        import os
        def f():
            return os.environ.get("JAX_PLATFORMS")
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert quiet == []
    exempt = lint(
        """
        import os
        def env_float(name, default):
            return os.environ.get("TOS_WHATEVER")
        """, f"{PKG}/utils/envtune.py", "knob-discipline")
    assert exempt == []


def test_knob_fires_on_unregistered_helper_read():
    found = lint(
        """
        from tensorflowonspark_tpu.utils.envtune import env_float
        x = env_float("TOS_NOT_A_REAL_KNOB", 1.0)
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert len(found) == 1 and "not registered" in found[0].message


def test_knob_quiet_on_registered_read_even_aliased():
    quiet = lint(
        """
        from tensorflowonspark_tpu.utils.envtune import env_float as _env_float
        from tensorflowonspark_tpu.utils.envtune import env_int
        a = _env_float("TOS_EOF_TIMEOUT", 20.0)
        b = env_int("TOS_MAX_RESTARTS", 2, minimum=0)
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert quiet == []


def test_knob_fires_on_dynamic_knob_name():
    found = lint(
        """
        from tensorflowonspark_tpu.utils.envtune import env_float
        def f(name):
            return env_float(name, 1.0)
        """, f"{PKG}/somemod.py", "knob-discipline")
    assert len(found) == 1 and "literal" in found[0].hint


def test_knob_registry_readme_sync(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("")
    readme = tmp_path / "README.md"
    # 1) markers missing entirely
    readme.write_text("# nothing\n")
    findings = core.run_analysis(pkg, ["knob-discipline"])
    assert any(f.anchor == "<readme>@knob-table"
               and "markers missing" in f.message for f in findings)
    # 2) markers present but the table drifted
    readme.write_text(
        f"{knobs.TABLE_BEGIN}\n| stale |\n{knobs.TABLE_END}\n")
    findings = core.run_analysis(pkg, ["knob-discipline"])
    assert any(f.anchor == "<readme>@knob-table"
               and "out of sync" in f.message for f in findings)
    # 3) generated table in place -> quiet
    readme.write_text(
        f"{knobs.TABLE_BEGIN}\n{knobs.knob_table_markdown()}\n{knobs.TABLE_END}\n")
    findings = core.run_analysis(pkg, ["knob-discipline"])
    assert not any(f.anchor == "<readme>@knob-table" for f in findings)


def test_knob_registry_flags_never_read_knobs(tmp_path):
    # a tmp package that reads nothing: every registered knob is "unused"
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("")
    findings = core.run_analysis(pkg, ["knob-discipline"])
    unused = {f.anchor.split("@", 1)[1] for f in findings
              if f.anchor.startswith("<registry>@")}
    assert unused == set(knobs.KNOBS)


# -- dial discipline ----------------------------------------------------------


def test_dial_fires_outside_net_py():
    found = lint(
        """
        import socket
        def dial(addr):
            return socket.create_connection(addr, timeout=5)
        """, f"{PKG}/somemod.py", "dial-discipline")
    assert len(found) == 1 and found[0].anchor == "dial@create_connection"


def test_dial_quiet_inside_net_py_and_on_sanctioned_dial():
    assert lint(
        """
        import socket
        def connect_with_backoff(addr):
            return socket.create_connection(addr)
        """, f"{PKG}/utils/net.py", "dial-discipline") == []
    assert lint(
        """
        from tensorflowonspark_tpu.utils.net import connect_with_backoff
        def dial(addr):
            return connect_with_backoff(addr, attempts=3)
        """, f"{PKG}/somemod.py", "dial-discipline") == []


def test_dial_fires_on_raw_zerocopy_io_outside_allowed_files():
    found = lint(
        """
        def pump(sock, bufs, out):
            sock.sendmsg(bufs)
            sock.recv_into(out)
        """, f"{PKG}/somemod.py", "dial-discipline")
    assert {f.anchor for f in found} == {"pump@sendmsg", "pump@recv_into"}


def test_dial_quiet_on_zerocopy_io_in_net_and_dataserver():
    src = """
        def pump(sock, bufs, out):
            sock.sendmsg(bufs)
            sock.recv_into(out)
        """
    assert lint(src, f"{PKG}/utils/net.py", "dial-discipline") == []
    assert lint(src, f"{PKG}/dataserver.py", "dial-discipline") == []


def test_dial_fires_on_collective_peer_sockets_outside_transport():
    """ISSUE 12 satellite: raw peer-to-peer collective sockets are confined
    to collective/transport.py — even the otherwise-sanctioned
    connect_with_backoff/bound_socket fire in other collective modules."""
    found = lint(
        """
        import socket
        from tensorflowonspark_tpu.utils.net import (
            bound_socket,
            connect_with_backoff,
        )
        def form(addr):
            srv = bound_socket("")
            c = connect_with_backoff(addr)
            s = socket.socket()
            return srv, c, s
        """, f"{PKG}/collective/group.py", "dial-discipline")
    assert {f.anchor for f in found} == {
        "form@bound_socket", "form@connect_with_backoff", "form@socket"}
    assert all("collective/transport.py" in f.message for f in found)


def test_dial_quiet_in_collective_transport_and_on_zerocopy_io_there():
    src = """
        from tensorflowonspark_tpu.utils.net import connect_with_backoff
        def dial(addr, sock, bufs, out):
            c = connect_with_backoff(addr)
            sock.sendmsg(bufs)
            sock.recv_into(out)
            return c
        """
    assert lint(src, f"{PKG}/collective/transport.py", "dial-discipline") == []


def test_dial_fires_on_ingest_peer_sockets():
    """Disaggregated-ingest satellite: worker->trainer chunk streams are
    confined to the dataserver transport homes — raw sockets (even the
    otherwise-sanctioned dial helpers) fire anywhere under ingest/."""
    found = lint(
        """
        import socket
        from tensorflowonspark_tpu.utils.net import connect_with_backoff
        def forward(addr):
            c = connect_with_backoff(addr)
            s = socket.socket()
            return c, s
        """, f"{PKG}/ingest/service.py", "dial-discipline")
    assert {f.anchor for f in found} == {
        "forward@connect_with_backoff", "forward@socket"}
    assert all("transport homes" in f.message for f in found)


def test_dial_quiet_on_ingest_dataclient_forwarding():
    """The compliant shape: the forwarder speaks DataClient (dataserver.py
    owns the socket) — nothing under ingest/ fires."""
    src = """
        from tensorflowonspark_tpu.dataserver import DataClient
        def forward(host, port, authkey, chunk):
            client = DataClient(host, port, authkey)
            return client.forward_chunks([chunk])
        """
    assert lint(src, f"{PKG}/ingest/service.py", "dial-discipline") == []


def test_dial_fires_on_embedding_tier_sockets():
    """ISSUE 19 satellite: the embedding tier has no wire of its own —
    raw sockets (even the sanctioned dial helpers) fire anywhere under
    embedding/; exchanges must ride the collective transport or the embed
    data-feed queue pair."""
    found = lint(
        """
        import socket
        from tensorflowonspark_tpu.utils.net import connect_with_backoff
        def fetch_rows(addr):
            c = connect_with_backoff(addr)
            s = socket.socket()
            return c, s
        """, f"{PKG}/embedding/table.py", "dial-discipline")
    assert {f.anchor for f in found} == {
        "fetch_rows@connect_with_backoff", "fetch_rows@socket"}
    assert all("embedding/" in f.message for f in found)


def test_dial_quiet_on_embedding_collective_and_feed_use():
    """The compliant shape: lookups ride group.sparse_all_to_all and the
    responder rides ctx.get_data_feed — nothing under embedding/ fires."""
    src = """
        def exchange(group, parts, ctx):
            got = group.sparse_all_to_all(parts)
            feed = ctx.get_data_feed(train_mode=False, qname_in="embed")
            return got, feed
        """
    assert lint(src, f"{PKG}/embedding/table.py", "dial-discipline") == []
    assert lint(src, f"{PKG}/embedding/serve.py", "dial-discipline") == []


def test_lock_discipline_covers_embedding_modules():
    """The embedding tier's modules are in the threaded set: the classic
    mixed locked/unlocked mutation fixture must fire there."""
    found = lint(_MIXED, f"{PKG}/embedding/table.py", "lock-discipline")
    assert any(f.anchor.endswith("n") for f in found), found


# -- lock discipline ----------------------------------------------------------

_MIXED = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def locked_inc(self):
        with self._lock:
            self.n += 1
    def unlocked_set(self):
        self.n = 5
"""


def test_lock_fires_on_mixed_locked_unlocked_mutation():
    found = lint(_MIXED, f"{PKG}/cluster.py", "lock-discipline")
    assert len(found) == 1
    assert found[0].anchor == "C.unlocked_set@mixed:n"
    assert "locked_inc" in found[0].message


def test_lock_discipline_covers_collective_modules():
    """ISSUE 12 satellite: the collective layer joined the threaded set —
    the same race fixture that fires in cluster.py fires there too."""
    for basename in ("group.py", "transport.py", "ops.py"):
        found = lint(_MIXED, f"{PKG}/collective/{basename}", "lock-discipline")
        assert len(found) == 1, basename
        assert found[0].anchor == "C.unlocked_set@mixed:n", basename


def test_lock_discipline_covers_rollout_and_tenancy_modules():
    """ISSUE 16 satellite: the rollout/tenancy modules joined the threaded
    set (governor thread vs router workers; batcher-owned queues) — the
    same race fixture that fires in cluster.py fires there too."""
    for basename in ("rollout.py", "tenancy.py"):
        found = lint(_MIXED, f"{PKG}/serving/{basename}", "lock-discipline")
        assert len(found) == 1, basename
        assert found[0].anchor == "C.unlocked_set@mixed:n", basename


def test_lock_quiet_outside_threaded_modules_and_when_all_locked():
    assert lint(_MIXED, f"{PKG}/models/mnist.py", "lock-discipline") == []
    assert lint(
        """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def inc(self):
                with self._lock:
                    self.n += 1
            def reset(self):
                with self._lock:
                    self.n = 0
        """, f"{PKG}/cluster.py", "lock-discipline") == []


def test_lock_fires_on_blocking_call_under_lock():
    found = lint(
        """
        import time
        class C:
            def f(self):
                with self._lock:
                    time.sleep(1.0)
        """, f"{PKG}/dataserver.py", "lock-discipline")
    assert len(found) == 1 and found[0].anchor == "C.f@block:sleep"


def test_lock_quiet_on_blocking_call_outside_lock_and_safe_joins():
    assert lint(
        """
        import time
        class C:
            def f(self):
                with self._lock:
                    x = 1
                time.sleep(1.0)
        """, f"{PKG}/dataserver.py", "lock-discipline") == []
    assert lint(
        """
        import os
        class C:
            def f(self, parts):
                with self._lock:
                    a = ",".join(parts)
                    b = os.path.join("x", "y")
                return a, b
        """, f"{PKG}/dataserver.py", "lock-discipline") == []


def test_lock_locked_suffix_means_caller_holds_the_lock():
    # the `*_locked` naming contract: its mutations count as locked...
    assert lint(
        """
        import threading
        class C:
            def inc(self):
                with self._lock:
                    self.n += 1
                    self._bump_locked()
            def _bump_locked(self):
                self.n += 1
        """, f"{PKG}/cluster.py", "lock-discipline") == []
    # ...and blocking calls in it ARE blocking-under-lock
    found = lint(
        """
        import time
        class C:
            def _wait_locked(self):
                time.sleep(0.5)
        """, f"{PKG}/cluster.py", "lock-discipline")
    assert len(found) == 1 and found[0].anchor == "C._wait_locked@block:sleep"


def test_reactor_fires_on_blocking_calls_in_callback_scope():
    found = lint(
        """
        import time
        class ServeReactor:
            def _on_readable(self, conn):
                time.sleep(0.1)
            def _sweep_deadlines(self):
                blob = recv_exact(self._sock, 8)
            def _flush_writes(self, conn):
                sendmsg_all(conn.sock, conn.wviews)
        """, f"{PKG}/serving/frontend.py", "reactor-discipline")
    assert {f.anchor for f in found} == {
        "ServeReactor._on_readable@block:sleep",
        "ServeReactor._sweep_deadlines@block:recv_exact",
        "ServeReactor._flush_writes@block:sendmsg_all"}


def test_reactor_quiet_on_exempt_methods_safe_joins_and_other_scopes():
    # __init__ (pre-publication) and stop() (caller-thread join point) are
    # the two contract exemptions; str joins and the one-shot non-blocking
    # primitives are not blocking; other files/classes are out of scope
    assert lint(
        """
        class ServeReactor:
            def __init__(self):
                self._probe_thread.join()
            def stop(self):
                self._thread.join(timeout=10.0)
            def _on_readable(self, conn):
                name = ",".join(parts)
                sent = sendmsg_some(conn.sock, conn.wviews)
        """, f"{PKG}/serving/frontend.py", "reactor-discipline") == []
    blocking_elsewhere = """
        import time
        class Helper:
            def _on_readable(self):
                time.sleep(0.1)
        """
    assert lint(blocking_elsewhere, f"{PKG}/serving/frontend.py",
                "reactor-discipline") == []  # class is not a *Reactor*
    assert lint(blocking_elsewhere.replace("Helper", "FooReactor"),
                f"{PKG}/serving/router.py", "reactor-discipline") == []


def test_dial_discipline_covers_the_reactor_frontend():
    # the frontend does raw non-blocking socket I/O, but dials and the
    # zero-copy loop primitives stay confined: a raw dial or sendmsg in
    # serving/frontend.py fires like anywhere else
    found = lint(
        """
        import socket
        class ServeReactor:
            def _reconnect(self, addr):
                return socket.create_connection(addr)
            def _flush(self, conn):
                conn.sock.sendmsg(conn.wviews)
        """, f"{PKG}/serving/frontend.py", "dial-discipline")
    assert {f.anchor for f in found} == {
        "ServeReactor._reconnect@create_connection",
        "ServeReactor._flush@sendmsg"}


def test_lock_fires_on_framing_wrapper_io_under_lock():
    # the tree's idiomatic blocking I/O goes through _send/_recv wrappers;
    # the checker must see those, not just bare socket method names
    found = lint(
        """
        class C:
            def call(self, msg):
                with self._lock:
                    _send_msg(self._sock, msg)
                    return _recv_msg(self._sock)
        """, f"{PKG}/coordinator.py", "lock-discipline")
    assert {f.anchor for f in found} == {"C.call@block:_send_msg",
                                         "C.call@block:_recv_msg"}


def test_lock_bare_annotation_is_not_a_mutation():
    assert lint(
        """
        import threading
        class C:
            def inc(self):
                with self._lock:
                    self.n += 1
            def h(self):
                self.n: int
        """, f"{PKG}/cluster.py", "lock-discipline") == []


def test_lock_closure_bodies_do_not_inherit_the_lock():
    assert lint(
        """
        import time, threading
        class C:
            def f(self):
                with self._lock:
                    def cb():
                        time.sleep(1.0)
                    self._cb = cb
        """, f"{PKG}/node.py", "lock-discipline") == []


# -- shard IO discipline ------------------------------------------------------


def test_shard_io_fires_on_raw_binary_shard_open():
    found = lint(
        """
        import gzip
        def f(shard_path, part_file):
            a = open(shard_path, "rb").read()
            b = gzip.open("data/part-00001", mode="rb").read()
            c = gzip.open(shard_path).read()   # gzip's DEFAULT mode is 'rb'
            return a, b, c
        """, f"{PKG}/somemod.py", "shard-io-discipline")
    assert len(found) == 3
    assert all("CRC" in f.message for f in found)


def test_shard_io_fires_on_path_read_bytes():
    found = lint(
        """
        from pathlib import Path
        def f(shard):
            return Path(shard).read_bytes()
        """, f"{PKG}/somemod.py", "shard-io-discipline")
    assert len(found) == 1 and "read_bytes" in found[0].anchor


def test_shard_io_fires_on_raw_shard_buffer_views():
    found = lint(
        """
        import mmap
        def f(shard_buf, shard_file):
            v = memoryview(shard_buf)[12:4096]
            m = mmap.mmap(shard_file.fileno(), 0)
            return v, m
        """, f"{PKG}/somemod.py", "shard-io-discipline")
    assert len(found) == 2
    assert all("lifetime contract" in f.message for f in found)


def test_shard_io_view_rule_confined_to_codec_homes():
    """tfrecord.py/dfutil.py own view production; ingest/ is exempt from
    the OPEN rule (it reads via the codecs) but NOT the view rule — its
    views must come from tfrecord.record_views, not ad-hoc slicing."""
    src = """
        def f(shard_buf):
            return memoryview(shard_buf)[0:100]
        """
    assert lint(src, f"{PKG}/tfrecord.py", "shard-io-discipline") == []
    assert lint(src, f"{PKG}/dfutil.py", "shard-io-discipline") == []
    assert len(lint(src, f"{PKG}/ingest/readers.py",
                    "shard-io-discipline")) == 1
    # non-shard-named buffers stay quiet everywhere (lexical heuristic)
    assert lint(
        """
        def f(frame_buf):
            return memoryview(frame_buf)[4:]
        """, f"{PKG}/somemod.py", "shard-io-discipline") == []


def test_shard_io_quiet_in_sanctioned_homes_and_on_non_shard_io():
    src = """
        def f(shard_path):
            return open(shard_path, "rb").read()
        """
    assert lint(src, f"{PKG}/tfrecord.py", "shard-io-discipline") == []
    assert lint(src, f"{PKG}/ingest/readers.py", "shard-io-discipline") == []
    quiet = lint(
        """
        def f(shard_meta, config_path, shard_out):
            a = open(shard_meta) .read()           # text mode: not a codec bypass
            b = open(config_path, "rb").read()     # binary, but not shard-named
            open(shard_out, "wb").write(b"x")      # writes are the writer's business
            return a, b
        """, f"{PKG}/somemod.py", "shard-io-discipline")
    assert quiet == []


# -- journal-write discipline (ISSUE 13) --------------------------------------


def test_journal_discipline_fires_on_stray_fsync():
    found = lint(
        """
        import os
        def persist(fd):
            os.fsync(fd)
        """, f"{PKG}/somemod.py", "journal-discipline")
    assert len(found) == 1 and "os.fsync" in found[0].message
    assert "journal.py" in found[0].hint


def test_journal_discipline_fires_on_journal_file_open():
    found = lint(
        """
        import os
        def peek(log_dir):
            a = open(log_dir + "/coordinator.journal").read()
            b = os.open(journal_path, os.O_WRONLY)
            return a, b
        """, f"{PKG}/somemod.py", "journal-discipline")
    assert {f.anchor for f in found} == {"peek@open", "peek@os.open"}


def test_journal_discipline_quiet_in_journal_py_and_on_non_journal_io():
    src = """
        import os
        def append(fd, path):
            os.write(fd, b"x")
            os.fsync(fd)
            return open(path + ".journal", "rb").read()
        """
    assert lint(src, f"{PKG}/journal.py", "journal-discipline") == []
    quiet = lint(
        """
        import os
        def f(path):
            data = open(path, "rb").read()       # not journal-named
            os.write(1, data)                    # write without fsync
            return data
        """, f"{PKG}/somemod.py", "journal-discipline")
    assert quiet == []


# -- timeout discipline (collective/) -----------------------------------------


def test_timeout_discipline_fires_on_unbounded_waits():
    found = lint(
        """
        def run(self, fut, tp, seq, cond):
            a = fut.result()
            cond.wait()
            b = tp.recv(0, seq, ("rs", 0, 0))
            return a, b
        """, f"{PKG}/collective/somemod.py", "timeout-discipline")
    assert {f.anchor for f in found} == {"run@result", "run@wait",
                                         "run@recv"}


def test_timeout_discipline_fires_on_explicit_none_timeout():
    found = lint(
        """
        def run(fut):
            return fut.result(timeout=None)
        """, f"{PKG}/collective/somemod.py", "timeout-discipline")
    assert len(found) == 1 and "result" in found[0].message


def test_timeout_discipline_quiet_on_bounded_waits_and_outside_collective():
    src = """
        def run(self, fut, tp, cond, gen, src, seq, tag, slice_):
            a = fut.result(timeout=2.0 * self._timeout + 30.0)
            cond.wait(min(0.5, remaining))
            b = tp.recv(src, seq, tag, timeout=_left(deadline))
            c = self.inbox.recv(gen, src, seq, tag, slice_)
            return a, b, c
        """
    assert lint(src, f"{PKG}/collective/somemod.py",
                "timeout-discipline") == []
    # same unbounded calls OUTSIDE collective/ are out of scope
    assert lint(
        """
        def run(fut):
            return fut.result()
        """, f"{PKG}/serving/router.py", "timeout-discipline") == []


# -- silent-except discipline -------------------------------------------------


def test_silent_except_fires():
    found = lint(
        """
        def f():
            try:
                risky()
            except ValueError:
                pass
        """, f"{PKG}/somemod.py", "silent-except")
    assert len(found) == 1 and found[0].anchor == "f@except:ValueError"


def test_silent_except_quiet_with_reasoned_pragma_only():
    assert lint(
        """
        def f():
            try:
                risky()
            except ValueError:  # toslint: allow-silent(best-effort teardown)
                pass
        """, f"{PKG}/somemod.py", "silent-except") == []
    # a reason-less pragma documents nothing and suppresses nothing
    found = lint(
        """
        def f():
            try:
                risky()
            except ValueError:  # toslint: allow-silent()
                pass
        """, f"{PKG}/somemod.py", "silent-except")
    assert len(found) == 1


def test_silent_except_quiet_when_logged_and_on_generic_disable():
    assert lint(
        """
        def f():
            try:
                risky()
            except ValueError:
                logger.debug("risky failed", exc_info=True)
        """, f"{PKG}/somemod.py", "silent-except") == []
    assert lint(
        """
        def f():
            try:
                risky()
            except ValueError:  # toslint: disable=silent-except
                pass
        """, f"{PKG}/somemod.py", "silent-except") == []


# -- trace purity -------------------------------------------------------------


def test_trace_purity_fires_on_decorated_wallclock():
    found = lint(
        """
        import time
        import jax
        @jax.jit
        def step(x):
            return x * time.time()
        """, f"{PKG}/parallel/dp.py", "trace-purity")
    assert len(found) == 1 and found[0].anchor == "step@time.time"


def test_trace_purity_fires_through_partial_decorator():
    found = lint(
        """
        import os
        from functools import partial
        import jax
        @partial(jax.jit, static_argnums=0)
        def step(n, x):
            return x if os.environ.get("TOS_X") else -x
        """, f"{PKG}/ops/xent.py", "trace-purity")
    assert any(f.anchor == "step@os.environ" for f in found)


def test_trace_purity_fires_on_wrapped_function_and_lambda():
    found = lint(
        """
        import numpy as np
        import jax
        def noisy(x):
            return x + np.random.rand()
        step = jax.jit(noisy)
        """, f"{PKG}/models/mnist.py", "trace-purity")
    assert len(found) == 1 and found[0].anchor == "noisy@numpy.random.rand"
    found = lint(
        """
        import time
        import jax
        step = jax.jit(lambda x: x * time.time())
        """, f"{PKG}/models/mnist.py", "trace-purity")
    assert len(found) == 1 and found[0].anchor == "<lambda>@time.time"


def test_trace_purity_fires_on_nonlocal_mutation():
    found = lint(
        """
        import jax
        def make_step():
            count = 0
            @jax.jit
            def step(x):
                nonlocal count
                count += 1
                return x
            return step
        """, f"{PKG}/parallel/dp.py", "trace-purity")
    assert any(f.anchor == "step@nonlocal:count" for f in found)


def test_trace_purity_quiet_on_pure_jit_and_untraced_impurity():
    assert lint(
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def step(key, x):
            return x + jax.random.normal(key, x.shape)
        """, f"{PKG}/parallel/dp.py", "trace-purity") == []
    assert lint(
        """
        import time
        def wall():
            return time.time()
        """, f"{PKG}/summary.py", "trace-purity") == []


# -- metrics discipline -------------------------------------------------------


def test_metrics_fires_on_module_level_counter_dicts():
    found = lint(
        """
        _METRICS = {}
        REQUEST_COUNTERS: dict = {}
        frame_stats = dict()
        """, f"{PKG}/somemod.py", "metrics-discipline")
    assert {f.anchor for f in found} == {
        "<module>@_METRICS", "<module>@REQUEST_COUNTERS",
        "<module>@frame_stats"}
    assert all("telemetry" in f.hint for f in found)


def test_metrics_fires_on_collections_counter_any_name():
    found = lint(
        """
        import collections
        from collections import Counter
        SEEN = collections.Counter()
        tallies = Counter()
        """, f"{PKG}/somemod.py", "metrics-discipline")
    assert {f.anchor for f in found} == {"<module>@SEEN", "<module>@tallies"}


def test_metrics_fires_on_defaultdict_store():
    found = lint(
        """
        from collections import defaultdict
        BYTE_COUNTERS = defaultdict(int)
        """, f"{PKG}/somemod.py", "metrics-discipline")
    assert len(found) == 1 and "BYTE_COUNTERS" in found[0].message


def test_metrics_quiet_on_registry_usage_and_non_metric_names():
    # the sanctioned path: metrics created through the telemetry registry
    assert lint(
        """
        from tensorflowonspark_tpu import telemetry
        _TX = telemetry.counter("dataplane.tx_bytes")
        def f(n):
            _TX.inc(n)
        """, f"{PKG}/somemod.py", "metrics-discipline") == []
    # non-metric-named module dicts (registries, tables) stay quiet
    assert lint(
        """
        KNOBS = {}
        _ROUTES: dict = {}
        _barrier_counter = [0]
        def g():
            local_counters = {}
            return local_counters
        """, f"{PKG}/somemod.py", "metrics-discipline") == []


def test_metrics_quiet_inside_telemetry_package():
    assert lint(
        """
        _METRICS = {}
        """, f"{PKG}/telemetry/registry.py", "metrics-discipline") == []


def test_span_discipline_fires_on_bad_span_names():
    """Span names recorded through telemetry.trace must be dotted lowercase
    (the metric-name convention) — ad-hoc spellings fragment the merged
    trace's subsystem grouping."""
    found = lint(
        """
        from tensorflowonspark_tpu.telemetry import trace as ttrace
        def f(ctx, t0):
            with ttrace.span("WireCall", parent=ctx):
                pass
            ttrace.record_span("onewordname", ctx, None, t0, 0.1)
            ttrace.record_child("serve.Reply", ctx, t0, 0.1)
        """, f"{PKG}/somemod.py", "metrics-discipline")
    assert {f.anchor for f in found} == {
        "f@span:WireCall", "f@span:onewordname", "f@span:serve.Reply"}
    assert all("dotted-lowercase" in f.hint for f in found)


def test_span_discipline_fires_on_module_level_span_buffers():
    found = lint(
        """
        import collections
        _SPANS = []
        trace_buffer = collections.deque()
        """, f"{PKG}/somemod.py", "metrics-discipline")
    assert {f.anchor for f in found} == {
        "<module>@_SPANS", "<module>@trace_buffer"}


def test_span_discipline_quiet_on_sanctioned_usage():
    # dotted-lowercase names through the tracer, and non-span containers
    assert lint(
        """
        from tensorflowonspark_tpu.telemetry import trace as ttrace
        def f(ctx, t0):
            with ttrace.span("serve.wire", parent=ctx):
                pass
            ttrace.record_child("feed.partition_consume", ctx, t0, 0.1)
        def g(name, ctx, t0):
            ttrace.record_span(name, ctx, None, t0, 0.1)  # dynamic: not ours
        _ROUTES = []
        """, f"{PKG}/somemod.py", "metrics-discipline") == []
    # an unrelated .span() method is not our API (re.Match.span takes a
    # group name, not a span name) — must not fire
    assert lint(
        """
        import re
        def h(text):
            m = re.match(r"(?P<word>\\\\w+)", text)
            return m.span("word")
        """, f"{PKG}/somemod.py", "metrics-discipline") == []
    # the tracer implementation itself is exempt
    assert lint(
        """
        _SPANS = []
        """, f"{PKG}/telemetry/trace.py", "metrics-discipline") == []


# -- baseline round-trip + ids ------------------------------------------------

_VIOLATION = """
def f():
    try:
        risky()
    except ValueError:
        pass
"""


def _tmp_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(_VIOLATION))
    return pkg


def test_baseline_round_trip(tmp_path):
    pkg = _tmp_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    findings = core.run_analysis(pkg, ["silent-except"])
    assert len(findings) == 1
    # add finding -> baseline suppresses
    refused = core.write_baseline(bl, findings)
    assert refused == []
    new, suppressed, stale = core.partition_by_baseline(
        core.run_analysis(pkg, ["silent-except"]), core.load_baseline(bl))
    assert new == [] and len(suppressed) == 1 and stale == set()
    # removing the baseline entry re-fires
    bl.write_text(json.dumps({"version": 1, "findings": []}))
    new, _, _ = core.partition_by_baseline(
        core.run_analysis(pkg, ["silent-except"]), core.load_baseline(bl))
    assert len(new) == 1


def test_baseline_refuses_knob_and_dial_classes(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(
        """
        import os, socket
        a = os.environ.get("TOS_RAW")
        b = socket.create_connection(("h", 1))
        """))
    bl = tmp_path / "baseline.json"
    findings = core.run_analysis(pkg, ["knob-discipline", "dial-discipline"])
    refused = core.write_baseline(bl, findings)
    assert {f.checker for f in refused} == {"knob-discipline", "dial-discipline"}
    assert not any(
        fid.startswith(("knob-discipline:", "dial-discipline:"))
        for fid in core.load_baseline(bl))


def test_finding_ids_are_line_free_and_duplicate_stable():
    src = """
    def f():
        try:
            a()
        except ValueError:
            pass
        try:
            b()
        except ValueError:
            pass
    """
    findings = lint(src, f"{PKG}/somemod.py", "silent-except")
    ids = [fid for _, fid in core.finding_ids(findings)]
    assert ids == [
        f"silent-except:{PKG}/somemod.py:f@except:ValueError",
        f"silent-except:{PKG}/somemod.py:f@except:ValueError#2",
    ]
    assert not any(str(f.line) in fid for f, fid in core.finding_ids(findings)
                   if f.line > 3)


def test_cli_baseline_update_is_deterministic(tmp_path):
    from tensorflowonspark_tpu.analysis.__main__ import main

    pkg = _tmp_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    argv = ["--package-root", str(pkg), "--baseline", str(bl),
            "--baseline-update", "--checkers", "silent-except"]
    assert main(argv) == 0
    first = bl.read_bytes()
    assert main(argv) == 0
    assert bl.read_bytes() == first
    assert b'"version"' in first
    # and the gate now passes against that baseline
    assert main(["--package-root", str(pkg), "--baseline", str(bl),
                 "--checkers", "silent-except"]) == 0


def test_scoped_baseline_update_preserves_other_checkers_entries(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    # the file must carry a threaded-module basename for lock-discipline
    (pkg / "cluster.py").write_text(textwrap.dedent(
        """
        import time
        class C:
            def f(self):
                with self._lock:
                    time.sleep(1)
            def g(self):
                try:
                    risky()
                except ValueError:
                    pass
        """))
    bl = tmp_path / "baseline.json"
    # full update: both checkers' findings land
    core.write_baseline(bl, core.run_analysis(
        pkg, ["lock-discipline", "silent-except"]))
    assert len(core.load_baseline(bl)) == 2
    # scoped update from a silent-except-only run (which sees no lock
    # findings) must NOT drop the lock-discipline entry
    core.write_baseline(bl, core.run_analysis(pkg, ["silent-except"]),
                        replace_checkers=["silent-except"])
    kept = core.load_baseline(bl)
    assert any(fid.startswith("lock-discipline:") for fid in kept)
    assert any(fid.startswith("silent-except:") for fid in kept)
    # and a scoped update DOES trim its own checker's stale entries
    (pkg / "cluster.py").write_text("def f():\n    pass\n")
    core.write_baseline(bl, core.run_analysis(pkg, ["silent-except"]),
                        replace_checkers=["silent-except"])
    kept = core.load_baseline(bl)
    assert not any(fid.startswith("silent-except:") for fid in kept)
    assert any(fid.startswith("lock-discipline:") for fid in kept)


def test_unknown_checker_id_is_a_usage_error(tmp_path):
    from tensorflowonspark_tpu.analysis.__main__ import main

    assert main(["--package-root", str(_tmp_pkg(tmp_path)),
                 "--checkers", "nope"]) == 2


# -- envtune additions (env_str / env_bool / registry warning) ---------------


def test_env_str_passthrough_and_default(monkeypatch):
    monkeypatch.delenv("TOS_COORDINATOR_HOST", raising=False)
    assert envtune.env_str("TOS_COORDINATOR_HOST", "d") == "d"
    monkeypatch.setenv("TOS_COORDINATOR_HOST", "")
    assert envtune.env_str("TOS_COORDINATOR_HOST", "d") == ""
    monkeypatch.setenv("TOS_COORDINATOR_HOST", "10.0.0.1")
    assert envtune.env_str("TOS_COORDINATOR_HOST", "d") == "10.0.0.1"


@pytest.mark.parametrize("raw,expect", [
    ("0", False), ("false", False), ("No", False), ("off", False),
    ("1", True), ("true", True), ("YES", True), ("on", True),
    ("junk", True),  # junk degrades to the default, never flips silently
])
def test_env_bool_values(monkeypatch, raw, expect):
    monkeypatch.setenv("TOS_SHM_RING", raw)
    assert envtune.env_bool("TOS_SHM_RING", True) is expect


def test_env_bool_unset_returns_default(monkeypatch):
    monkeypatch.delenv("TOS_SHM_RING", raising=False)
    assert envtune.env_bool("TOS_SHM_RING", False) is False


def test_unregistered_knob_read_warns_once(monkeypatch, caplog):
    monkeypatch.setattr(envtune, "_unregistered_warned", set())
    with caplog.at_level("WARNING", logger="tensorflowonspark_tpu.utils.envtune"):
        envtune.env_float("TOS_DEFINITELY_UNREGISTERED", 1.0)
        envtune.env_float("TOS_DEFINITELY_UNREGISTERED", 1.0)
    hits = [r for r in caplog.records if "not registered" in r.message]
    assert len(hits) == 1
    caplog.clear()
    with caplog.at_level("WARNING", logger="tensorflowonspark_tpu.utils.envtune"):
        envtune.env_float("TOS_EOF_TIMEOUT", 20.0)
    assert not [r for r in caplog.records if "not registered" in r.message]


def test_every_registered_knob_has_doc_and_default():
    for k in knobs.KNOBS.values():
        assert k.doc and k.default and k.kind in {"float", "int", "str", "bool"}
    assert knobs.knob_table_markdown().splitlines()[0].startswith("| Knob ")


# -- lock-order (tossan static half, ISSUE 17) --------------------------------


def lock_findings(files: dict[str, str]) -> list[core.Finding]:
    """Build the whole-tree lock graph over in-memory modules and return
    the lock-order findings (the checker's finalize path, unit-sized)."""
    from tensorflowonspark_tpu.analysis import lockgraph

    mods = [core.ModuleSource(p, textwrap.dedent(s))
            for p, s in files.items()]
    return list(lockgraph.lock_order_findings(lockgraph.build_lockgraph(mods)))


_CYCLE_A = f"""
    from {PKG}.utils.locks import tos_named_lock
    from {PKG}.bmod import B

    class A:
        def __init__(self):
            self._lock = tos_named_lock("a._lock")
            self._b = B()

        def m(self):
            with self._lock:
                self._b.n()
    """

_CYCLE_B = f"""
    from {PKG}.utils.locks import tos_named_lock
    from {PKG}.amod import A

    class B:
        def __init__(self):
            self._lock = tos_named_lock("b._lock")
            self._a = A()

        def n(self):
            with self._lock:
                pass

        def r(self):
            with self._lock:
                self._a.m()
    """


def test_lock_order_fires_on_two_module_cycle():
    found = lock_findings({f"{PKG}/amod.py": _CYCLE_A,
                           f"{PKG}/bmod.py": _CYCLE_B})
    assert len(found) == 1
    f = found[0]
    assert f.checker == "lock-order"
    assert "potential deadlock" in f.message
    # the full witness chain names both locks and both call sites
    assert "a._lock -> b._lock" in f.message
    assert "b._lock -> a._lock" in f.message
    assert "amod.py" in f.message and "bmod.py" in f.message
    assert f.anchor == "cycle:a._lock->b._lock"


def test_lock_order_quiet_on_diamond_without_cycle():
    found = lock_findings({f"{PKG}/dmod.py": f"""
        from {PKG}.utils.locks import tos_named_lock

        class D:
            def __init__(self):
                self._a = tos_named_lock("d.a")
                self._b = tos_named_lock("d.b")
                self._c = tos_named_lock("d.c")
                self._d = tos_named_lock("d.d")

            def m1(self):
                with self._a:
                    with self._b:
                        pass

            def m2(self):
                with self._a:
                    with self._c:
                        pass

            def m3(self):
                with self._b:
                    with self._d:
                        pass

            def m4(self):
                with self._c:
                    with self._d:
                        pass
        """})
    assert found == []


def test_lock_order_pragma_with_reason_suppresses_cycle():
    b_blessed = _CYCLE_B.replace(
        "self._a.m()",
        "self._a.m()  # toslint: allow-lock-order(startup-only path, "
        "externally serialized)")
    found = lock_findings({f"{PKG}/amod.py": _CYCLE_A,
                           f"{PKG}/bmod.py": b_blessed})
    assert found == []
    # a reason-less pragma documents nothing and suppresses nothing
    b_bare = _CYCLE_B.replace("self._a.m()",
                              "self._a.m()  # toslint: allow-lock-order()")
    found = lock_findings({f"{PKG}/amod.py": _CYCLE_A,
                           f"{PKG}/bmod.py": b_bare})
    assert len(found) == 1


def test_lock_order_flags_callback_fired_under_lock():
    found = lock_findings({f"{PKG}/cbmod.py": f"""
        from {PKG}.utils.locks import tos_named_lock

        class Batcher:
            def __init__(self, on_done):
                self._lock = tos_named_lock("batcher._lock")
                self._cb = on_done

            def fire(self):
                with self._lock:
                    self._cb(1)

        class User:
            def __init__(self):
                self._lock = tos_named_lock("user._lock")
                self._batcher = Batcher(on_done=self._handle)

            def _handle(self, x):
                with self._lock:
                    pass
        """})
    assert any(f.anchor == "callback:_cb@user._lock" for f in found)
    f = next(f for f in found if f.anchor.startswith("callback:"))
    assert "batcher._lock" in f.message and "_handle" in f.message


def test_lock_order_quiet_on_callback_fired_outside_lock():
    # the batcher's _fire_done pattern: collect under the lock, invoke after
    found = lock_findings({f"{PKG}/cbmod.py": f"""
        from {PKG}.utils.locks import tos_named_lock

        class Batcher:
            def __init__(self, on_done):
                self._lock = tos_named_lock("batcher._lock")
                self._cb = on_done

            def fire(self):
                with self._lock:
                    batch = [1]
                self._cb(batch)

        class User:
            def __init__(self):
                self._lock = tos_named_lock("user._lock")
                self._batcher = Batcher(on_done=self._handle)

            def _handle(self, x):
                with self._lock:
                    pass
        """})
    assert found == []


def test_lock_order_sees_cycle_through_module_function_and_local_var():
    # interprocedural depth: a module function constructs a tree class into
    # a LOCAL and calls through it; unnamed threading.Lock attrs get
    # synthesized <module>.<Class>.<attr> node ids
    found = lock_findings({f"{PKG}/x.py": f"""
        import threading
        from {PKG}.y import helper

        class X:
            def __init__(self):
                self._lock = threading.Lock()

            def m(self):
                with self._lock:
                    helper()
        """, f"{PKG}/y.py": f"""
        import threading
        from {PKG}.x import X

        class Y:
            def __init__(self):
                self._lock = threading.Lock()

            def n(self):
                with self._lock:
                    x = X()
                    x.m()

        def helper():
            y = Y()
            y.n()
        """})
    assert len(found) == 1
    assert "x.X._lock" in found[0].message
    assert "y.Y._lock" in found[0].message


def test_lock_order_refuses_baseline(tmp_path):
    # like knob/dial classes: --baseline-update refuses lock-order findings
    assert "lock-order" in core.NEVER_BASELINE
    f = core.Finding("lock-order", f"{PKG}/amod.py", 3, "cycle", "fix",
                     "cycle:a._lock->b._lock")
    refused = core.write_baseline(tmp_path / "b.json", [f])
    assert refused == [f]
    assert core.load_baseline(tmp_path / "b.json") == set()


def test_dump_lockgraph_cli_writes_dot_and_json(tmp_path, capsys):
    from tensorflowonspark_tpu.analysis.__main__ import main

    assert main(["--dump-lockgraph", str(tmp_path / "lg")]) == 0
    dot = (tmp_path / "lg" / "lockgraph.dot").read_text()
    data = json.loads((tmp_path / "lg" / "lockgraph.json").read_text())
    assert dot.startswith("digraph lockgraph")
    assert data["schema"] == "tos-lockgraph-v1"
    # the real tree's cross-module spine is in the resolved graph
    edges = {(e["from"], e["to"]) for e in data["edges"]}
    assert ("coordinator._lock", "journal._lock") in edges
    for e in data["edges"]:
        assert e["witness"], e  # every edge carries its witness chain


def test_cli_format_json_emits_machine_rows(capsys):
    from tensorflowonspark_tpu.analysis.__main__ import main

    assert main(["--format=json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["schema"] == "toslint-findings-v1"
    assert all(set(r) == {"checker", "path", "line", "message", "hint",
                          "id", "baselined"} for r in data["findings"])
    # a clean tree still reports its baselined findings, marked as such
    assert all(r["baselined"] for r in data["findings"])
