"""Elastic recovery: incarnation fencing, restart policy/supervisor units,
the fault-injection grammar, and deterministic chaos end-to-end tests.

The chaos tests are tier-1 by design (ISSUE 1): every recovery path —
supervised restart with checkpoint resume, partition re-feed after a severed
socket, exactly-once inference retry against a restarted node — runs on a
deterministic fault schedule (``TOS_FAULTINJECT``) instead of waiting for a
soak run to hit a flake.  The randomized soak variant lives in
``test_soak_dataplane.py`` (``slow`` + ``chaos``).
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from tensorflowonspark_tpu import cluster as tcluster
from tensorflowonspark_tpu import faultinject
from tensorflowonspark_tpu.coordinator import CoordinatorClient, CoordinatorServer
from tensorflowonspark_tpu.node import NodeConfig
from tensorflowonspark_tpu.supervisor import RestartPolicy, Supervisor
from tensorflowonspark_tpu.utils.net import connect_with_backoff

import mapfuns


# -- fault-injection grammar -------------------------------------------------

def test_fault_plan_grammar():
    plan = faultinject.FaultPlan.parse(
        "kill:after_batches=3,incarnation=0;sever:after_data_ops=2;"
        "drop_heartbeats:count=5,executor=1")
    plan.set_identity(executor_id=1, incarnation=0)
    # kill counts batches deterministically: fires exactly on the 3rd
    assert not plan._tick("kill")
    assert not plan._tick("kill")
    assert plan._tick("kill")
    assert not plan._tick("kill")  # one-shot
    # sever fires on the 2nd data op
    assert not plan._tick("sever")
    assert plan._tick("sever")
    # drop_heartbeats scoped to executor 1 (matches)
    assert plan._tick("drop_heartbeats")


def test_fault_plan_incarnation_disarms_after_restart():
    plan = faultinject.FaultPlan.parse("kill:after_batches=1,incarnation=0")
    plan.set_identity(executor_id=0, incarnation=1)  # restarted process
    for _ in range(5):
        assert not plan._tick("kill")


def test_fault_plan_executor_filter():
    plan = faultinject.FaultPlan.parse("sever:after_data_ops=1,executor=3")
    plan.set_identity(executor_id=2)
    assert not plan._tick("sever")
    plan.set_identity(executor_id=3)
    assert plan._tick("sever")


def test_fault_plan_rejects_junk():
    with pytest.raises(ValueError, match="unknown fault action"):
        faultinject.FaultPlan.parse("explode:after=1")
    with pytest.raises(ValueError, match="unknown keys"):
        faultinject.FaultPlan.parse("kill:after_batches=1,bogus=2")


# -- restart policy / backoff ------------------------------------------------

def test_restart_policy_delay_bounds():
    policy = RestartPolicy(max_restarts=3, backoff_base=0.5,
                           backoff_factor=2.0, backoff_max=4.0, jitter=0.25)
    for attempt, base in [(0, 0.5), (1, 1.0), (2, 2.0), (3, 4.0), (10, 4.0)]:
        for _ in range(20):
            d = policy.delay(attempt)
            assert base * 0.75 <= d <= base * 1.25, (attempt, d)


def test_connect_with_backoff_rides_out_dark_port():
    # reserve a port, go dark, come back 0.6s later — the restart window
    probe = socket.socket()
    probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)

    def _listen_late():
        time.sleep(0.6)
        server.bind(("127.0.0.1", port))
        server.listen(1)

    t = threading.Thread(target=_listen_late, daemon=True)
    t.start()
    try:
        sock = connect_with_backoff(("127.0.0.1", port), timeout=5.0,
                                    attempts=8, base=0.2, factor=1.5)
        sock.close()
    finally:
        t.join()
        server.close()


def test_connect_with_backoff_surfaces_failure():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="after 2 attempt"):
        connect_with_backoff(("127.0.0.1", port), timeout=1.0,
                             attempts=2, base=0.05)
    assert time.monotonic() - t0 < 5.0


# -- consumption watermark bookkeeping ---------------------------------------

def test_consumption_watermark_lags_returned_batch():
    """The partition-consumed count must not advance until the batch that
    CLOSED the partition has been returned to the map_fun — otherwise a death
    between EndPartition-pop and the map_fun processing that final batch
    silently loses it (the ledger would believe the partition consumed)."""
    from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues
    from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition

    queues = FeedQueues(("input",))
    q = queues.get_queue("input")
    for item in (1, 2, EndPartition(), 3, 4, EndPartition(), EndOfFeed()):
        q.put(item)
    feed = DataFeed(queues, qname_in="input")
    assert feed.next_batch(3) == [1, 2]
    # the closing batch was only just handed back: not yet consumed
    assert queues.partitions_consumed("input") == 0
    assert feed.next_batch(3) == [3, 4]
    # coming back for more proves batch 1 was processed
    assert queues.partitions_consumed("input") == 1
    assert feed.next_batch(3) == []
    assert feed.should_stop()
    assert queues.partitions_consumed("input") == 2


def test_watermark_dedupes_refed_partition():
    """An at-least-once re-feed can put TWO EndPartition markers for one
    logical partition in the queue (reply lost after the server queued the
    first); keyed markers must count once, or the watermark over-advances
    past still-buffered work that a later death would fail to re-deliver."""
    from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues
    from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition

    queues = FeedQueues(("input",))
    q = queues.get_queue("input")
    for item in (1, 2, EndPartition(key=(0, 0)), 1, 2, EndPartition(key=(0, 0)),
                 3, EndPartition(key=(0, 1)), EndOfFeed()):
        q.put(item)
    feed = DataFeed(queues, qname_in="input")
    while not feed.should_stop():
        feed.next_batch(8)
    # the EndOfFeed pop flushed every deferred report on its way in
    assert queues.partitions_consumed("input") == 2  # (0,0) counted once


def test_ledger_tail_drain_accounting():
    """needs_drain reflects acked-but-unconsumed work; update_watermark (the
    tail-drain poll path) empties it; requeue_unconsumed puts it back in play
    and resets the watermark anchor for the replacement process."""
    from tensorflowonspark_tpu.cluster import _PartitionLedger

    ledger = _PartitionLedger(num_partitions=2, num_epochs=1, num_slots=1)
    for consumed_at_ack in (0, 1):
        assert ledger.next_task(0) is not None
        ledger.ack(0, consumed=consumed_at_ack)
    # first ack anchored at 0, second advanced by 1: one of the two acked
    # partitions is still only buffered
    assert ledger.needs_drain(0)
    ledger.update_watermark(0, 2)
    assert not ledger.needs_drain(0)
    assert ledger.next_task(0) is None  # all resolved, nothing to drain

    ledger2 = _PartitionLedger(num_partitions=2, num_epochs=1, num_slots=1)
    for consumed_at_ack in (0, 1):
        assert ledger2.next_task(0) is not None
        ledger2.ack(0, consumed=consumed_at_ack)
    assert ledger2.requeue_unconsumed(0) == 1  # the buffered one, not both
    assert not ledger2.needs_drain(0)
    assert ledger2.next_task(0) is not None  # back in play


def test_abandon_slot_returns_orphans_forfeits_own():
    """A terminating consumer forfeits its OWN share, but an in-flight task
    it picked up from the orphan pool is a dead peer's work and must go back
    in play instead of being silently dropped."""
    from tensorflowonspark_tpu.cluster import _PartitionLedger

    ledger = _PartitionLedger(num_partitions=2, num_epochs=1, num_slots=2)
    t1 = ledger.next_task(1)
    ledger.requeue(1)                    # slot 1 died: its task is orphaned
    assert ledger.next_task(0) == (0, 0)
    ledger.ack(0)
    assert ledger.next_task(0) == t1     # slot 0 adopts the orphan...
    ledger.abandon_slot(0)               # ...then its consumer terminates
    assert ledger.next_task(1) == t1     # the orphan survives the forfeit


# -- incarnation fencing (in-process coordinator) ----------------------------

def _fenced_pair():
    srv = CoordinatorServer(2)
    addr = srv.start()
    clients = []
    for host in ("h0", "h1"):
        c = CoordinatorClient(addr)
        ident = c.register({"host": host})
        c.set_identity(ident["executor_id"], ident["incarnation"])
        clients.append((c, ident))
    return srv, clients


def test_incarnation_fencing_rejects_stale_node():
    srv, clients = _fenced_pair()
    try:
        (c0, id0), (c1, id1) = clients
        assert id0["incarnation"] == id1["incarnation"] == 0
        # declare node 1 dead: fenced, idempotent, no double-declare
        assert srv.mark_dead([id1["executor_id"]], record_error=False) == [id1["executor_id"]]
        assert srv.mark_dead([id1["executor_id"]], record_error=False) == []
        assert srv.registered_incarnation(id1["executor_id"]) == (1, False)
        # the zombie's heartbeat is answered with stop=True (wind down)
        assert c1.heartbeat(id1["executor_id"]) is True
        # its barriers/reduces fail loudly instead of joining live generations
        with pytest.raises(RuntimeError, match="stale incarnation"):
            c1.reduce("zombie-reduce", 1, kind="sum", count=1)
        # its meta updates are swallowed
        c1.update_meta(id1["executor_id"], {"zombie_patch": True})
        assert "zombie_patch" not in srv.cluster_info()[id1["executor_id"]]
        # a replacement re-registers into the slot and adopts incarnation 1
        c2 = CoordinatorClient(srv.address)
        ident2 = c2.register({"host": "h1-replacement"},
                             replace=id1["executor_id"])
        assert ident2["executor_id"] == id1["executor_id"]
        assert ident2["incarnation"] == 1
        c2.set_identity(ident2["executor_id"], ident2["incarnation"])
        assert c2.reduce("live-reduce", 2, kind="sum", count=1) == 2
        # slot meta was replaced wholesale
        assert srv.cluster_info()[id1["executor_id"]]["host"] == "h1-replacement"
        # the pre-restart zombie stays fenced even after the replacement is up
        with pytest.raises(RuntimeError, match="stale incarnation"):
            c1.reduce("zombie-reduce-2", 1, kind="sum", count=1)
        # a live (still-tracked) slot refuses replacement
        c3 = CoordinatorClient(srv.address)
        with pytest.raises(RuntimeError, match="still .*tracked"):
            c3.register({"host": "usurper"}, replace=id0["executor_id"])
        for c in (c0, c1, c2, c3):
            c.close()
    finally:
        srv.stop()


def test_mark_dead_aborts_inflight_rendezvous():
    srv, clients = _fenced_pair()
    try:
        (c0, id0), (c1, id1) = clients
        result: list = []

        def _waiter():
            try:
                c0.reduce("pair", 1, kind="sum", count=2, timeout=30.0)
            except RuntimeError as e:
                result.append(e)

        t = threading.Thread(target=_waiter, daemon=True)
        t.start()
        time.sleep(0.3)  # let the waiter join the generation
        srv.mark_dead([id1["executor_id"]], record_error=False)
        t.join(5.0)
        # the survivor unblocked in seconds, not after the 30s timeout
        assert result and "aborted" in str(result[0])
        c0.close()
        c1.close()
    finally:
        srv.stop()


# -- supervisor units --------------------------------------------------------

class _StubCoordinator:
    def __init__(self, info=None, errors=None, tracked_after_respawn=True):
        self.failures: list = []
        self.stopped = False
        # liveness mirrors the real protocol: the dead slot is untracked
        # until a respawned replacement re-registers (or never, for the
        # boot-death scenario)
        self.tracked = False
        self.tracked_after_respawn = tracked_after_respawn
        self._errors = errors or []
        self._info = info or []

    def record_failure(self, executor_id, reason):
        self.failures.append((executor_id, reason))

    def signal_stop(self):
        self.stopped = True

    def errors(self):
        return self._errors

    def cluster_info(self):
        return self._info

    def node_meta(self, executor_id):
        return next((m for m in self._info
                     if m["executor_id"] == executor_id), None)

    def registered_incarnation(self, executor_id):
        return (1, self.tracked)


class _StubLauncher:
    def __init__(self, n=2, coord=None):
        self.processes = [object()] * n
        self.configs = [
            NodeConfig(coordinator_addr=("127.0.0.1", 1), authkey=b"k",
                       map_fun=mapfuns.noop, launch_index=i)
            for i in range(n)
        ]
        self.respawned: list = []
        self.coord = coord

    def respawn(self, index, config):
        self.respawned.append((index, config))
        if self.coord is not None:
            self.coord.tracked = self.coord.tracked_after_respawn


def _drain(sup, executor_id):
    """Wait for the in-flight restart to resolve BEFORE stopping (stop()
    cancels pending backoff waits, which is correct in production but would
    make these assertions race the restart thread)."""
    deadline = time.monotonic() + 10.0
    while sup.restarting(executor_id) and time.monotonic() < deadline:
        time.sleep(0.01)
    sup.stop(timeout=10.0)


def test_supervisor_respawns_into_slot_with_replacement_config():
    coord = _StubCoordinator(info=[{"executor_id": 1, "launch_index": 1}])
    launcher = _StubLauncher(coord=coord)
    sup = Supervisor(coord, launcher,
                     RestartPolicy(max_restarts=2, backoff_base=0.01,
                                   backoff_max=0.02))
    sup.handle_death(1)
    _drain(sup, 1)
    assert launcher.respawned, "supervisor never respawned the slot"
    index, config = launcher.respawned[0]
    assert index == 1
    assert config.replace_executor_id == 1
    assert sup.restart_count(1) == 1
    assert not coord.stopped and not coord.failures


def test_supervisor_exhausted_budget_is_permanent():
    coord = _StubCoordinator(info=[{"executor_id": 1, "launch_index": 1}])
    sup = Supervisor(coord, _StubLauncher(),
                     RestartPolicy(max_restarts=0, backoff_base=0.01))
    sup.handle_death(1)
    _drain(sup, 1)
    assert sup.permanently_failed(1) is not None
    assert coord.stopped
    assert coord.failures and "restart budget" in coord.failures[0][1]


def test_supervisor_map_fun_error_is_not_restartable():
    coord = _StubCoordinator(
        info=[{"executor_id": 1, "launch_index": 1}],
        errors=[{"executor_id": 1, "traceback": "ValueError: app bug"}])
    launcher = _StubLauncher()
    sup = Supervisor(coord, launcher,
                     RestartPolicy(max_restarts=2, backoff_base=0.01))
    sup.handle_death(1)
    _drain(sup, 1)
    assert not launcher.respawned
    assert sup.permanently_failed(1) is not None
    assert coord.stopped


def test_supervisor_boot_death_consumes_budget():
    """A replacement that dies before re-registering never enters liveness
    tracking — the supervisor itself must notice (re-register window) and
    spend the remaining budget, rather than leaving the slot dark forever."""
    coord = _StubCoordinator(info=[{"executor_id": 1, "launch_index": 1}],
                             tracked_after_respawn=False)
    launcher = _StubLauncher(coord=coord)
    sup = Supervisor(coord, launcher,
                     RestartPolicy(max_restarts=2, backoff_base=0.01,
                                   backoff_max=0.02))
    sup._reregister_timeout = 0.1
    sup.handle_death(1)
    _drain(sup, 1)
    assert len(launcher.respawned) == 2       # both budgeted attempts spent
    assert sup.permanently_failed(1) is not None
    assert coord.stopped
    assert coord.failures and "restart budget" in coord.failures[0][1]


def test_supervisor_spares_late_registering_replacement():
    """A replacement that boots slower than the re-register window (cold
    jax/TPU init) but registers during the NEXT backoff must not be reaped —
    killing it would burn budget on a slot that already recovered."""
    coord = _StubCoordinator(info=[{"executor_id": 1, "launch_index": 1}],
                             tracked_after_respawn=False)
    launcher = _StubLauncher(coord=coord)
    sup = Supervisor(coord, launcher,
                     RestartPolicy(max_restarts=5, backoff_base=0.2,
                                   backoff_factor=1.5, backoff_max=0.3))
    sup._reregister_timeout = 0.05
    sup.handle_death(1)
    time.sleep(0.3)       # respawn #1 happened; its boot outlived the window
    coord.tracked = True  # ...but it registers during the next backoff
    _drain(sup, 1)
    assert len(launcher.respawned) == 1
    assert sup.permanently_failed(1) is None
    assert not coord.stopped


def test_elastic_refuses_jax_distributed():
    with pytest.raises(ValueError, match="jax_distributed"):
        tcluster.run(mapfuns.noop, None, num_executors=1,
                     jax_distributed=True, elastic=True)


def test_elastic_refuses_pod_launcher():
    from tensorflowonspark_tpu.launcher import TPUPodLauncher

    with pytest.raises(ValueError, match="TPUPodLauncher"):
        tcluster.run(mapfuns.noop, None, num_executors=1,
                     launcher=TPUPodLauncher(hosts=["h0"]), elastic=True)


# -- chaos end-to-end (deterministic, tier-1) --------------------------------

def _coverage(tmp_path):
    seen: list[int] = []
    for f in tmp_path.glob("seen_*.txt"):
        seen.extend(int(x) for x in f.read_text().split())
    return seen


@pytest.mark.chaos
def test_elastic_restart_resumes_from_checkpoint_and_completes(tmp_path, monkeypatch):
    """The acceptance scenario: 2-worker STREAMING train, SIGKILL one worker
    mid-epoch (after its 3rd batch), elastic=True.  train() must complete
    without raising, every item must be delivered (at-least-once), and the
    restarted worker must have resumed from the latest committed checkpoint
    under a bumped incarnation."""
    monkeypatch.setenv("TOS_SHM_RING", "0")  # a SIGKILL leaves rings wedged
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "4")
    monkeypatch.setenv("TOS_RESTART_BACKOFF_BASE", "0.2")
    items = list(range(120))
    parts = [items[i * 20:(i + 1) * 20] for i in range(6)]
    # per_node_env targets ONE launch slot; `incarnation=0` keeps the fault
    # disarmed in the replacement process (it re-parses the same env)
    per_node_env = [{}, {"TOS_FAULTINJECT": "kill:after_batches=3,incarnation=0"}]
    cluster = tcluster.run(
        mapfuns.elastic_sum_batches,
        {"batch_size": 2, "out_dir": str(tmp_path),
         "model_dir": str(tmp_path / "ckpt")},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        queue_capacity=4,
        heartbeat_interval=0.5,
        per_node_env=per_node_env,
        log_dir=str(tmp_path / "logs"),
        reservation_timeout=120.0,
        elastic=True,
    )
    cluster.train(parts, num_epochs=1)
    metas = {m["executor_id"]: m for m in cluster.coordinator.cluster_info()}
    victims = [eid for eid, m in metas.items() if m.get("incarnation") == 1]
    assert len(victims) == 1, metas
    victim = victims[0]
    assert cluster.supervisor.restart_count(victim) == 1
    # the replacement loaded the latest checkpoint its predecessor committed
    # (killed during batch 3 => steps 1 and 2 were saved)
    assert metas[victim]["resumed_step_inc1"] == 2
    # fencing: the predecessor's incarnation is burned, the slot is live
    assert cluster.coordinator.registered_incarnation(victim) == (1, True)
    cluster.shutdown(timeout=120.0)
    # the recovered death never became a fatal node error
    assert cluster.coordinator.errors() == []
    seen = _coverage(tmp_path)
    assert set(seen) == set(items)      # every partition delivered & consumed
    assert len(seen) >= len(items)      # at-least-once: duplicates allowed


@pytest.mark.chaos
def test_severed_data_socket_is_refed_without_restart(tmp_path, monkeypatch):
    """`sever` drops the data connection mid-stream with the node healthy:
    the driver must requeue the unacknowledged partition and re-feed it over
    a fresh connection — no supervisor involved, no item lost, and (because
    the sever fires before any of that partition's items were queued) none
    duplicated."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    items = list(range(80))
    parts = [items[i * 20:(i + 1) * 20] for i in range(4)]
    per_node_env = [{}, {"TOS_FAULTINJECT": "sever:after_data_ops=2"}]
    cluster = tcluster.run(
        mapfuns.elastic_sum_batches,
        {"batch_size": 4, "out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        per_node_env=per_node_env,
        log_dir=str(tmp_path / "logs"),
        reservation_timeout=120.0,
    )
    cluster.train(parts, num_epochs=1)
    cluster.shutdown(timeout=60.0)
    assert sorted(_coverage(tmp_path)) == items


@pytest.mark.chaos
def test_elastic_inference_retries_exactly_once_on_restarted_node(tmp_path, monkeypatch):
    """Killing a scoring node mid-partition must not lose or duplicate
    results: the in-flight partition is retried ONLY against the restarted
    node (fresh queues), and the partition-index dedupe keeps the output
    ordered exactly-count."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "4")
    monkeypatch.setenv("TOS_RESTART_BACKOFF_BASE", "0.2")
    import tensorflowonspark_tpu as tos

    vals = list(range(60))
    per_node_env = [{}, {"TOS_FAULTINJECT": "kill:after_batches=2,incarnation=0"}]
    cluster = tcluster.run(
        mapfuns.echo_inference, {},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        heartbeat_interval=0.5,
        per_node_env=per_node_env,
        log_dir=str(tmp_path),
        reservation_timeout=120.0,
        elastic=True,
    )
    preds = cluster.inference(tos.PartitionedDataset.from_iterable(vals, 6))
    cluster.shutdown(timeout=120.0)
    assert preds == [v * 2 for v in vals]


@pytest.mark.chaos
def test_feed_failure_names_executor_and_partition(tmp_path, monkeypatch):
    """Satellite: a feed failure that exhausts its retry budget surfaces a
    RuntimeError naming the executor AND partition (the old code collected
    bare exceptions with no identity)."""
    monkeypatch.setenv("TOS_SHM_RING", "0")
    monkeypatch.setenv("TOS_MAX_PARTITION_ATTEMPTS", "1")  # fail on first sever
    items = list(range(80))
    parts = [items[i * 20:(i + 1) * 20] for i in range(4)]
    per_node_env = [{}, {"TOS_FAULTINJECT": "sever:after_data_ops=2"}]
    cluster = tcluster.run(
        mapfuns.elastic_sum_batches,
        {"batch_size": 4, "out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        per_node_env=per_node_env,
        log_dir=str(tmp_path / "logs"),
        reservation_timeout=120.0,
    )
    with pytest.raises(RuntimeError,
                       match=r"feeding executor \d+ failed on partition \d+ "
                             r"\(epoch 0, attempt 1/1\)"):
        cluster.train(parts, num_epochs=1)
    cluster.shutdown(timeout=60.0)


# -- node death x pipelined consensus vote (ISSUE 3 satellite, weak #7) -------


def test_mark_dead_aborts_pipelined_vote_and_cons_pending_resets():
    """Deterministic interleaving of the dead-node monitor's abort with an
    in-flight PIPELINED consensus vote: result() must raise the abort
    promptly (never ride out the vote timeout), and — because the raise
    skips the _cons_pending clear — the NEXT all_done_begin must recover by
    resetting the dedicated consensus connection instead of deadlocking on
    its held lock."""
    from tensorflowonspark_tpu.feeding import FeedQueues
    from tensorflowonspark_tpu.node import NodeContext

    srv, clients = _fenced_pair()
    try:
        (c0, id0), (c1, id1) = clients
        info = [{"executor_id": 0, "job_name": "chief"},
                {"executor_id": 1, "job_name": "worker"}]
        ctx0 = NodeContext(
            executor_id=0, job_name="chief", task_index=0, num_executors=2,
            cluster_info=info, queues=FeedQueues(),
            config=NodeConfig(coordinator_addr=srv.address, authkey=None,
                              map_fun=mapfuns.noop),
            client=c0)
        result = ctx0.all_done_begin(False, timeout=60.0)
        assert ctx0._cons_pending
        time.sleep(0.3)  # let the vote join the generation
        srv.mark_dead([id1["executor_id"]], record_error=False)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="aborted"):
            result()
        assert time.monotonic() - t0 < 10.0  # abort, not the 60s vote timeout
        # the raise skipped the _cons_pending clear: the abandoned vote's
        # reply is unread and its connection lock still held
        assert ctx0._cons_pending
        old_cons = ctx0._cons_client
        result2 = ctx0.all_done_begin(True, timeout=30.0)
        assert ctx0._cons_client is not old_cons  # fresh connection, no deadlock
        # a replacement registers into the dead slot and completes the round
        c2 = CoordinatorClient(srv.address)
        ident2 = c2.register({"host": "h1-replacement"},
                             replace=id1["executor_id"])
        c2.set_identity(ident2["executor_id"], ident2["incarnation"])
        name = f"all_done:{c0._gen}"  # the generation ctx0's second vote used
        peer = threading.Thread(
            target=lambda: c2.reduce(name, True, kind="all", count=2,
                                     timeout=30.0), daemon=True)
        peer.start()
        assert result2() is True
        assert not ctx0._cons_pending
        peer.join(10.0)
        ctx0._reset_consensus_client()
        c2.close()
    finally:
        srv.stop()


@pytest.mark.chaos
def test_node_death_mid_pipelined_vote_unblocks_survivor(tmp_path, monkeypatch):
    """e2e: SIGKILL one node after its 2nd batch while its peer's pipelined
    consensus vote is in flight.  The survivor must see the monitor's abort
    within seconds (not the 120s vote timeout), survive the abandoned-vote
    reset, and exit; the driver must surface the death instead of hanging."""
    monkeypatch.setenv("TOS_SHM_RING", "0")  # a SIGKILL leaves rings wedged
    monkeypatch.setenv("TOS_DEAD_NODE_TIMEOUT", "4")
    items = list(range(120))
    parts = [items[i * 20:(i + 1) * 20] for i in range(6)]
    per_node_env = [{}, {"TOS_FAULTINJECT": "kill:after_batches=2"}]
    cluster = tcluster.run(
        mapfuns.pipelined_consensus_consumer,
        {"batch_size": 4, "out_dir": str(tmp_path), "step_delay": 0.05},
        num_executors=2,
        input_mode=tcluster.InputMode.STREAMING,
        heartbeat_interval=0.5,
        per_node_env=per_node_env,
        log_dir=str(tmp_path / "logs"),
        reservation_timeout=120.0,
    )
    t0 = time.monotonic()
    raised = []
    try:
        cluster.train(parts, num_epochs=1)
    except RuntimeError as e:
        raised.append(e)
    try:
        cluster.shutdown(timeout=120.0)
    except RuntimeError as e:
        raised.append(e)
    assert raised, "the node death was never surfaced to the driver"
    assert time.monotonic() - t0 < 120.0  # never rode out the vote timeout
    survivor = (tmp_path / "cons_0.txt").read_text() \
        if (tmp_path / "cons_0.txt").exists() else \
        (tmp_path / "cons_1.txt").read_text()
    assert survivor.startswith("aborted:"), survivor
    assert "reset-ok" in survivor, survivor
