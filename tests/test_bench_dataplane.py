"""The committed dataplane microbench must keep running (tier-1 smoke) —
it is the driver-verifiable evidence for the zero-copy data plane's fan-out
numbers in PERF_NOTES, so it must not rot between measurements."""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench_dataplane  # noqa: E402


def test_run_fanout_smoke_counts_every_row():
    r = bench_dataplane.run_fanout(
        2, row_bytes=10_000, rows_per_part=32, parts_per_node=2,
        wire=2, send_window=4, chunk_rows=8)
    assert r["num_nodes"] == 2
    assert r["mb_per_s"] > 0 and r["rows_per_s"] > 0
    # run_fanout raises on row loss; reaching here means 2*2*32 rows landed


def test_run_fanout_legacy_wire_smoke():
    r = bench_dataplane.run_fanout(
        1, row_bytes=1_000, rows_per_part=64, parts_per_node=2,
        wire=1, send_window=1, chunk_rows=32)
    assert r["wire"] == 1 and r["rows_per_s"] > 0


def test_metrics_compare_smoke_runs_both_legs():
    """The instrumentation-overhead guard must keep running (BENCH_r06):
    both legs complete, count every row, and report the overhead ratio.
    The 3% acceptance bar itself is asserted on the committed full-size
    numbers (BENCH_r06.json), not on this CI box's noisy quick run."""
    r = bench_dataplane.metrics_compare(quick=True, num_nodes=1, repeats=1)
    assert r["metrics_on"]["mb_per_s"] > 0
    assert r["metrics_off"]["mb_per_s"] > 0
    assert isinstance(r["overhead_pct"], float)
    # the off leg must actually have disabled the registry for its run and
    # restored the ambient default afterwards
    from tensorflowonspark_tpu import telemetry
    assert telemetry.enabled()


@pytest.mark.slow
def test_bench_quick_table_renders():
    results = bench_dataplane.bench(quick=True, fanout=(1, 2))
    table = bench_dataplane.markdown_table(results)
    assert "image_150KB" in table and "tabular_1KB" in table
    assert "zerocopy_v2_pipelined" in table
