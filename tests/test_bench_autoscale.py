"""Tier-1 smoke for the committed autoscaling bench (ISSUE 9): one quick
1x -> 4x -> 1x run must go end-to-end with the real policy loop and pass
its own acceptance gate — the guard that keeps ``bench_autoscale.py``
importable and runnable as the resize/serving paths evolve (numbers in
BENCH_r11.json come from full runs on an idle box)."""

from __future__ import annotations

import pytest


def test_bench_autoscale_quick_runs_and_tracks_step(monkeypatch):
    monkeypatch.setenv("TOS_SHM_RING", "0")
    import bench_autoscale  # repo root is on sys.path via conftest

    results = bench_autoscale.bench(quick=True)
    assert [r["phase"] for r in results["phases"]] == ["1x", "4x", "1x"]
    for r in results["phases"]:
        assert r["requests"] > 0 and r["qps"] > 0
        assert r["p99_ms"] >= r["p50_ms"] > 0
    # the gate the full run records into BENCH_r11.json
    acc = results["acceptance"]
    assert acc["scaled_out_on_step"], results["decisions"]["counts"]
    assert acc["scaled_back_in"], results["trajectory"][-5:]
    assert acc["errors_other"] == 0, results["errors_other"][:3]
    # the decision trail carries its stats justification
    counts = results["decisions"]["counts"]
    assert counts["scale_out"] >= 1 and counts["scale_in"] >= 1
    assert all("stats" in d for d in results["decisions"]["decisions"])
    # the sampled trajectory actually moved
    assert max(s["replicas"] for s in results["trajectory"]) > 1
    assert results["trajectory"][-1]["replicas"] == 1
    # the table renderer stays in sync with the result schema
    table = bench_autoscale.markdown_table(results)
    assert "4x" in table and "scale_out" in table
    # the CLI flag parses (argparse wiring)
    with pytest.raises(SystemExit):
        bench_autoscale.main(["--help"])
