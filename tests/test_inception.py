"""Inception-v3 + streaming-inference-loop tests (parity config 5)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.checkpoint import export_bundle
from tensorflowonspark_tpu.models import inception, wide_deep

import tensorflowonspark_tpu as tos
from tensorflowonspark_tpu.inference import bundle_inference_loop


def test_inception_forward_shape():
    """Full v3 topology at the smallest legal input (75x75, fully-conv)."""
    model = inception.InceptionV3(num_classes=10, compute_dtype=jnp.float32)
    variables = jax.jit(lambda k: model.init(
        k, jnp.zeros((1, 75, 75, 3), jnp.float32), train=True))(jax.random.PRNGKey(0))
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
        variables, jnp.zeros((2, 75, 75, 3)))
    assert logits.shape == (2, 10)
    # channel plan sanity: final concat before pool is 2048 channels
    assert variables["params"]["head"]["kernel"].shape[0] == 2048


def test_inception_registry():
    from tensorflowonspark_tpu.models.registry import build

    model = build({"model": "inception_v3", "num_classes": 7})
    assert model.num_classes == 7


@pytest.mark.slow
def test_bundle_inference_loop_e2e(tmp_path):
    """Streaming inference through a real cluster with a bundle-driven
    map_fun: ordered, exactly-count results (SURVEY.md §3.3 invariant).
    Uses wide_deep (fast on CPU); the loop itself is model-agnostic."""
    config = {"model": "wide_deep", "vocab_size": 101, "embed_dim": 2,
              "hidden": (4,), "bf16": False}
    model = wide_deep.build_wide_deep(config)
    params = wide_deep.init_params(model, jax.random.PRNGKey(0))
    export_bundle(str(tmp_path / "bundle"), jax.device_get(params), config)

    rows = wide_deep.synthetic_criteo(23)
    feats = [r["features"] for r in rows]
    cluster = tos.run(
        bundle_inference_loop,
        {"export_dir": str(tmp_path / "bundle"), "batch_size": 8},
        num_executors=2,
        input_mode=tos.InputMode.STREAMING,
        log_dir=str(tmp_path / "logs"),
    )
    try:
        preds = cluster.inference(tos.PartitionedDataset.from_iterable(feats, 3))
    finally:
        cluster.shutdown()
    assert len(preds) == 23
    # order check: scoring locally must match the streamed results
    apply = jax.jit(lambda p, x: model.apply({"params": p}, x))
    local = np.asarray(apply(params, np.stack(feats).astype(np.float32)))
    streamed = np.asarray([np.asarray(p).reshape(()) for p in preds])
    np.testing.assert_allclose(streamed, local, rtol=2e-4, atol=2e-4)
