"""Shuffling utilities (data.py): partition reorder + streaming buffer
shuffle — deterministic under seed, every element exactly once."""

from collections import Counter

from tensorflowonspark_tpu.data import PartitionedDataset, shuffle_buffer


def test_shuffle_partitions_is_permutation_and_deterministic():
    ds = PartitionedDataset.from_partitions([[1, 2], [3, 4], [5], [6, 7, 8]])
    s1 = ds.shuffle_partitions(seed=7)
    s2 = ds.shuffle_partitions(seed=7)
    s3 = ds.shuffle_partitions(seed=8)
    assert list(s1) == list(s2)                      # deterministic
    assert sorted(s1) == sorted(ds)                  # permutation of elements
    assert s1.num_partitions == ds.num_partitions
    # partitions move as units
    flat = list(s1)
    assert [6, 7, 8] == flat[flat.index(6) : flat.index(6) + 3]
    assert list(s3) != list(s1)                      # seed matters


def test_shuffle_buffer_exactly_once_and_deterministic():
    items = list(range(100))
    out1 = list(shuffle_buffer(items, buffer_size=16, seed=3))
    out2 = list(shuffle_buffer(items, buffer_size=16, seed=3))
    assert out1 == out2
    assert Counter(out1) == Counter(items)           # exactly once
    assert out1 != items                             # actually shuffled


def test_shuffle_buffer_small_input_and_full_buffer():
    # input smaller than buffer: pure Fisher-Yates of everything
    out = list(shuffle_buffer([1, 2, 3], buffer_size=10, seed=0))
    assert Counter(out) == Counter([1, 2, 3])
    # buffer_size 1 degenerates to identity order
    assert list(shuffle_buffer(list(range(10)), buffer_size=1, seed=0)) == list(range(10))
