"""ResNet model family tests (parity config 3, BASELINE.json:9).

Runs on the virtual 8-device CPU mesh (conftest) with a tiny ResNet so the
sharded train-step path — dp batch split + fsdp param shard + BN stat
mutation — is exercised exactly as the flagship runs it on a pod.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.models import resnet
from tensorflowonspark_tpu.parallel import dp as dplib
from tensorflowonspark_tpu.parallel import mesh as meshlib


def tiny_resnet():
    return resnet.ResNet(stage_sizes=(1, 1, 1, 1), num_classes=8, width=8,
                         compute_dtype=jnp.float32)


def make_state(model, mesh, optimizer):
    # jit the init: one (persistently cached) XLA program instead of
    # hundreds of eager per-op compiles — 1-core-box wall-clock hygiene
    variables = jax.jit(lambda k: model.init(
        k, jnp.zeros((1, 32, 32, 3), jnp.float32), train=True))(jax.random.PRNGKey(0))
    params = meshlib.shard_tree(mesh, variables["params"])
    batch_stats = meshlib.shard_tree(
        mesh, variables["batch_stats"],
        jax.tree.map(lambda _: meshlib.replicated(mesh), variables["batch_stats"]))
    return dplib.BNTrainState.create(params, batch_stats, optimizer)


def make_batch(mesh, n=16, num_classes=8, seed=0):
    rng = np.random.RandomState(seed)
    return meshlib.shard_batch(mesh, {
        "image": rng.rand(n, 32, 32, 3).astype(np.float32),
        "label": (np.arange(n) % num_classes).astype(np.int32),
    })


def test_forward_shapes():
    model = tiny_resnet()
    variables = jax.jit(lambda k: model.init(
        k, jnp.zeros((1, 32, 32, 3), jnp.float32), train=True))(jax.random.PRNGKey(0))
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
        variables, jnp.zeros((4, 32, 32, 3)))
    assert logits.shape == (4, 8)
    assert logits.dtype == jnp.float32


def test_resnet50_registry_builds():
    from tensorflowonspark_tpu.models.registry import build

    model = build({"model": "resnet50", "num_classes": 10})
    assert model.stage_sizes == (3, 4, 6, 3)
    assert model.num_classes == 10


@pytest.fixture
def no_persistent_cache():
    """This jaxlib build cannot round-trip the bn-train-step executables
    through the persistent compilation cache: reloading the fsdp variant
    corrupts the heap (glibc "corrupted size vs. prev_size" abort that kills
    the whole pytest process), and reloading the dp variant silently returns
    zeroed batch_stats aux outputs.  Cold compiles are correct, so these two
    tests opt out of the cache and pay the ~30s compile every run."""
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def test_train_step_descends_loss_fsdp_mesh(no_persistent_cache):
    mesh = meshlib.make_mesh(dp=-1, fsdp=2)
    model = tiny_resnet()
    optimizer = optax.sgd(0.05, momentum=0.9)
    state = make_state(model, mesh, optimizer)
    step_fn = dplib.make_bn_train_step(resnet.make_loss_fn(model, weight_decay=0.0),
                                       optimizer)
    batch = make_batch(mesh)
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(jax.device_get(state.step)) == 5


def test_batch_stats_update(no_persistent_cache):
    mesh = meshlib.make_mesh(dp=-1)
    model = tiny_resnet()
    optimizer = optax.sgd(0.05)
    state = make_state(model, mesh, optimizer)
    before = jax.device_get(state.batch_stats)
    step_fn = dplib.make_bn_train_step(resnet.make_loss_fn(model, weight_decay=0.0),
                                       optimizer)
    state, _ = step_fn(state, make_batch(mesh))
    after = jax.device_get(state.batch_stats)
    diffs = jax.tree.map(lambda a, b: float(np.abs(a - b).max()), before, after)
    assert max(jax.tree.leaves(diffs)) > 0.0


def test_fsdp_shardings_split_largest_divisible_dim():
    mesh = meshlib.make_mesh(dp=-1, fsdp=2)
    tree = {"kernel": jnp.zeros((6, 8)), "bias": jnp.zeros((3,)), "scalar": jnp.zeros(())}
    shardings = meshlib.fsdp_shardings(mesh, tree)
    assert shardings["kernel"].spec == jax.sharding.PartitionSpec(None, "fsdp")
    # bias dim 3 is not divisible by 2 -> replicated
    assert shardings["bias"].spec == jax.sharding.PartitionSpec()
    assert shardings["scalar"].spec == jax.sharding.PartitionSpec()


@pytest.mark.dryrun
@pytest.mark.slow
def test_graft_entry_dryrun():
    """The driver's multichip gate runs this same entry point directly every
    round — the ONE test whose coverage is independently re-executed outside
    the suite.  Opt-in (`-m dryrun`, ~90s: six full SPMD train-step compiles)
    so the default gate can afford to include every other slow test.  Also
    marked ``slow``: a bare ``-m 'not slow'`` on the command line REPLACES the
    addopts marker filter, and this duplicate of the driver's own gate should
    not ride back in through that door."""
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_forward_tiny():
    """entry() builds the real ResNet-50; too big for CPU CI — check the
    callable contract on a tiny clone instead."""
    model = tiny_resnet()
    variables = jax.jit(lambda k: model.init(
        k, jnp.zeros((1, 32, 32, 3), jnp.float32), train=True))(jax.random.PRNGKey(0))

    def forward(params, batch_stats, images):
        return model.apply({"params": params, "batch_stats": batch_stats},
                           images, train=False)

    out = jax.jit(forward)(variables["params"], variables["batch_stats"],
                           jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 8)


def test_space_to_depth_stem_shapes_and_grads():
    """Opt-in MLPerf stem: same output shape as the classic stem, trains
    (finite loss + grads).  Numerics intentionally differ — it is a model
    variant, not a weight-compatible rewrite."""
    import optax

    from tensorflowonspark_tpu.parallel import dp as dplib
    from tensorflowonspark_tpu.parallel import mesh as meshlib

    classic = resnet.ResNet(stage_sizes=(1, 1), num_classes=8, width=16,
                            compute_dtype=jnp.float32, norm_dtype=jnp.float32)
    s2d = resnet.ResNet(stage_sizes=(1, 1), num_classes=8, width=16,
                        compute_dtype=jnp.float32, norm_dtype=jnp.float32,
                        stem="space_to_depth")
    x = jnp.asarray(np.random.RandomState(0).rand(2, 64, 64, 3), jnp.float32)
    vc = classic.init(jax.random.PRNGKey(0), x, train=True)
    vs = s2d.init(jax.random.PRNGKey(0), x, train=True)
    out_c = classic.apply(vc, x, train=False)
    out_s = s2d.apply(vs, x, train=False)
    assert out_c.shape == out_s.shape == (2, 8)
    # stem kernel really is the 4x4-on-12-channels form
    assert vs["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 16)

    mesh = meshlib.make_mesh(dp=-1)
    state = dplib.BNTrainState.create(
        meshlib.shard_tree(mesh, vs["params"],
                           jax.tree.map(lambda _: meshlib.replicated(mesh),
                                        vs["params"])),
        meshlib.shard_tree(mesh, vs["batch_stats"],
                           jax.tree.map(lambda _: meshlib.replicated(mesh),
                                        vs["batch_stats"])),
        optax.sgd(0.1))
    step = dplib.make_bn_train_step(resnet.make_loss_fn(s2d), optax.sgd(0.1))
    batch = meshlib.shard_batch(mesh, {
        "image": np.random.RandomState(1).rand(8, 64, 64, 3).astype(np.float32),
        "label": (np.arange(8) % 8).astype(np.int32)})
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
