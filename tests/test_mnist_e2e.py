"""End-to-end MNIST parity tests: the reference's examples-as-tests
(SURVEY.md §4 'Example-as-test'), covering parity configs 1 (streaming), 2
(direct TFRecords) and the bundle-export → streaming-inference loop.

Real node processes + real JAX (CPU); tiny model/shapes to fit this box.
"""

import os
import sys

import pytest

import tensorflowonspark_tpu as tos

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples", "mnist")
if EXAMPLES not in sys.path:
    sys.path.insert(0, EXAMPLES)

import mnist_dist  # noqa: E402
import mnist_tfr  # noqa: E402

TINY = {"features": [4, 8], "dense": 16, "batch_size": 16, "lr": 0.05}


@pytest.mark.slow
def test_streaming_train_then_inference(tmp_path):
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    args = {**TINY, "model_dir": str(tmp_path / "model"), "export_dir": str(tmp_path / "export"),
            "log_dir": str(tmp_path / "logs")}
    data = tos.PartitionedDataset.from_iterable(synthetic_mnist(240), 4)

    cluster = tos.run(mnist_dist.main_fun, args, num_executors=2,
                      input_mode=tos.InputMode.STREAMING,
                      log_dir=str(tmp_path / "nodelogs"), reservation_timeout=120)
    cluster.train(data, num_epochs=2)
    cluster.shutdown(timeout=300)

    # checkpoint + bundle landed
    assert os.path.isdir(tmp_path / "model")
    assert os.path.exists(tmp_path / "export" / "bundle.json")
    # tensorboard events written by the chief
    import glob

    assert glob.glob(str(tmp_path / "logs" / "train" / "events.out.tfevents.*"))

    # streaming inference over the exported bundle: ordered, exactly-count
    infer_args = {**TINY, "export_dir": str(tmp_path / "export")}
    c2 = tos.run(mnist_dist.inference_fun, infer_args, num_executors=2,
                 input_mode=tos.InputMode.STREAMING,
                 log_dir=str(tmp_path / "nodelogs2"), reservation_timeout=120)
    samples = synthetic_mnist(64, seed=9)
    preds = c2.inference([list(p) for p in
                          (samples[:20], samples[20:45], samples[45:])])
    c2.shutdown(timeout=300)
    assert len(preds) == 64
    assert all(isinstance(p, int) and 0 <= p < 10 for p in preds)
    # the synthetic task is learnable: most predictions should be right
    labels = [l for _, l in samples]
    acc = sum(p == l for p, l in zip(preds, labels)) / len(labels)
    assert acc > 0.5, f"accuracy {acc}"


@pytest.mark.slow
def test_restart_resumes_from_checkpoint(tmp_path):
    """Whole-job restart (SURVEY.md §5.3 recovery contract): a second cluster
    pointed at the same model_dir must resume from the saved FULL train state
    — the step counter keeps counting instead of resetting to zero."""
    from tensorflowonspark_tpu.checkpoint import latest_step_dir
    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    args = {**TINY, "model_dir": str(tmp_path / "model")}
    data = tos.PartitionedDataset.from_iterable(synthetic_mnist(40), 2)

    c1 = tos.run(mnist_dist.main_fun, args, num_executors=1,
                 input_mode=tos.InputMode.STREAMING,
                 log_dir=str(tmp_path / "logs1"), reservation_timeout=120)
    c1.train(data)
    c1.shutdown(timeout=300)
    first = latest_step_dir(str(tmp_path / "model"))
    step1 = int(first.rsplit("_", 1)[1])
    assert step1 > 0

    # "restart": a brand-new cluster over the same model_dir
    c2 = tos.run(mnist_dist.main_fun, args, num_executors=1,
                 input_mode=tos.InputMode.STREAMING,
                 log_dir=str(tmp_path / "logs2"), reservation_timeout=120)
    c2.train(data)
    c2.shutdown(timeout=300)
    step2 = int(latest_step_dir(str(tmp_path / "model")).rsplit("_", 1)[1])
    assert step2 == 2 * step1, (step1, step2)  # resumed, not restarted


def test_direct_tfrecord_train(tmp_path):
    data_dir = str(tmp_path / "tfr")
    mnist_tfr.prepare_data(data_dir, samples=320, partitions=4)
    args = {**TINY, "data_dir": data_dir, "export_dir": str(tmp_path / "export"), "epochs": 1}
    cluster = tos.run(mnist_tfr.main_fun, args, num_executors=2,
                      input_mode=tos.InputMode.DIRECT,
                      log_dir=str(tmp_path / "nodelogs"), reservation_timeout=120)
    cluster.shutdown(timeout=300)
    assert os.path.exists(tmp_path / "export" / "bundle.json")


@pytest.mark.slow
def test_evaluator_role_evaluates(tmp_path):
    """The evaluator node must observably evaluate (VERDICT r3 item 10):
    it loads checkpoints as the chief writes them, publishes accuracies
    through the meta channel, writes eval scalars, and exits cleanly once
    the chief drops the TRAINING_DONE marker — all without participating
    in the data feed or the training consensus."""
    import glob

    from tensorflowonspark_tpu.models.mnist import synthetic_mnist

    args = {**TINY, "model_dir": str(tmp_path / "model"),
            "log_dir": str(tmp_path / "logs"),
            "checkpoint_every": 2, "eval_interval": 0.2,
            "eval_samples": 64}
    data = tos.PartitionedDataset.from_iterable(synthetic_mnist(128), 4)
    # 3 executors = chief + worker + evaluator
    cluster = tos.run(mnist_dist.main_fun, args, num_executors=3,
                      eval_node=True, input_mode=tos.InputMode.STREAMING,
                      log_dir=str(tmp_path / "nodelogs"),
                      reservation_timeout=120)
    cluster.train(data)
    cluster.shutdown(timeout=300)
    metas = cluster.coordinator.cluster_info()
    ev = next(m for m in metas if m["job_name"] == "evaluator")
    evals = ev.get("evals")
    assert evals, f"evaluator never evaluated: {ev}"
    # it scored the FINAL checkpoint (written by the coordinated chief_save)
    from tensorflowonspark_tpu.checkpoint import latest_step_dir

    final_step = int(latest_step_dir(args["model_dir"]).rsplit("_", 1)[1])
    assert evals[-1]["step"] == final_step
    assert all(0.0 <= e["accuracy"] <= 1.0 for e in evals)
    # eval scalars landed in their own TB event file
    assert glob.glob(str(tmp_path / "logs" / "eval" / "events.out.tfevents.*"))
    assert os.path.exists(tmp_path / "model" / "TRAINING_DONE")
