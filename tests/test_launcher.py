"""Launcher unit tests: per-host env composition and transport plumbing.

The heavyweight end-to-end (two real node processes joining one
jax.distributed job) lives in test_distributed.py; these tests pin the
cheap invariants: chip-slice env derivation (the CUDA_VISIBLE_DEVICES
analogue), ssh command construction, and payload delivery over stdin.
"""

from __future__ import annotations

import io

import cloudpickle

from tensorflowonspark_tpu import launcher as launchermod
from tensorflowonspark_tpu.launcher import SubprocessLauncher, TPUPodLauncher
from tensorflowonspark_tpu.node import NodeConfig


def _config(**kw) -> NodeConfig:
    return NodeConfig(coordinator_addr=("127.0.0.1", 1), authkey=b"k",
                      map_fun=lambda a, c: None, **kw)


class _CapturingStdin(io.BytesIO):
    def close(self):
        self.value = self.getvalue()
        super().close()


class _FakeProc:
    def __init__(self):
        self.stdin = _CapturingStdin()
        self.returncode = None

    def poll(self):
        return self.returncode


def test_pod_launcher_chip_slice_env():
    pod = TPUPodLauncher(
        hosts=["host-a", "host-b"],
        chip_slices=[[0, 1], [2, 3]],
        chip_coords=[[[0, 0, 0], [1, 0, 0]], [[0, 1, 0], [1, 1, 0]]],
    )
    env0, env1 = pod.host_env(0), pod.host_env(1)
    assert env0["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env1["TPU_VISIBLE_CHIPS"] == "2,3"
    # bounds derived from the discovered coords, not guessed
    assert env0["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"
    assert env1["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"


def test_pod_launcher_cpu_simulation_env():
    pod = TPUPodLauncher(hosts=["localhost"], transport="local",
                         platform="cpu", simulate_chips=4)
    env = pod.host_env(0)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["JAX_NUM_CPU_DEVICES"] == "4"
    assert env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] == "gloo"


def test_pod_launcher_custom_transport_delivers_payload():
    spawned = []

    def transport(host, command, env):
        proc = _FakeProc()
        spawned.append((host, command, env, proc))
        return proc

    pod = TPUPodLauncher(hosts=["h0", "h1"], transport=transport,
                         env={"MY_FLAG": "1"})
    configs = [_config(), _config()]
    pod.launch(configs)
    assert [s[0] for s in spawned] == ["h0", "h1"]
    for (host, command, env, proc), config in zip(spawned, configs):
        assert command[-2:] == ["-m", "tensorflowonspark_tpu.node_entry"]
        # pod membership forces the jax.distributed bootstrap
        got = cloudpickle.loads(proc.stdin.value)
        assert got.jax_distributed is True
        assert got.env["MY_FLAG"] == "1"
    assert pod.alive() == [0, 1]


def test_pod_launcher_ssh_command(monkeypatch):
    calls = []

    def fake_popen(cmd, **kw):
        calls.append(cmd)
        return _FakeProc()

    monkeypatch.setattr(launchermod.subprocess, "Popen", fake_popen)
    pod = TPUPodLauncher(hosts=["tpu-vm-0"],
                         env={"A": "1", "XLA_FLAGS": "--flag_a --flag_b"})
    pod.launch([_config()])
    (cmd,) = calls
    assert cmd[0] == "ssh"
    assert "tpu-vm-0" in cmd
    env_i = cmd.index("env")
    assert "A=1" in cmd[env_i:]
    # ssh flattens argv into one remote shell line: values with spaces must
    # arrive shell-quoted or `env` would execute '--flag_b' as the command
    assert "'XLA_FLAGS=--flag_a --flag_b'" in cmd[env_i:]
    assert cmd[-1].endswith("tensorflowonspark_tpu.node_entry")


def test_pod_launcher_rejects_mismatched_configs():
    pod = TPUPodLauncher(hosts=["a"])
    try:
        pod.launch([_config(), _config()])
    except ValueError as e:
        assert "2 configs" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_subprocess_launcher_handle_lifecycle():
    import subprocess
    import sys

    launcher = SubprocessLauncher()
    # bypass launch(): exercise the handle adapter directly on a real process
    proc = subprocess.Popen([sys.executable, "-c", "import sys; sys.exit(3)"])
    handle = launchermod.PopenHandle(proc)
    launcher._procs.append(handle)
    assert launcher.join(timeout=30.0)
    assert handle.exitcode == 3
    assert launcher.alive() == []
