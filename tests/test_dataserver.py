"""Data-plane server/client tests (replaces the reference's manager-queue
feeding paths, SURVEY.md §3.2/§3.3)."""

import threading

import pytest

from tensorflowonspark_tpu.dataserver import DataClient, DataServer
from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues

AUTH = b"secret"


def start_pair(feed_timeout=5.0, capacity=1024):
    queues = FeedQueues(capacity=capacity)
    server = DataServer(queues, AUTH, feed_timeout=feed_timeout)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=8,
                        stall_timeout=feed_timeout)
    return queues, server, client


def test_feed_partition_and_markers():
    queues, server, client = start_pair()
    feed = DataFeed(queues)
    state = client.feed_partition(range(20))
    assert state == "running"
    client.send_eof()
    assert feed.next_batch(100) == list(range(20))
    assert feed.next_batch(1) == []
    assert feed.should_stop()
    client.close()
    server.stop()


def test_auth_rejected():
    queues = FeedQueues()
    server = DataServer(queues, AUTH)
    port = server.start()
    with pytest.raises(RuntimeError, match="auth"):
        DataClient("127.0.0.1", port, b"wrong")
    server.stop()


def test_infer_exactly_count_ordered():
    queues, server, client = start_pair()

    def model():
        feed = DataFeed(queues, train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(4)
            if batch:
                feed.batch_results([x * x for x in batch])

    t = threading.Thread(target=model, daemon=True)
    t.start()
    results = client.infer_partition(list(range(30)))
    assert results == [x * x for x in range(30)]
    client.send_eof()
    t.join(5)
    client.close()
    server.stop()


def test_infer_empty_partition():
    queues, server, client = start_pair()
    assert client.infer_partition([]) == []
    client.close()
    server.stop()


def test_terminating_fast_drain():
    queues, server, client = start_pair()
    feed = DataFeed(queues)
    feed.terminate()
    state = client.feed_partition(range(10_000))
    assert state == "terminating"
    client.close()
    server.stop()


def test_feed_timeout_when_consumer_stalls():
    queues, server, client = start_pair(feed_timeout=0.3, capacity=4)
    with pytest.raises(RuntimeError, match="feed timeout"):
        client.feed_partition(range(100))
    client.close()
    server.stop()


def test_infer_timeout_when_model_absent():
    queues, server, client = start_pair(feed_timeout=0.3)
    with pytest.raises(RuntimeError, match="inference produced"):
        client.infer_partition([1, 2, 3])
    client.close()
    server.stop()


def test_ring_upgrade_engages_on_localhost():
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    queues, server, client = start_pair()
    assert client.using_ring
    feed = DataFeed(queues)
    client.feed_partition(range(50))
    client.send_eof()
    assert feed.next_batch(100) == list(range(50))
    client.close()
    server.stop()


def test_tcp_path_still_works_when_ring_disabled():
    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=8, prefer_ring=False)
    assert not client.using_ring
    feed = DataFeed(queues)
    client.feed_partition(range(10))
    client.send_eof()
    assert feed.next_batch(100) == list(range(10))
    client.close()
    server.stop()


def test_oversized_messages_stream_through_ring():
    # Chunks (and replies) larger than the ring are segmented transparently
    # in both directions; the client stays on the ring throughout.
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=4,
                        ring_capacity=64 * 1024)
    assert client.using_ring
    feed = DataFeed(queues)
    big = b"B" * (200 * 1024)  # one chunk of these exceeds the 64k ring
    client.feed_partition([big, big, b"small"])
    client.send_eof()
    got = feed.next_batch(10)
    assert got == [big, big, b"small"]
    assert client.using_ring  # never downgraded
    client.close()
    server.stop()

    # Fresh pair for the reply direction (the EOF above still sits in the
    # old input queue): replies larger than the ring segment too.
    queues2 = FeedQueues(capacity=1024)
    server2 = DataServer(queues2, AUTH, feed_timeout=5.0)
    client2 = DataClient("127.0.0.1", server2.start(), AUTH, chunk_size=4,
                         ring_capacity=64 * 1024)
    assert client2.using_ring

    def model():
        f = DataFeed(queues2, train_mode=False)
        while not f.should_stop():
            batch = f.next_batch(4)
            if batch:
                f.batch_results([x * 3 for x in batch])  # replies > ring too

    t = threading.Thread(target=model, daemon=True)
    t.start()
    assert client2.infer_partition([big, b"x"]) == [big * 3, b"xxx"]
    assert client2.using_ring
    client2.send_eof()
    t.join(5)
    client2.close()
    server2.stop()


def test_ring_inference_roundtrip():
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    queues, server, client = start_pair()
    assert client.using_ring

    def model():
        feed = DataFeed(queues, train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(4)
            if batch:
                feed.batch_results([x + 1 for x in batch])

    t = threading.Thread(target=model, daemon=True)
    t.start()
    assert client.infer_partition(list(range(40))) == [x + 1 for x in range(40)]
    client.send_eof()
    t.join(5)
    client.close()
    server.stop()


def test_send_eof_after_server_stop_fails_fast():
    """Teardown race regression: a node can stop its data plane before the
    driver's EOF arrives.  On the shm-ring transport that used to block for
    the FULL call timeout (~minutes) because nothing closed the rings before
    process exit; server.stop() now joins ring threads (rings close) and
    send_eof carries its own short timeout.  The driver must see an error
    within seconds either way."""
    import time

    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        # TCP-only: established connections outlive stop() by design (the
        # node process exit closes them); the fast-fail contract under test
        # is specific to the ring transport.
        pytest.skip("native shm ring not buildable")
    queues, server, client = start_pair(feed_timeout=600.0)
    assert client.using_ring
    client.send_eof("input")  # healthy path works
    server.stop()
    t0 = time.monotonic()
    with pytest.raises(Exception):
        client.send_eof("input")
        # ring path may downgrade to TCP and fail there; either way:
        client.send_eof("input")
    assert time.monotonic() - t0 < 30.0
    client.close()
