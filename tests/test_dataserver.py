"""Data-plane server/client tests (replaces the reference's manager-queue
feeding paths, SURVEY.md §3.2/§3.3)."""

import threading

import pytest

from tensorflowonspark_tpu.dataserver import DataClient, DataServer
from tensorflowonspark_tpu.feeding import DataFeed, FeedQueues

AUTH = b"secret"


def start_pair(feed_timeout=5.0, capacity=1024):
    queues = FeedQueues(capacity=capacity)
    server = DataServer(queues, AUTH, feed_timeout=feed_timeout)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=8,
                        stall_timeout=feed_timeout)
    return queues, server, client


def test_feed_partition_and_markers():
    queues, server, client = start_pair()
    feed = DataFeed(queues)
    state = client.feed_partition(range(20))
    assert state == "running"
    client.send_eof()
    assert feed.next_batch(100) == list(range(20))
    assert feed.next_batch(1) == []
    assert feed.should_stop()
    client.close()
    server.stop()


def test_auth_rejected():
    queues = FeedQueues()
    server = DataServer(queues, AUTH)
    port = server.start()
    with pytest.raises(RuntimeError, match="auth"):
        DataClient("127.0.0.1", port, b"wrong")
    server.stop()


def test_infer_exactly_count_ordered():
    queues, server, client = start_pair()

    def model():
        feed = DataFeed(queues, train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(4)
            if batch:
                feed.batch_results([x * x for x in batch])

    t = threading.Thread(target=model, daemon=True)
    t.start()
    results = client.infer_partition(list(range(30)))
    assert results == [x * x for x in range(30)]
    client.send_eof()
    t.join(5)
    client.close()
    server.stop()


def test_infer_empty_partition():
    queues, server, client = start_pair()
    assert client.infer_partition([]) == []
    client.close()
    server.stop()


def test_terminating_fast_drain():
    queues, server, client = start_pair()
    feed = DataFeed(queues)
    feed.terminate()
    state = client.feed_partition(range(10_000))
    assert state == "terminating"
    client.close()
    server.stop()


def test_feed_timeout_when_consumer_stalls():
    queues, server, client = start_pair(feed_timeout=0.3, capacity=4)
    with pytest.raises(RuntimeError, match="feed timeout"):
        client.feed_partition(range(100))
    client.close()
    server.stop()


def test_infer_timeout_when_model_absent():
    queues, server, client = start_pair(feed_timeout=0.3)
    with pytest.raises(RuntimeError, match="inference produced"):
        client.infer_partition([1, 2, 3])
    client.close()
    server.stop()


def test_ring_upgrade_engages_on_localhost(monkeypatch):
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    # TOS_SHM_RING=1 forces the ring regardless of what the transport probe
    # measures on this box (unset means probe-decides; see utils.net)
    monkeypatch.setenv("TOS_SHM_RING", "1")
    queues, server, client = start_pair()
    assert client.using_ring
    feed = DataFeed(queues)
    client.feed_partition(range(50))
    client.send_eof()
    assert feed.next_batch(100) == list(range(50))
    client.close()
    server.stop()


def test_tcp_path_still_works_when_ring_disabled():
    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=8, prefer_ring=False)
    assert not client.using_ring
    feed = DataFeed(queues)
    client.feed_partition(range(10))
    client.send_eof()
    assert feed.next_batch(100) == list(range(10))
    client.close()
    server.stop()


def test_oversized_messages_stream_through_ring(monkeypatch):
    # Chunks (and replies) larger than the ring are segmented transparently
    # in both directions; the client stays on the ring throughout.
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    monkeypatch.setenv("TOS_SHM_RING", "1")
    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=4,
                        ring_capacity=64 * 1024)
    assert client.using_ring
    feed = DataFeed(queues)
    big = b"B" * (200 * 1024)  # one chunk of these exceeds the 64k ring
    client.feed_partition([big, big, b"small"])
    client.send_eof()
    got = feed.next_batch(10)
    assert got == [big, big, b"small"]
    assert client.using_ring  # never downgraded
    client.close()
    server.stop()

    # Fresh pair for the reply direction (the EOF above still sits in the
    # old input queue): replies larger than the ring segment too.
    queues2 = FeedQueues(capacity=1024)
    server2 = DataServer(queues2, AUTH, feed_timeout=5.0)
    client2 = DataClient("127.0.0.1", server2.start(), AUTH, chunk_size=4,
                         ring_capacity=64 * 1024)
    assert client2.using_ring

    def model():
        f = DataFeed(queues2, train_mode=False)
        while not f.should_stop():
            batch = f.next_batch(4)
            if batch:
                f.batch_results([x * 3 for x in batch])  # replies > ring too

    t = threading.Thread(target=model, daemon=True)
    t.start()
    assert client2.infer_partition([big, b"x"]) == [big * 3, b"xxx"]
    assert client2.using_ring
    client2.send_eof()
    t.join(5)
    client2.close()
    server2.stop()


def test_ring_inference_roundtrip(monkeypatch):
    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    monkeypatch.setenv("TOS_SHM_RING", "1")
    queues, server, client = start_pair()
    assert client.using_ring

    def model():
        feed = DataFeed(queues, train_mode=False)
        while not feed.should_stop():
            batch = feed.next_batch(4)
            if batch:
                feed.batch_results([x + 1 for x in batch])

    t = threading.Thread(target=model, daemon=True)
    t.start()
    assert client.infer_partition(list(range(40))) == [x + 1 for x in range(40)]
    client.send_eof()
    t.join(5)
    client.close()
    server.stop()


def test_send_eof_after_server_stop_fails_fast(monkeypatch):
    """Teardown race regression: a node can stop its data plane before the
    driver's EOF arrives.  On the shm-ring transport that used to block for
    the FULL call timeout (~minutes) because nothing closed the rings before
    process exit; server.stop() now joins ring threads (rings close) and
    send_eof carries its own short timeout.  The driver must see an error
    within seconds either way."""
    import time

    from tensorflowonspark_tpu import shm_ring

    if not shm_ring.available():
        # TCP-only: established connections outlive stop() by design (the
        # node process exit closes them); the fast-fail contract under test
        # is specific to the ring transport.
        pytest.skip("native shm ring not buildable")
    monkeypatch.setenv("TOS_SHM_RING", "1")
    queues, server, client = start_pair(feed_timeout=600.0)
    assert client.using_ring
    client.send_eof("input")  # healthy path works
    server.stop()
    t0 = time.monotonic()
    with pytest.raises(Exception):
        client.send_eof("input")
        # ring path may downgrade to TCP and fail there; either way:
        client.send_eof("input")
    assert time.monotonic() - t0 < 30.0
    client.close()


# -- zero-copy wire format (ISSUE 3 tentpole) ---------------------------------


def test_wire_negotiates_v2_and_packs_chunks():
    """Current client x current server negotiate the vectorized wire and
    round-trip packed bytes/ndarray/tuple/dict chunks bit-identically."""
    import numpy as np

    queues, server, client = start_pair()
    assert client._wire >= 2  # vectorized wire (v3 = v2 frames + trace ops)
    feed = DataFeed(queues)
    byte_rows = [bytes([i]) * 4096 for i in range(20)]
    assert client.feed_partition(byte_rows) == "running"
    assert feed.next_batch(100) == byte_rows
    arr_rows = [np.full((4, 3), i, np.float32) for i in range(10)]
    assert client.feed_partition(arr_rows) == "running"
    got = feed.next_batch(100)
    assert all(np.array_equal(a, b) and a.dtype == b.dtype
               for a, b in zip(arr_rows, got))
    tup_rows = [(np.arange(6, dtype=np.int64) + i, i) for i in range(10)]
    assert client.feed_partition(tup_rows) == "running"
    got = feed.next_batch(100)
    assert all(np.array_equal(a[0], b[0]) and a[1] == b[1]
               for a, b in zip(tup_rows, got))
    dict_rows = [{"x": np.ones(3, np.float32) * i, "label": i}
                 for i in range(10)]
    assert client.feed_partition(dict_rows) == "running"
    got = feed.next_batch(100)
    assert all(np.array_equal(a["x"], b["x"]) and a["label"] == b["label"]
               for a, b in zip(dict_rows, got))
    client.close()
    server.stop()


def test_wire_v2_roundtrip_values_exact():
    import numpy as np

    queues, server, client = start_pair()
    feed = DataFeed(queues)
    rows = [bytes([i]) * 1000 for i in range(16)]
    client.feed_partition(rows)
    assert feed.next_batch(100) == rows
    arrs = [np.full((5, 2), i, np.int64) for i in range(8)]
    client.feed_partition(arrs)
    got = feed.next_batch(100)
    assert all(np.array_equal(a, b) and a.dtype == b.dtype
               for a, b in zip(arrs, got))
    dicts = [{"x": np.full(4, i, np.float32), "y": float(i)} for i in range(6)]
    client.feed_partition(dicts)
    got = feed.next_batch(100)
    assert all(np.array_equal(a["x"], b["x"]) and a["y"] == b["y"]
               for a, b in zip(dicts, got))
    client.close()
    server.stop()


def test_old_server_negotiates_down_to_v1():
    """A server that predates the hello op answers unknown-op; the client
    must stay on the v1 wire and still feed correctly (auto-negotiation)."""
    from tensorflowonspark_tpu import dataserver as ds

    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    orig_handle = ds.DataServer._handle

    def legacy_handle(self, msg):
        if msg[0] == "hello":  # old servers have no hello branch
            return ("err", f"unknown op {msg[0]!r}")
        return orig_handle(self, msg)

    server._handle = legacy_handle.__get__(server)
    port = server.start()
    client = DataClient("127.0.0.1", port, AUTH, chunk_size=8,
                        prefer_ring=False)
    assert client._wire == 1
    feed = DataFeed(queues)
    rows = [bytes([i]) * 256 for i in range(20)]
    assert client.feed_partition(rows) == "running"
    assert feed.next_batch(100) == rows
    client.close()
    server.stop()


def test_v1_client_against_current_server():
    """A legacy client (plain length-framed pickle, no hello) must keep
    working against the new server: v1 frames get v1 replies."""
    import pickle
    import socket
    import struct

    from tensorflowonspark_tpu.utils.net import (
        hmac_handshake_client, recv_exact)

    queues = FeedQueues(capacity=1024)
    server = DataServer(queues, AUTH, feed_timeout=5.0)
    port = server.start()
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    assert hmac_handshake_client(sock, AUTH)
    LEN = struct.Struct(">Q")

    def v1_call(msg):
        data = pickle.dumps(msg, protocol=4)
        sock.sendall(LEN.pack(len(data)) + data)
        (n,) = LEN.unpack(recv_exact(sock, 8))
        assert n < (1 << 62), "reply must be a v1 frame for a v1 peer"
        return pickle.loads(recv_exact(sock, n))

    assert v1_call(("feed", "input", [1, 2, 3])) == ("ok", "running")
    reply = v1_call(("end_partition", "input", None))
    assert reply[0] == "ok"
    feed = DataFeed(queues)
    assert feed.next_batch(10) == [1, 2, 3]
    v1_call(("close",))
    sock.close()
    server.stop()


def test_pipelined_window_preserves_order_and_terminating():
    """send_window > 1 pipelines chunk frames; ordering is preserved and a
    mid-stream 'terminating' still stops the feed fast."""
    queues, server, client = start_pair()
    client.send_window = 8
    feed = DataFeed(queues)
    items = list(range(200))
    assert client.feed_partition(items) == "running"
    got = feed.next_batch(500)
    assert got == items  # in-order delivery across the pipelined window
    feed.terminate()
    assert client.feed_partition(range(10_000)) == "terminating"
    client.close()
    server.stop()


def test_pipelined_window_one_is_strict_ping_pong():
    queues, server, client = start_pair()
    client.send_window = 1
    feed = DataFeed(queues)
    assert client.feed_partition(range(50)) == "running"
    assert feed.next_batch(100) == list(range(50))
    client.close()
    server.stop()


def test_feed_timeout_error_surfaces_through_pipeline():
    """An err reply (server-side feed timeout) mid-burst must surface as the
    same RuntimeError the unpipelined path raised."""
    queues, server, client = start_pair(feed_timeout=0.3, capacity=4)
    client.send_window = 4
    with pytest.raises(RuntimeError, match="feed timeout"):
        client.feed_partition(range(100))
    client.close()
    server.stop()


def test_ring_forced_off_via_knob(monkeypatch):
    monkeypatch.setenv("TOS_SHM_RING", "0")
    queues, server, client = start_pair()
    assert not client.using_ring
    feed = DataFeed(queues)
    client.feed_partition(range(10))
    assert feed.next_batch(20) == list(range(10))
    client.close()
    server.stop()


def test_ring_probe_gates_auto_selection(monkeypatch):
    """Unset TOS_SHM_RING: the measured probe decides.  Forcing the cached
    probe verdict both ways must flip the selected transport."""
    from tensorflowonspark_tpu import shm_ring
    from tensorflowonspark_tpu.utils import net as unet

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    monkeypatch.delenv("TOS_SHM_RING", raising=False)
    monkeypatch.setattr(unet, "_ring_probe_cache", {64 * 1024: False})
    queues, server, client = start_pair()
    assert not client.using_ring  # probe said TCP: ring never selected
    client.close()
    server.stop()

    monkeypatch.setattr(unet, "_ring_probe_cache", {64 * 1024: True})
    queues2, server2, client2 = start_pair()
    assert client2.using_ring  # probe said ring
    feed = DataFeed(queues2)
    client2.feed_partition([b"r" * 2048] * 10)
    assert feed.next_batch(20) == [b"r" * 2048] * 10
    client2.close()
    server2.stop()


def test_junk_shm_ring_value_degrades_to_probe(monkeypatch):
    """A TOS_SHM_RING typo must degrade to the documented default (the
    probe), never silently force a transport off (or on)."""
    from tensorflowonspark_tpu import shm_ring
    from tensorflowonspark_tpu.utils import net as unet

    if not shm_ring.available():
        pytest.skip("native shm ring not buildable")
    monkeypatch.setenv("TOS_SHM_RING", "auto")  # junk: not a bool value
    monkeypatch.setattr(unet, "_ring_probe_cache", {64 * 1024: True})
    queues, server, client = start_pair()
    assert client.using_ring  # probe (True) decided, not the junk value
    client.close()
    server.stop()


def test_received_ndarrays_are_writable_on_both_transports(monkeypatch):
    """Pickled ndarrays were always writable; the zero-copy receive path
    must not hand user code read-only arrays — and writability must not
    depend on which transport delivered the batch."""
    import numpy as np

    from tensorflowonspark_tpu import shm_ring

    rows = [np.full((64, 64), i, np.float32) for i in range(6)]  # >= 4KB: packed
    configs = [("0", False)]
    if shm_ring.available():
        configs.append(("1", True))
    # mixed shapes >= 4KB: pack_chunk refuses, so numpy's OWN protocol-5
    # reduce puts these out-of-band — the plain-row receive path must be
    # writable too (it reconstructs from views of the receive blob)
    mixed = [np.full((64, 64), 1.0, np.float32),
             np.full((32, 64), 2.0, np.float32)]
    for knob, expect_ring in configs:
        monkeypatch.setenv("TOS_SHM_RING", knob)
        queues, server, client = start_pair()
        assert client.using_ring == expect_ring
        feed = DataFeed(queues)
        for batch in (rows, mixed):
            client.feed_partition(batch)
            got = feed.next_batch(10)
            for a, b in zip(batch, got):
                assert np.array_equal(a, b)
                assert b.flags.writeable, \
                    f"read-only array over ring={expect_ring}"
                b += 1.0  # in-place mutation (the map_fun normalize idiom)
        client.close()
        server.stop()


def test_structured_dtype_rows_round_trip():
    """Structured dtypes must survive the wire with field names intact —
    they are excluded from columnar packing (dtype.str would collapse them
    to raw void) and travel via numpy's own reduce."""
    import numpy as np

    dt = np.dtype([("a", "<f4"), ("b", "<i4")])
    rows = [np.zeros(2048, dtype=dt) for _ in range(3)]  # >= 4KB each
    for i, r in enumerate(rows):
        r["a"] += i
        r["b"] += 10 * i
    from tensorflowonspark_tpu.data import pack_chunk

    assert pack_chunk(rows) is None  # never packed
    queues, server, client = start_pair()
    feed = DataFeed(queues)
    client.feed_partition(rows)
    got = feed.next_batch(10)
    for a, b in zip(rows, got):
        assert b.dtype == dt
        np.testing.assert_array_equal(a["a"], b["a"])
        np.testing.assert_array_equal(a["b"], b["b"])
    client.close()
    server.stop()
