"""Tier-1 smoke for the committed ingest scaling bench (ISSUE 6 satellite):
the bench machinery must keep producing EXACT record counts on a tiny shard
set in every mode — a pipeline that loses or duplicates records must fail
here, not silently skew BENCH_r08's MB/s."""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench_ingest  # noqa: E402


@pytest.mark.parametrize("mode", ["direct", "direct_threaded", "streaming"])
def test_bench_mode_exact_counts(tmp_path, mode):
    paths, total_bytes = bench_ingest.prepare_shards(
        str(tmp_path), num_shards=4, records_per_shard=24, record_bytes=512)
    # _run_mode raises on any count mismatch — exactness is the assertion
    result = bench_ingest._run_mode(mode, 2, paths, records_per_shard=24)
    assert result["mb_per_s"] > 0
    assert result["num_nodes"] == 2
    assert result["mode"] == mode


def test_bench_quick_table_shape(tmp_path):
    results = bench_ingest.bench(quick=True, fanout=(1,), repeats=1,
                                 data_dir=str(tmp_path / "shards"))
    for mode in ("direct", "direct_threaded", "streaming"):
        assert len(results[mode]) == 1
        assert results[mode][0]["mb_per_s"] > 0
        assert results[f"{mode}_scaling"] == [1.0]
    out = bench_ingest.markdown_table(results)
    assert "direct" in out and "streaming" in out
