"""Tier-1 smoke for the committed ingest scaling bench (ISSUE 6 satellite):
the bench machinery must keep producing EXACT record counts on a tiny shard
set in every mode — a pipeline that loses or duplicates records must fail
here, not silently skew BENCH_r08's MB/s."""

from __future__ import annotations

import os
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench_ingest  # noqa: E402


@pytest.mark.parametrize("mode", ["direct", "direct_threaded", "streaming"])
def test_bench_mode_exact_counts(tmp_path, mode):
    paths, total_bytes = bench_ingest.prepare_shards(
        str(tmp_path), num_shards=4, records_per_shard=24, record_bytes=512)
    # _run_mode raises on any count mismatch — exactness is the assertion
    result = bench_ingest._run_mode(mode, 2, paths, records_per_shard=24)
    assert result["mb_per_s"] > 0
    assert result["num_nodes"] == 2
    assert result["mode"] == mode


@pytest.mark.skipif((os.cpu_count() or 1) < 2,
                    reason="quick-table streaming cell needs a second core: "
                           "on a 1-core box the driver pump and the node "
                           "consumer time-slice each other and the cell "
                           "starves (fails on clean HEAD there too)")
def test_bench_quick_table_shape(tmp_path):
    results = bench_ingest.bench(quick=True, fanout=(1,), repeats=1,
                                 data_dir=str(tmp_path / "shards"))
    for mode in ("direct", "direct_threaded", "streaming"):
        assert len(results[mode]) == 1
        assert results[mode][0]["mb_per_s"] > 0
        assert results[f"{mode}_scaling"] == [1.0]
    out = bench_ingest.markdown_table(results)
    assert "direct" in out and "streaming" in out


def test_bench_zerocopy_and_columnar_compare_quick(tmp_path):
    """Round-12 compare machinery: both legs run, exact counts hold (the
    runners raise on any mismatch), and the speedup fields are present."""
    zc = bench_ingest.bench_zerocopy(quick=True, data_dir=str(tmp_path / "zc"))
    assert zc["zerocopy"]["mb_per_s"] > 0 and zc["bytescopy"]["mb_per_s"] > 0
    assert "speedup_pct" in zc
    col = bench_ingest.bench_columnar(quick=True,
                                      data_dir=str(tmp_path / "col"))
    assert col["columnar"]["mb_per_s"] > 0 and col["rowdecode"]["mb_per_s"] > 0
    assert col["speedup_x"] > 0


def test_bench_disagg_scenario_quick(tmp_path):
    """Round-15 machinery: the disaggregated tier and node-local legs both
    deliver exact trainer-side counts (the runner raises on mismatch), the
    cache compare runs both epochs, and the markdown renders."""
    res = bench_ingest.bench_disagg(quick=True,
                                    data_dir=str(tmp_path / "svc"))
    assert res["node_local"]["rows_per_s"] > 0
    assert res["disagg_w2"]["rows_per_s"] > 0
    assert res["disagg_w2"]["num_workers"] == 2
    cache = res["cache_epochs"]
    assert cache["cold"]["rows"] == cache["warm"]["rows"] == res["records"]
    assert cache["warm_over_cold"] > 1.0  # the repeated epoch must win
    assert cache["cache"]["entries"] > 0
    out = bench_ingest.markdown_r15(res)
    assert "disaggregated ingest tier" in out


def test_bench_bigshard_scenario_quick(tmp_path):
    """Single-large-shard scenario: the shard actually splits into span
    items and every cell (split N=1/N=2, whole-shard N=2) keeps exact
    counts."""
    big = bench_ingest.bench_bigshard(quick=True,
                                      data_dir=str(tmp_path / "big"))
    assert big["num_items"] > 1              # the shard went out as spans
    assert big["n2_whole_shard"]["num_items"] == 1
    assert big["n1"]["mb_per_s"] > 0 and big["n2"]["mb_per_s"] > 0
    zc = {"zerocopy": big["n1"], "bytescopy": big["n1"], "speedup_pct": 0.0}
    col = {"columnar": big["n1"], "rowdecode": big["n1"], "speedup_x": 1.0}
    out = bench_ingest.markdown_round12(zc, col, big)
    assert "single-large-shard" in out
