"""dfutil bridge tests: rows ⇄ TFRecord shards with schema (reference
``test/test_dfutil.py`` round-trip incl. binary-features option)."""

import numpy as np
import pytest

from tensorflowonspark_tpu import dfutil
from tensorflowonspark_tpu.data import PartitionedDataset
from tensorflowonspark_tpu.utils.paths import register_fs_root


def rows():
    return [
        {"label": 1, "feat": [0.5, 1.5], "name": "alice"},
        {"label": 0, "feat": [2.5, 3.5], "name": "bob"},
        {"label": 1, "feat": [4.0, 5.0], "name": "carol"},
    ]


def test_infer_schema():
    s = dfutil.infer_schema(rows()[0])
    assert [c.name for c in s.columns] == ["feat", "label", "name"]
    assert s["label"].dtype == "int64" and s["label"].scalar
    assert s["feat"].dtype == "float" and not s["feat"].scalar
    assert s["name"].dtype == "bytes" and s["name"].scalar


def test_roundtrip(tmp_path):
    ds = PartitionedDataset.from_iterable(rows(), 2)
    schema = dfutil.save_as_tfrecords(ds, str(tmp_path / "out"))
    loaded, schema2 = dfutil.load_tfrecords(str(tmp_path / "out"))
    assert schema2 is not None and schema2.to_json() == schema.to_json()
    assert loaded.num_partitions == 2
    got = sorted(loaded, key=lambda r: r["name"])
    want = sorted(rows(), key=lambda r: r["name"])
    for g, w in zip(got, want):
        assert g["label"] == w["label"]
        assert g["name"] == w["name"]
        assert g["feat"] == pytest.approx(w["feat"])


def test_binary_features(tmp_path):
    data = [{"img": b"\x00\x01\xff", "id": 7}]
    ds = PartitionedDataset.from_iterable(data, 1)
    dfutil.save_as_tfrecords(ds, str(tmp_path / "b"))
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "b"), binary_features={"img"})
    (row,) = list(loaded)
    assert row["img"] == b"\x00\x01\xff"  # bytes preserved, scalar squeezed
    assert row["id"] == 7


def test_numpy_values(tmp_path):
    data = [{"x": np.arange(4, dtype=np.float32), "y": np.int64(2)}]
    ds = PartitionedDataset.from_iterable(data, 1)
    dfutil.save_as_tfrecords(ds, str(tmp_path / "np"))
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "np"))
    (row,) = list(loaded)
    assert row["x"] == pytest.approx([0.0, 1.0, 2.0, 3.0])
    assert row["y"] == 2


def test_scheme_mapped_paths(tmp_path):
    """hdfs:// URIs must work when backed by a registered local root
    (HopsFS parity, SURVEY.md §7.3-4)."""
    register_fs_root("hdfs", str(tmp_path))
    ds = PartitionedDataset.from_iterable(rows(), 1)
    dfutil.save_as_tfrecords(ds, "hdfs://namenode/user/test/out")
    loaded, _ = dfutil.load_tfrecords("hdfs://namenode/user/test/out")
    assert len(list(loaded)) == 3


def test_empty_dataset_raises(tmp_path):
    ds = PartitionedDataset.from_iterable([], 1)
    with pytest.raises(ValueError, match="empty"):
        dfutil.save_as_tfrecords(ds, str(tmp_path / "e"))


def test_save_load_gzip_shards(tmp_path):
    rows = [{"x": [float(i), i + 0.5], "label": i % 3} for i in range(12)]
    data = PartitionedDataset.from_iterable(rows, 3)
    dfutil.save_as_tfrecords(data, str(tmp_path / "gz"), compression="gzip")
    shards = dfutil.shard_files(str(tmp_path / "gz"))
    assert len(shards) == 3 and all(s.endswith(".gz") for s in shards)
    schema = dfutil.read_schema(str(tmp_path / "gz"))
    back = [row for s in shards for row in dfutil.read_shard(s, schema)]
    assert len(back) == 12
    assert back[0]["x"] == [0.0, 0.5] and back[11]["label"] == 2


def test_resave_with_different_compression_clobbers(tmp_path):
    rows = [{"x": [1.0], "label": 1} for _ in range(4)]
    data = PartitionedDataset.from_iterable(rows, 2)
    dfutil.save_as_tfrecords(data, str(tmp_path / "d"))
    dfutil.save_as_tfrecords(data, str(tmp_path / "d"), compression="gzip")
    shards = dfutil.shard_files(str(tmp_path / "d"))
    assert len(shards) == 2 and all(s.endswith(".gz") for s in shards)
    schema = dfutil.read_schema(str(tmp_path / "d"))
    back = [r for s in shards for r in dfutil.read_shard(s, schema)]
    assert len(back) == 4  # no duplicated generations


def test_failed_resave_preserves_previous_generation(tmp_path):
    """A crash mid-save must not destroy the previous dataset generation
    (advisor r4 medium): new shards are written under temp names and only
    renamed into place after every partition committed."""
    import glob
    import os

    rows = [{"x": [float(i)], "label": i} for i in range(4)]
    data = PartitionedDataset.from_iterable(rows, 2)
    dfutil.save_as_tfrecords(data, str(tmp_path / "d"))
    before = sorted(os.path.basename(s) for s in dfutil.shard_files(str(tmp_path / "d")))

    def poison():
        yield {"x": [9.0], "label": 9}
        raise IOError("disk full mid-save")

    bad = PartitionedDataset([lambda: iter([{"x": [8.0], "label": 8}]), poison])
    with pytest.raises(IOError, match="disk full"):
        dfutil.save_as_tfrecords(bad, str(tmp_path / "d"))
    # old generation fully intact, readable, and no temp litter
    shards = dfutil.shard_files(str(tmp_path / "d"))
    assert sorted(os.path.basename(s) for s in shards) == before
    back = [r for s in shards for r in dfutil.read_shard(s, dfutil.read_schema(str(tmp_path / "d")))]
    assert sorted(r["label"] for r in back) == [0, 1, 2, 3]
    assert glob.glob(str(tmp_path / "d" / ".tmp-part-*")) == []


class TestShardColumns:
    def _write(self, tmp_path, rows, partitions=1):
        data = PartitionedDataset.from_iterable(rows, partitions)
        schema = dfutil.save_as_tfrecords(data, str(tmp_path / "cols"))
        return dfutil.shard_files(str(tmp_path / "cols")), schema

    def test_columns_match_row_decode(self, tmp_path):
        import numpy as np

        rows = [{"x": [float(i), i + 0.25], "label": i % 5,
                 "name": f"row-{i}", "blob": bytes([i, i + 1])}
                for i in range(17)]
        shards, schema = self._write(tmp_path, rows)
        cols, counts = dfutil.read_shard_columns(shards[0], schema,
                                                 binary_features={"blob"})
        assert cols["x"].dtype == np.float32 and cols["x"].shape == (34,)
        np.testing.assert_allclose(cols["x"].reshape(17, 2),
                                   [r["x"] for r in rows])
        assert cols["label"].dtype == np.int64
        np.testing.assert_array_equal(cols["label"], [r["label"] for r in rows])
        assert cols["name"] == [r["name"] for r in rows]          # str decode
        assert cols["blob"] == [r["blob"] for r in rows]          # raw bytes
        for name in ("x", "label", "name", "blob"):
            want = 2 if name == "x" else 1
            np.testing.assert_array_equal(counts[name],
                                          [want] * len(rows))

    def test_ragged_and_missing_features(self, tmp_path):
        import numpy as np

        from tensorflowonspark_tpu import example as ex
        from tensorflowonspark_tpu import tfrecord

        # hand-build records: ragged int lists, one record missing the column
        recs = [ex.encode_example({"v": [1, 2, 3], "tag": "a"}),
                ex.encode_example({"tag": "b"}),
                ex.encode_example({"v": [-7], "tag": "c"})]
        p = str(tmp_path / "ragged.tfrecord")
        tfrecord.write_records(p, recs)
        schema = dfutil.Schema([dfutil.ColumnSpec("v", "int64", False),
                                dfutil.ColumnSpec("tag", "bytes", True)])
        cols, counts = dfutil.read_shard_columns(p, schema)
        np.testing.assert_array_equal(cols["v"], [1, 2, 3, -7])
        np.testing.assert_array_equal(counts["v"], [3, 0, 1])
        assert cols["tag"] == ["a", "b", "c"]

    def test_unpacked_primitive_encodings(self, tmp_path):
        """TF writes packed primitives; other writers may emit repeated
        (unpacked) floats/ints — both must decode identically."""
        import struct

        import numpy as np

        from tensorflowonspark_tpu import tfrecord

        # hand-roll a Feature with UNPACKED floats: float_list(field 2) whose
        # body repeats field 1 wire-type 5 entries
        def unpacked_float_feature(vals):
            body = b"".join(bytes([0x0D]) + struct.pack("<f", v) for v in vals)
            feat = bytes([0x12, len(body)]) + body          # float_list
            return feat

        def unpacked_int_feature(vals):
            body = b""
            for v in vals:
                body += bytes([0x08, v])                    # small positives
            return bytes([0x1A, len(body)]) + body          # int64_list

        def entry(name, feat):
            e = bytes([0x0A, len(name)]) + name + bytes([0x12, len(feat)]) + feat
            return bytes([0x0A, len(e)]) + e

        fmap = entry(b"f", unpacked_float_feature([1.5, -2.0])) \
            + entry(b"i", unpacked_int_feature([3, 9]))
        rec = bytes([0x0A, len(fmap)]) + fmap
        p = str(tmp_path / "unpacked.tfrecord")
        tfrecord.write_records(p, [rec])
        schema = dfutil.Schema([dfutil.ColumnSpec("f", "float", False),
                                dfutil.ColumnSpec("i", "int64", False)])
        cols, counts = dfutil.read_shard_columns(p, schema)
        np.testing.assert_allclose(cols["f"], [1.5, -2.0])
        np.testing.assert_array_equal(cols["i"], [3, 9])

    def test_kind_mismatch_raises(self, tmp_path):
        rows = [{"x": 1.5}]
        shards, _ = self._write(tmp_path, rows)
        bad = dfutil.Schema([dfutil.ColumnSpec("x", "int64", True)])
        with pytest.raises((TypeError, ValueError)):
            dfutil.read_shard_columns(shards[0], bad)

    def _force_fallback(self, monkeypatch):
        """Make `from tensorflowonspark_tpu import example_native` raise: a
        None sys.modules entry raises ImportError at import time (patching
        builtins.__import__ would NOT work — the already-imported submodule
        resolves via the package attribute, bypassing the hook)."""
        import sys

        import tensorflowonspark_tpu as pkg

        monkeypatch.setitem(sys.modules,
                            "tensorflowonspark_tpu.example_native", None)
        monkeypatch.delattr(pkg, "example_native", raising=False)

    def test_python_fallback_matches_native(self, tmp_path, monkeypatch):
        import numpy as np

        rows = [{"x": [float(i)], "label": i, "s": f"v{i}"} for i in range(9)]
        shards, schema = self._write(tmp_path, rows)
        native_cols, native_counts = dfutil.read_shard_columns(shards[0], schema)

        self._force_fallback(monkeypatch)
        with pytest.raises(ImportError):
            from tensorflowonspark_tpu import example_native  # noqa: F401
        py_cols, py_counts = dfutil.read_shard_columns(shards[0], schema)
        for k in native_cols:
            if isinstance(native_cols[k], list):
                assert native_cols[k] == py_cols[k]
            else:
                np.testing.assert_array_equal(native_cols[k], py_cols[k])
            np.testing.assert_array_equal(native_counts[k], py_counts[k])

    def test_python_fallback_kind_mismatch_raises(self, tmp_path, monkeypatch):
        rows = [{"x": 1.5}]
        shards, _ = self._write(tmp_path, rows)
        bad = dfutil.Schema([dfutil.ColumnSpec("x", "int64", True)])
        self._force_fallback(monkeypatch)
        with pytest.raises(TypeError, match="not of dtype"):
            dfutil.read_shard_columns(shards[0], bad)

    def test_duplicate_map_keys_last_wins_both_paths(self, tmp_path, monkeypatch):
        """Proto map semantics: the LAST entry for a repeated key wins — in
        the native parser AND the Python fallback."""
        import numpy as np

        from tensorflowonspark_tpu import example as ex
        from tensorflowonspark_tpu import tfrecord

        def entry(name, feat):
            e = bytes([0x0A, len(name)]) + name + bytes([0x12, len(feat)]) + feat
            return bytes([0x0A, len(e)]) + e

        def int_feature(v):
            body = bytes([0x0A, 0x01, v])          # packed int64_list [v]
            return bytes([0x1A, len(body)]) + body

        fmap = entry(b"k", int_feature(7)) + entry(b"k", int_feature(9))
        rec = bytes([0x0A, len(fmap)]) + fmap
        assert ex.decode_example(rec) == {"k": [9]}  # python reference
        p = str(tmp_path / "dup.tfrecord")
        tfrecord.write_records(p, [rec])
        schema = dfutil.Schema([dfutil.ColumnSpec("k", "int64", True)])
        cols, counts = dfutil.read_shard_columns(p, schema)
        np.testing.assert_array_equal(cols["k"], [9])
        self._force_fallback(monkeypatch)
        cols2, _ = dfutil.read_shard_columns(p, schema)
        np.testing.assert_array_equal(cols2["k"], [9])

    def test_empty_feature_absent_both_paths(self, tmp_path, monkeypatch):
        """A present-but-VALUELESS feature counts as absent in both decode
        paths — even when its (empty) wire kind mismatches the schema: you
        cannot type an empty list, so no kind error is raised."""
        import numpy as np

        from tensorflowonspark_tpu import tfrecord

        def entry(name, feat):
            e = bytes([0x0A, len(name)]) + name + bytes([0x12, len(feat)]) + feat
            return bytes([0x0A, len(e)]) + e

        empty_float_list = bytes([0x12, 0x00])      # float_list {}
        fmap = entry(b"x", empty_float_list)
        rec = bytes([0x0A, len(fmap)]) + fmap
        p = str(tmp_path / "empty.tfrecord")
        tfrecord.write_records(p, [rec])
        schema = dfutil.Schema([dfutil.ColumnSpec("x", "int64", True)])
        cols, counts = dfutil.read_shard_columns(p, schema)  # no TypeError
        assert len(cols["x"]) == 0
        np.testing.assert_array_equal(counts["x"], [0])
        self._force_fallback(monkeypatch)
        cols2, counts2 = dfutil.read_shard_columns(p, schema)
        assert len(cols2["x"]) == 0
        np.testing.assert_array_equal(counts2["x"], [0])


def test_rows_to_columns_round_trip():
    """The columnar half of the zero-copy wire format: row-dicts reshape to
    per-key columns and back without loss; heterogeneous chunks refuse."""
    import numpy as np

    from tensorflowonspark_tpu import dfutil

    rows = [{"x": np.ones(3, np.float32) * i, "label": i} for i in range(5)]
    keys, cols = dfutil.rows_to_columns(rows)
    assert keys == ("x", "label")
    assert cols[1] == [0, 1, 2, 3, 4]
    back = dfutil.columns_to_rows(keys, cols)
    assert all(np.array_equal(a["x"], b["x"]) and a["label"] == b["label"]
               for a, b in zip(rows, back))
    # key mismatch / non-dict rows refuse (the wire keeps them row-major)
    assert dfutil.rows_to_columns([{"a": 1}, {"b": 2}]) is None
    assert dfutil.rows_to_columns([1, 2]) is None
    assert dfutil.rows_to_columns([]) is None


def test_decode_span_columns_matches_read_shard_columns(tmp_path):
    """The buffer-level columnar decoder is read_shard_columns on a span
    subset: full-span decode matches, and a window decodes just its
    records (the ingest reader's per-chunk call shape)."""
    from tensorflowonspark_tpu import tfrecord

    ds = PartitionedDataset.from_iterable(rows() * 3, 1)
    schema = dfutil.save_as_tfrecords(ds, str(tmp_path / "out"))
    shard = dfutil.shard_files(str(tmp_path / "out"))[0]
    whole_cols, whole_counts = dfutil.read_shard_columns(shard, schema)
    buf, spans = tfrecord.read_record_spans(shard)
    cols, counts = dfutil.decode_span_columns(buf, spans, schema)
    np.testing.assert_array_equal(cols["feat"], whole_cols["feat"])
    assert cols["name"] == whole_cols["name"]
    window_cols, window_counts = dfutil.decode_span_columns(
        buf, spans[2:5], schema)
    np.testing.assert_array_equal(window_cols["feat"],
                                  whole_cols["feat"][4:10])
    assert len(window_counts["label"]) == 3


def test_column_chunk_slice_rows_and_pickle(tmp_path):
    """ColumnChunk: zero-copy batch slices whose representation follows
    the SCHEMA declaration (ragged columns always (values, counts) pairs
    — even for a chunk whose counts happen to be uniform), row expansion
    matching from_example shapes, and a protocol-5 pickle round trip
    shipping columns out-of-band."""
    import pickle

    r = [{"feat": [0.5 * i, 1.0 * i], "label": i, "name": f"n{i}"}
         for i in range(6)]
    r[3]["feat"] = [9.0]  # make 'feat' genuinely ragged
    schema = dfutil.infer_schema(r[0])
    schema["feat"].width = None  # declare the raggedness
    cols, counts = dfutil.records_to_columns(
        [dfutil.to_example(x, schema) for x in r], schema)
    chunk = dfutil.ColumnChunk.from_schema(cols, counts, schema)
    assert len(chunk) == 6
    s = chunk.slice(1, 3)
    assert s["label"].tolist() == [1, 2]          # int64 scalar column
    assert s["name"] == ["n1", "n2"]              # str scalar column
    vals, cnts = s["feat"]                        # ragged -> values+counts
    assert vals.tolist() == [0.5, 1.0, 1.0, 2.0] and cnts.tolist() == [2, 2]
    # representation STABILITY: a window whose counts happen to be
    # uniform must come back in the same ragged form, not an ndarray
    vals01, cnts01 = chunk.slice(0, 3)["feat"]
    assert cnts01.tolist() == [2, 2, 2]
    back = chunk.rows()
    assert back[0]["label"] == 0 and back[0]["name"] == "n0"
    assert back[3]["feat"] == [9.0]
    bufs = []
    blob = pickle.dumps(chunk, protocol=5, buffer_callback=bufs.append)
    assert bufs  # numeric columns travelled out-of-band
    again = pickle.loads(blob, buffers=[b.raw() for b in bufs])
    assert again.rows() == back


def test_column_chunk_declared_width_violation_fails_loudly(tmp_path):
    """Data that violates its column's declared fixed width must raise a
    ValueError naming the column — a silent per-chunk representation
    switch would mis-frame batches mid-feed."""
    r = [{"feat": [0.5, 1.0], "label": i} for i in range(4)]
    schema = dfutil.infer_schema(r[0])  # feat declares width=2
    assert schema["feat"].width == 2
    r[2]["feat"] = [9.0]  # on-disk record breaks the declaration
    cols, counts = dfutil.records_to_columns(
        [dfutil.to_example(x, schema) for x in r], schema)
    chunk = dfutil.ColumnChunk.from_schema(cols, counts, schema)
    with pytest.raises(ValueError, match="feat.*width=None"):
        chunk.slice(0, 2)


def test_column_chunk_fixed_width_slice(tmp_path):
    ds = PartitionedDataset.from_iterable(rows(), 1)
    schema = dfutil.save_as_tfrecords(ds, str(tmp_path / "out"))
    shard = dfutil.shard_files(str(tmp_path / "out"))[0]
    cols, counts = dfutil.read_shard_columns(shard, schema)
    chunk = dfutil.ColumnChunk.from_schema(cols, counts, schema)
    s = chunk.slice(0, 2)
    assert s["feat"].shape == (2, 2)  # fixed-width k=2 reshapes [n, k]
    # the slice is a VIEW of the chunk's contiguous buffer, not a copy
    assert s["feat"].base is not None


def test_save_relaxes_inferred_width_on_ragged_data(tmp_path):
    """An auto-inferred fixed width must demote to ragged (None) when any
    written row disagrees — otherwise the stored schema promises a
    columnar layout the shards break mid-train.  A caller-provided
    schema keeps its own declarations."""
    rows_ragged = [{"x": [1.0, 2.0], "y": 1}, {"x": [3.0], "y": 2}]
    ds = PartitionedDataset.from_iterable(rows_ragged, 1)
    schema = dfutil.save_as_tfrecords(ds, str(tmp_path / "out"))
    assert schema["x"].width is None          # relaxed while writing
    stored = dfutil.read_schema(str(tmp_path / "out"))
    assert stored["x"].width is None
    # columnar read of the ragged dataset works (pair representation)
    shard = dfutil.shard_files(str(tmp_path / "out"))[0]
    cols, counts = dfutil.read_shard_columns(shard, stored)
    chunk = dfutil.ColumnChunk.from_schema(cols, counts, stored)
    vals, cnts = chunk.slice(0, 2)["x"]
    assert cnts.tolist() == [2, 1] and vals.tolist() == [1.0, 2.0, 3.0]
    # uniform data keeps its inferred width
    uniform = [{"x": [1.0, 2.0]}, {"x": [3.0, 4.0]}]
    s2 = dfutil.save_as_tfrecords(
        PartitionedDataset.from_iterable(uniform, 1), str(tmp_path / "u"))
    assert s2["x"].width == 2
