"""dfutil bridge tests: rows ⇄ TFRecord shards with schema (reference
``test/test_dfutil.py`` round-trip incl. binary-features option)."""

import numpy as np
import pytest

from tensorflowonspark_tpu import dfutil
from tensorflowonspark_tpu.data import PartitionedDataset
from tensorflowonspark_tpu.utils.paths import register_fs_root


def rows():
    return [
        {"label": 1, "feat": [0.5, 1.5], "name": "alice"},
        {"label": 0, "feat": [2.5, 3.5], "name": "bob"},
        {"label": 1, "feat": [4.0, 5.0], "name": "carol"},
    ]


def test_infer_schema():
    s = dfutil.infer_schema(rows()[0])
    assert [c.name for c in s.columns] == ["feat", "label", "name"]
    assert s["label"].dtype == "int64" and s["label"].scalar
    assert s["feat"].dtype == "float" and not s["feat"].scalar
    assert s["name"].dtype == "bytes" and s["name"].scalar


def test_roundtrip(tmp_path):
    ds = PartitionedDataset.from_iterable(rows(), 2)
    schema = dfutil.save_as_tfrecords(ds, str(tmp_path / "out"))
    loaded, schema2 = dfutil.load_tfrecords(str(tmp_path / "out"))
    assert schema2 is not None and schema2.to_json() == schema.to_json()
    assert loaded.num_partitions == 2
    got = sorted(loaded, key=lambda r: r["name"])
    want = sorted(rows(), key=lambda r: r["name"])
    for g, w in zip(got, want):
        assert g["label"] == w["label"]
        assert g["name"] == w["name"]
        assert g["feat"] == pytest.approx(w["feat"])


def test_binary_features(tmp_path):
    data = [{"img": b"\x00\x01\xff", "id": 7}]
    ds = PartitionedDataset.from_iterable(data, 1)
    dfutil.save_as_tfrecords(ds, str(tmp_path / "b"))
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "b"), binary_features={"img"})
    (row,) = list(loaded)
    assert row["img"] == b"\x00\x01\xff"  # bytes preserved, scalar squeezed
    assert row["id"] == 7


def test_numpy_values(tmp_path):
    data = [{"x": np.arange(4, dtype=np.float32), "y": np.int64(2)}]
    ds = PartitionedDataset.from_iterable(data, 1)
    dfutil.save_as_tfrecords(ds, str(tmp_path / "np"))
    loaded, _ = dfutil.load_tfrecords(str(tmp_path / "np"))
    (row,) = list(loaded)
    assert row["x"] == pytest.approx([0.0, 1.0, 2.0, 3.0])
    assert row["y"] == 2


def test_scheme_mapped_paths(tmp_path):
    """hdfs:// URIs must work when backed by a registered local root
    (HopsFS parity, SURVEY.md §7.3-4)."""
    register_fs_root("hdfs", str(tmp_path))
    ds = PartitionedDataset.from_iterable(rows(), 1)
    dfutil.save_as_tfrecords(ds, "hdfs://namenode/user/test/out")
    loaded, _ = dfutil.load_tfrecords("hdfs://namenode/user/test/out")
    assert len(list(loaded)) == 3


def test_empty_dataset_raises(tmp_path):
    ds = PartitionedDataset.from_iterable([], 1)
    with pytest.raises(ValueError, match="empty"):
        dfutil.save_as_tfrecords(ds, str(tmp_path / "e"))


def test_save_load_gzip_shards(tmp_path):
    rows = [{"x": [float(i), i + 0.5], "label": i % 3} for i in range(12)]
    data = PartitionedDataset.from_iterable(rows, 3)
    dfutil.save_as_tfrecords(data, str(tmp_path / "gz"), compression="gzip")
    shards = dfutil.shard_files(str(tmp_path / "gz"))
    assert len(shards) == 3 and all(s.endswith(".gz") for s in shards)
    schema = dfutil.read_schema(str(tmp_path / "gz"))
    back = [row for s in shards for row in dfutil.read_shard(s, schema)]
    assert len(back) == 12
    assert back[0]["x"] == [0.0, 0.5] and back[11]["label"] == 2


def test_resave_with_different_compression_clobbers(tmp_path):
    rows = [{"x": [1.0], "label": 1} for _ in range(4)]
    data = PartitionedDataset.from_iterable(rows, 2)
    dfutil.save_as_tfrecords(data, str(tmp_path / "d"))
    dfutil.save_as_tfrecords(data, str(tmp_path / "d"), compression="gzip")
    shards = dfutil.shard_files(str(tmp_path / "d"))
    assert len(shards) == 2 and all(s.endswith(".gz") for s in shards)
    schema = dfutil.read_schema(str(tmp_path / "d"))
    back = [r for s in shards for r in dfutil.read_shard(s, schema)]
    assert len(back) == 4  # no duplicated generations
